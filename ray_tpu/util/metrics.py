"""Application metrics: Counter / Gauge / Histogram.

Capability parity: reference python/ray/util/metrics.py (Counter :164, Histogram
:217, Gauge :295) + the dashboard-agent scrape path (C++ DEFINE_stats ->
OpenCensus -> Prometheus; SURVEY.md §5). Here each process keeps a local registry;
worker processes push deltas to the node coordinator over their control pipe every
REPORT_INTERVAL_S (the reference's agent scrape, inverted), and the aggregated view
is served by the state API / dashboard exporter (util/state.py, dashboard.py).
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

def _report_interval() -> float:
    """Read at use: env changes apply live (config.py contract)."""
    try:
        from ray_tpu.config import CONFIG

        return CONFIG.metrics_report_interval_s
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return 2.0) by design
    except Exception:
        return 2.0

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
]

DROPPED_SERIES_METRIC = "metrics_dropped_series_total"


def _max_series() -> int:
    """Bounded-cardinality cap: max distinct label sets per metric (read at
    use so env changes apply live). <= 0 disables the guard."""
    try:
        from ray_tpu.config import CONFIG

        return CONFIG.control_max_series
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return 1024) by design
    except Exception:
        return 1024


_dropped_lock = threading.Lock()
_dropped_series: Dict[str, int] = defaultdict(int)


def _record_dropped(metric_name: str, n: int = 1) -> None:
    with _dropped_lock:
        _dropped_series[metric_name] += n


def dropped_series_snapshot() -> Optional[dict]:
    """Synthetic counter export for the cardinality guard. Kept out of the
    Metric registry on purpose: the guard must never be subject to itself,
    and its own cardinality is bounded by the number of metric NAMES."""
    with _dropped_lock:
        if not _dropped_series:
            return None
        return {
            "name": DROPPED_SERIES_METRIC, "type": "counter",
            "description": "label sets dropped by the bounded-cardinality "
                           "guard (RAY_TPU_CONTROL_MAX_SERIES), by metric",
            "values": {(("metric", k),): float(v)
                       for k, v in _dropped_series.items()},
        }


class _Registry:
    """Per-process metric registry; worker side pushes deltas to the coordinator."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, "Metric"] = {}
        self._push_thread: Optional[threading.Thread] = None

    def register(self, m: "Metric") -> None:
        with self._lock:
            existing = self._metrics.get(m.name)
            if existing is not None and existing.TYPE != m.TYPE:
                raise ValueError(f"metric {m.name!r} already registered as {existing.TYPE}")
            self._metrics[m.name] = m
        self._ensure_push_thread()

    def snapshot(self) -> List[dict]:
        with self._lock:
            out = [m._export() for m in self._metrics.values()]
        dropped = dropped_series_snapshot()
        if dropped is not None:
            out.append(dropped)
        return out

    def _ensure_push_thread(self) -> None:
        """Workers and remote client drivers push snapshots to the head; the
        process HOLDING the cluster (in-process driver/head) must not — its
        registry is read directly by the state API, and a self-push would
        land a periodically-frozen copy in metrics_by_worker["driver"] that
        the merge then counts AGAIN (doubling driver counters) and, for
        gauges, writes over the live value with one up to a report interval
        stale (same keying rule as telemetry._ensure_flush_thread)."""
        if self._push_thread is not None:
            return
        from ray_tpu.core import global_state

        if global_state.try_cluster() is not None:
            return
        w = global_state.try_worker()
        if w is None or not hasattr(w, "push_metrics"):
            return

        def loop():
            while True:
                time.sleep(_report_interval())
                try:
                    snap = self.snapshot()
                    if snap:
                        w.push_metrics(snap)
                # graftlint: allow[swallowed-exception] degrades to the coded fallback (return) by design
                except Exception:
                    return  # pipe closed: worker exiting

        self._push_thread = threading.Thread(target=loop, daemon=True, name="metrics-push")
        self._push_thread.start()


_registry = _Registry()


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    TYPE = "base"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name is required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        out = dict(self._default_tags)
        if tags:
            out.update(tags)
        return out

    def _admit(self, key: Tuple, existing: Dict) -> bool:
        """Cardinality guard, called under self._lock: a key already present
        always updates; a NEW label set past the cap is dropped (and counted)
        so an exploding tag value can never grow memory unboundedly."""
        if key in existing:
            return True
        cap = _max_series()
        if cap <= 0 or len(existing) < cap:
            return True
        _record_dropped(self.name)
        return False

    def _export(self) -> dict:
        raise NotImplementedError


class Counter(Metric):
    """Monotonic counter (reference metrics.py:164)."""

    TYPE = "counter"

    def __init__(self, name, description="", tag_keys=None):
        self._values: Dict[Tuple, float] = defaultdict(float)
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() value must be >= 0")
        key = _tag_key(self._merged(tags))
        with self._lock:
            if self._admit(key, self._values):
                self._values[key] += value

    def _export(self) -> dict:
        with self._lock:
            return {"name": self.name, "type": self.TYPE, "description": self.description,
                    "values": {k: v for k, v in self._values.items()}}


class Gauge(Metric):
    """Last-value gauge (reference metrics.py:295)."""

    TYPE = "gauge"

    def __init__(self, name, description="", tag_keys=None):
        self._values: Dict[Tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tag_key(self._merged(tags))
        with self._lock:
            if self._admit(key, self._values):
                self._values[key] = float(value)

    def _export(self) -> dict:
        with self._lock:
            return {"name": self.name, "type": self.TYPE, "description": self.description,
                    "values": dict(self._values)}


class Histogram(Metric):
    """Bucketed histogram (reference metrics.py:217)."""

    TYPE = "histogram"

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        self._buckets: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = defaultdict(float)
        self._counts: Dict[Tuple, int] = defaultdict(int)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tag_key(self._merged(tags))
        with self._lock:
            if not self._admit(key, self._buckets):
                return
            buckets = self._buckets.setdefault(key, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            buckets[i] += 1
            self._sums[key] += value
            self._counts[key] += 1

    def _export(self) -> dict:
        with self._lock:
            return {
                "name": self.name, "type": self.TYPE, "description": self.description,
                "boundaries": self.boundaries,
                "values": {k: {"buckets": list(v), "sum": self._sums[k],
                               "count": self._counts[k]}
                           for k, v in self._buckets.items()},
            }


# ------------------------------------------------------------------- aggregation

def _rebin(counts: List[int], src_bounds: List[float],
           dst_bounds: List[float]) -> List[int]:
    """Map bucket counts from one boundary set onto another: each source
    bucket's count lands in the destination bucket containing the source
    bucket's upper edge (the overflow bucket stays overflow). Lossy only in
    the sense any re-binning is — counts and sums are preserved exactly."""
    out = [0] * (len(dst_bounds) + 1)
    for i, cnt in enumerate(counts):
        if not cnt:
            continue
        if i < len(src_bounds):
            edge = src_bounds[i]
            j = 0
            while j < len(dst_bounds) and edge > dst_bounds[j]:
                j += 1
        else:
            j = len(dst_bounds)
        out[j] += cnt
    return out


def merge_snapshots(snaps: List[List[dict]]) -> Dict[str, dict]:
    """Merge per-process snapshots (driver registry + worker pushes + node
    deltas) by metric name. Histograms carry their own per-metric
    `boundaries` through the worker->coordinator push; when two processes
    registered the same histogram with DIFFERENT boundaries, the incoming
    buckets are re-binned onto the first-seen set instead of being
    zip-truncated into corruption. The merged view applies the same
    bounded-cardinality guard as live registries (a fleet of pre-guard
    workers must not explode head memory); merge-time drops are folded into
    the dropped-series counter so degradation is visible."""
    cap = _max_series()
    merge_dropped: Dict[str, int] = defaultdict(int)

    def admit(name: str, key: Tuple, existing: Dict) -> bool:
        if key in existing or name == DROPPED_SERIES_METRIC:
            return True
        if cap <= 0 or len(existing) < cap:
            return True
        merge_dropped[name] += 1
        return False

    out: Dict[str, dict] = {}
    for snap in snaps:
        for m in snap:
            cur = out.get(m["name"])
            if cur is None:
                import copy

                cur = copy.deepcopy(m)
                if cap > 0 and m["name"] != DROPPED_SERIES_METRIC \
                        and len(cur["values"]) > cap:
                    keep = list(cur["values"].items())[:cap]
                    merge_dropped[m["name"]] += len(cur["values"]) - cap
                    cur["values"] = dict(keep)
                out[m["name"]] = cur
                continue
            if m["type"] == "counter":
                for k, v in m["values"].items():
                    if admit(m["name"], k, cur["values"]):
                        cur["values"][k] = cur["values"].get(k, 0.0) + v
            elif m["type"] == "gauge":
                for k, v in m["values"].items():
                    if admit(m["name"], k, cur["values"]):
                        cur["values"][k] = v
            elif m["type"] == "histogram":
                src_bounds = list(m.get("boundaries", DEFAULT_HISTOGRAM_BOUNDARIES))
                dst_bounds = list(cur.get("boundaries", DEFAULT_HISTOGRAM_BOUNDARIES))
                same = src_bounds == dst_bounds
                for k, v in m["values"].items():
                    buckets = (list(v["buckets"]) if same
                               else _rebin(v["buckets"], src_bounds, dst_bounds))
                    tgt = cur["values"].get(k)
                    if tgt is None:
                        if admit(m["name"], k, cur["values"]):
                            cur["values"][k] = {"buckets": buckets,
                                                "sum": v["sum"], "count": v["count"]}
                    else:
                        tgt["buckets"] = [a + b for a, b in zip(tgt["buckets"], buckets)]
                        tgt["sum"] += v["sum"]
                        tgt["count"] += v["count"]
    if merge_dropped:
        cur = out.get(DROPPED_SERIES_METRIC)
        if cur is None:
            cur = {"name": DROPPED_SERIES_METRIC, "type": "counter",
                   "description": "label sets dropped by the bounded-"
                                  "cardinality guard "
                                  "(RAY_TPU_CONTROL_MAX_SERIES), by metric",
                   "values": {}}
            out[DROPPED_SERIES_METRIC] = cur
        for name, n in merge_dropped.items():
            k = (("metric", name),)
            cur["values"][k] = cur["values"].get(k, 0.0) + float(n)
    return out


# --------------------------------------------------------------- wire codecs

def snapshot_to_wire(snap: List[dict]) -> List[dict]:
    """JSON-safe form of a snapshot: the tag-tuple dict keys (tuples of
    (k, v) pairs) become explicit `series` lists. Node agents ship their
    merged per-node delta to the head as JSON bytes in this form — the head
    never unpickles agent control traffic (core/agent_rpc.py trust
    posture)."""
    out = []
    for m in snap:
        w = {"name": m["name"], "type": m["type"],
             "description": m.get("description", "")}
        if "boundaries" in m:
            w["boundaries"] = list(m["boundaries"])
        w["series"] = [
            {"tags": [[k, v] for k, v in key], "value": val}
            for key, val in m["values"].items()
        ]
        out.append(w)
    return out


def snapshot_from_wire(wire: List[dict]) -> List[dict]:
    """Inverse of snapshot_to_wire: rebuild the tag-tuple-keyed snapshot
    shape that merge_snapshots consumes. Tolerant of malformed entries
    (skips them) — the input crossed a process boundary."""
    out = []
    for m in wire:
        try:
            d = {"name": m["name"], "type": m["type"],
                 "description": m.get("description", "")}
            if "boundaries" in m:
                d["boundaries"] = list(m["boundaries"])
            values = {}
            for s in m.get("series", []):
                key = tuple((str(k), str(v)) for k, v in s["tags"])
                values[key] = s["value"]
            d["values"] = values
            out.append(d)
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (continue) by design
        except Exception:
            continue
    return out


def _tags_match(key_tuple: Tuple, where: Optional[Dict[str, str]]) -> bool:
    """Does this tag-set key (tuple of (k, v) pairs) satisfy the label filter?"""
    if not where:
        return True
    tags = dict(key_tuple)
    return all(tags.get(k) == v for k, v in where.items())


def aggregate_buckets(merged: dict,
                      where: Optional[Dict[str, str]] = None) -> List[int]:
    """Sum a histogram metric's per-tag-set bucket counts into one vector,
    optionally restricted to tag sets matching the `where` label filter
    (e.g. {"route": "/chat"} to quantile serve_ttft_seconds per-route)."""
    bounds = merged.get("boundaries", [])
    agg = [0] * (len(bounds) + 1)
    for key, v in merged.get("values", {}).items():
        if not _tags_match(key, where):
            continue
        for i, c in enumerate(v["buckets"]):
            agg[i] += c
    return agg


def histogram_counts_below(merged: dict, threshold: float,
                           where: Optional[Dict[str, str]] = None
                           ) -> Tuple[float, int]:
    """(estimated observations <= threshold, total observations) for a merged
    histogram — the good/total split behind latency SLO burn rates. The count
    inside the bucket containing the threshold is linearly interpolated, like
    histogram_quantile's inverse."""
    bounds = merged.get("boundaries", [])
    agg = aggregate_buckets(merged, where)
    total = sum(agg)
    if total <= 0:
        return 0.0, 0
    good = 0.0
    for i, c in enumerate(agg):
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else float("inf")
        if threshold >= hi:
            good += c
        elif threshold > lo:
            good += c * (threshold - lo) / (hi - lo)
    return good, total


def histogram_quantile(merged: dict, q: float,
                       where: Optional[Dict[str, str]] = None
                       ) -> Optional[float]:
    """Estimate the q-quantile (0..1) of a merged histogram metric,
    Prometheus histogram_quantile-style: find the bucket where the cumulative
    count crosses q and interpolate linearly inside it. The overflow bucket
    answers with its lower edge (no upper bound to lerp to). Aggregates
    across ALL tag sets unless `where` narrows them (label filter, e.g.
    {"route": "/chat"}). Returns None for an empty histogram."""
    bounds = merged.get("boundaries", [])
    agg = aggregate_buckets(merged, where)
    total = sum(agg)
    if total <= 0:
        return None
    target = max(0.0, min(1.0, q)) * total
    cum = 0
    for i, c in enumerate(agg):
        if cum + c >= target and c > 0:
            if i >= len(bounds):
                return float(bounds[-1]) if bounds else None
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (target - cum) / c
            return float(lo + (bounds[i] - lo) * frac)
        cum += c
    return float(bounds[-1]) if bounds else None


def prometheus_text(merged: Dict[str, dict], prefix: str = "ray_tpu") -> str:
    """Render merged metrics in Prometheus exposition format (reference: the
    dashboard agent's re-export; dashboard/modules/metrics)."""
    lines = []
    for name, m in sorted(merged.items()):
        full = f"{prefix}_{name}"
        lines.append(f"# HELP {full} {m.get('description', '')}")
        lines.append(f"# TYPE {full} {m['type']}")

        def fmt_tags(key_tuple, extra=None):
            items = list(key_tuple) + (list(extra.items()) if extra else [])
            if not items:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in items)
            return "{" + inner + "}"

        if m["type"] in ("counter", "gauge"):
            for k, v in m["values"].items():
                lines.append(f"{full}{fmt_tags(k)} {v}")
        else:
            for k, v in m["values"].items():
                cum = 0
                for bound, cnt in zip(m["boundaries"] + [float("inf")], v["buckets"]):
                    cum += cnt
                    lines.append(f'{full}_bucket{fmt_tags(k, {"le": bound})} {cum}')
                lines.append(f"{full}_sum{fmt_tags(k)} {v['sum']}")
                lines.append(f"{full}_count{fmt_tags(k)} {v['count']}")
    return "\n".join(lines) + "\n"
