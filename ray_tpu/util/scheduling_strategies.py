"""Scheduling strategies (reference python/ray/util/scheduling_strategies.py).

Import-path parity: ``from ray_tpu.util.scheduling_strategies import ...``.
"""
from ray_tpu.core.task_spec import (  # noqa: F401
    DoesNotExist,
    Exists,
    In,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    NotIn,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "SpreadSchedulingStrategy",
    "In",
    "NotIn",
    "Exists",
    "DoesNotExist",
]
