"""@hot_path / @control_path: latency-contract registries for graftlint.

Both decorators are runtime no-ops beyond recording the function in a
registry — their value is the CONTRACT they declare, which graftlint
enforces statically (`ray-tpu lint`):

- ``@hot_path`` marks a function on a device-rate loop (engine scheduler
  step, fused decode emit, grad-sync stage, ring-collective wait). The
  host-sync-in-hot-path check walks it plus its one-level same-file callees
  and flags device->host syncs (`.item()`, `np.asarray`, `float()` on
  arrays, `block_until_ready`) — the defect class behind the 110 ms decode
  round trip PR 12 had to dig out. A DESIGNED sync point (the one fetch per
  K-step burst) stays, with an inline
  ``# graftlint: allow[host-sync-in-hot-path] <why>``.

- ``@control_path`` marks a function the control plane depends on staying
  prompt (health probes, drain paths) that does NOT already ride a
  "control" actor concurrency group (those are picked up from the
  ``concurrency_group="control"`` declaration directly). The
  blocking-control-path check flags sleeps/object-fetches/socket reads
  inside.

Keep this module import-light: hot modules import it at module load.
"""
from __future__ import annotations

from typing import Callable, Optional, Set, TypeVar

F = TypeVar("F", bound=Callable)

HOT_PATHS: Set[str] = set()
CONTROL_PATHS: Set[str] = set()


def _register(registry: Set[str], fn: Callable) -> None:
    registry.add(f"{fn.__module__}:{fn.__qualname__}")


def hot_path(fn: Optional[F] = None, *, reason: str = "") -> F:
    """Declare a function hot: no host syncs inside (graftlint-enforced)."""
    del reason  # documentation at the decoration site, not used at runtime

    def deco(f: F) -> F:
        _register(HOT_PATHS, f)
        return f

    return deco(fn) if fn is not None else deco  # type: ignore[return-value]


def control_path(fn: Optional[F] = None, *, reason: str = "") -> F:
    """Declare a function control-plane: no blocking calls inside."""
    del reason

    def deco(f: F) -> F:
        _register(CONTROL_PATHS, f)
        return f

    return deco(fn) if fn is not None else deco  # type: ignore[return-value]
