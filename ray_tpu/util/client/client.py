"""Client context: the remote driver's runtime API, forwarded over one channel.

Every runtime-API method (submit/get/put/wait/kill_actor/...) is forwarded as
(req_id, method, args, kwargs); a demux thread matches responses. ObjectRefs and
ActorHandles arriving in results re-bind to this context automatically because
they resolve the process-global worker at call time.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional, Tuple

from .server import DEFAULT_AUTHKEY, load_authkey
from .server import REF_RETURNING as _REF_RETURNING  # shared with the server's leasing

# methods forwarded with a response
_FORWARDED = {
    "submit", "get", "put", "wait", "cancel",
    "get_named_actor", "register_fn", "fn_known", "lookup_placement_group",
    "pg_ready_ref", "create_placement_group", "remove_placement_group",
    "kv_request", "state_request",
}
# fire-and-forget: callable from __del__/GC finalizers (possibly ON the recv
# thread), so they must never wait for a response or touch the socket directly
_NO_REPLY = {"decref", "kill_actor", "push_metrics", "push_spans",
             "push_telemetry", "push_tqdm", "drop_stream"}


class ClientContext:
    def __init__(self, address: str, authkey: Optional[bytes] = None,
                 timeout: Optional[float] = None):
        import queue

        if authkey is None:
            # RAY_TPU_CLIENT_AUTHKEY env, then the head's session-dir file
            # (same-host drivers); the legacy fixed key only as a last resort
            # for loopback servers started with an explicit DEFAULT_AUTHKEY
            authkey = load_authkey() or DEFAULT_AUTHKEY
        host, _, port = address.rpartition(":")
        # secure_transport.dial: mTLS under RAY_TPU_USE_TLS (the server refuses
        # plaintext there), plain mp Client otherwise
        from ray_tpu.core.secure_transport import dial

        self._conn = dial((host or "127.0.0.1", int(port)), authkey=authkey,
                          timeout=timeout)
        self._req_counter = itertools.count()
        self._pending: Dict[int, Tuple[threading.Event, list]] = {}
        self._pending_lock = threading.Lock()
        self._closed = False
        # all sends go through the outbox: SimpleQueue.put is reentrant, so GC
        # finalizers (ObjectRef.__del__ -> decref) can enqueue from any thread —
        # including mid-send or on the recv thread — without deadlock/corruption
        self._outbox: "queue.SimpleQueue" = queue.SimpleQueue()
        self._send_thread = threading.Thread(
            target=self._send_loop, daemon=True, name="ray-tpu-client-send")
        self._send_thread.start()
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="ray-tpu-client-recv")
        self._recv_thread.start()
        assert self._call("_ping") == "pong"
        info = self._call("runtime_context")
        self.node_id_hex = info["node_id"]
        # job-level runtime_env default for specs built by THIS client driver
        # (set by ray_tpu.init(address=..., runtime_env=...)); object-scoped so
        # concurrent contexts in one process don't share defaults
        self.default_runtime_env = None
        self.accel = "client-driver"

    # -- transport -------------------------------------------------------------
    def _fail_all_pending(self, reason: str) -> None:
        with self._pending_lock:
            # _closed flips under the same lock _call registers under, so a call
            # either sees closed and raises, or registers in time to be failed here
            self._closed = True
            pending, self._pending = self._pending, {}
        for ev, out in pending.values():
            out.extend((False, ConnectionError(reason)))
            ev.set()

    def _send_loop(self) -> None:
        while not self._closed:
            msg = self._outbox.get()
            if msg is None:
                break
            try:
                self._conn.send(msg)
            except BaseException as e:  # noqa: BLE001
                if msg[0] is not None:
                    # a request failed to serialize/send: fail just that call,
                    # the channel itself may still be fine for picklable traffic
                    with self._pending_lock:
                        slot = self._pending.pop(msg[0], None)
                    if slot is not None:
                        ev, out = slot
                        out.extend((False, e))
                        ev.set()
                if isinstance(e, (OSError, EOFError, BrokenPipeError)):
                    # transport is dead: nothing sent after this can complete
                    # graftlint: allow[lock-hygiene] monotonic shutdown latch: every writer only sets True
                    self._closed = True
                    self._fail_all_pending("client connection lost (send failed)")
                    break

    def _recv_loop(self) -> None:
        while not self._closed:
            try:
                req_id, ok, value = self._conn.recv()
            # graftlint: allow[swallowed-exception] peer closed mid-recv; the loop exits via its closed flag
            except Exception:
                # EOF, OSError, or an unpicklable reply (missing class client-side):
                # the stream position is unrecoverable — fail all pending calls
                break
            with self._pending_lock:
                slot = self._pending.pop(req_id, None)
            if slot is not None:
                ev, out = slot
                out.extend((ok, value))
                ev.set()
        # graftlint: allow[lock-hygiene] monotonic shutdown latch: every writer only sets True
        self._closed = True
        self._fail_all_pending("client connection closed")

    def _call(self, method: str, *args, **kwargs):
        req_id = next(self._req_counter)
        ev: threading.Event = threading.Event()
        out: list = []
        with self._pending_lock:
            if self._closed:
                raise ConnectionError("client connection is closed")
            self._pending[req_id] = (ev, out)
        self._outbox.put((req_id, method, args, kwargs))
        ev.wait()
        ok, value = out
        if not ok:
            raise value
        if method in _REF_RETURNING:
            from .server import set_ref_ownership

            set_ref_ownership(value, True)
        return value

    def _cast(self, method: str, *args, **kwargs) -> None:
        """Fire-and-forget (no response; safe from GC finalizers)."""
        self._outbox.put((None, method, args, kwargs))

    # -- runtime API -----------------------------------------------------------
    def __getattr__(self, name: str):
        if name in _FORWARDED:
            return lambda *a, **k: self._call(name, *a, **k)
        if name in _NO_REPLY:
            return lambda *a, **k: self._cast(name, *a, **k)
        raise AttributeError(name)

    def runtime_context(self) -> Dict[str, Any]:
        ctx = self._call("runtime_context")
        ctx["worker_id"] = "client-driver"
        return ctx

    def as_future(self, ref):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="client-async-get").start()
        return fut

    def close(self) -> None:
        # graftlint: allow[lock-hygiene] monotonic shutdown latch: every writer only sets True
        self._closed = True
        self._outbox.put(None)  # unblock the sender
        try:
            self._conn.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass


def connect(address: str, authkey: Optional[bytes] = None) -> ClientContext:
    """Connect this process as a remote driver (reference ray.init('ray://...'))."""
    from ray_tpu.core import global_state

    ctx = ClientContext(address, authkey)
    global_state.set_worker(ctx)
    return ctx


def disconnect() -> None:
    from ray_tpu.core import global_state

    w = global_state.try_worker()
    if isinstance(w, ClientContext):
        w.close()
        global_state.set_worker(None)
