"""Client context: the remote driver's runtime API, forwarded over one channel.

Every runtime-API method (submit/get/put/wait/kill_actor/...) is forwarded as
(req_id, method, args, kwargs); a demux thread matches responses. ObjectRefs and
ActorHandles arriving in results re-bind to this context automatically because
they resolve the process-global worker at call time.

Head fault tolerance: the transport survives a head outage. On connection
loss the send loop redials with jittered backoff (bounded by
RAY_TPU_HEAD_RECONNECT_TIMEOUT_S); loss-intolerant casts (decref/kill_actor/
drop_stream) are sequence-numbered into a bounded replay outbox and re-sent
on reconnect — the server dedups by per-client high-water seq and acks, so a
same-head transport blip applies each exactly once and a restarted head
receives the in-doubt tail. Blocking calls in flight when the transport died
fail typed (HeadUnavailableError, carrying the outage age) instead of
hanging; calls issued DURING the outage queue and complete after reconnect.
"""
from __future__ import annotations

import collections
import itertools
import random
import secrets
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core.exceptions import HeadUnavailableError

from .server import DEFAULT_AUTHKEY, load_authkey
from .server import REF_RETURNING as _REF_RETURNING  # shared with the server's leasing

# methods forwarded with a response
_FORWARDED = {
    "submit", "get", "put", "wait", "cancel",
    "get_named_actor", "register_fn", "fn_known", "lookup_placement_group",
    "pg_ready_ref", "create_placement_group", "remove_placement_group",
    "kv_request", "state_request",
}
# fire-and-forget: callable from __del__/GC finalizers (possibly ON the recv
# thread), so they must never wait for a response or touch the socket directly
_NO_REPLY = {"decref", "kill_actor", "push_metrics", "push_spans",
             "push_telemetry", "push_tqdm", "drop_stream"}
# the loss-INTOLERANT subset: dropping one leaks an object or an actor, so
# these ride the sequence-numbered replay outbox (acked-or-queued); the
# telemetry pushes above tolerate loss and stay plain casts
_REPLAYABLE = {"decref", "kill_actor", "drop_stream"}

# internal wire markers (never collide with int req_ids)
_ACK_ID = "_seq_ack"
_HANDSHAKE_ID = "_handshake_ping"


class ClientContext:
    def __init__(self, address: str, authkey: Optional[bytes] = None,
                 timeout: Optional[float] = None):
        import queue

        if authkey is None:
            # RAY_TPU_CLIENT_AUTHKEY env, then the head's session-dir file
            # (same-host drivers); the legacy fixed key only as a last resort
            # for loopback servers started with an explicit DEFAULT_AUTHKEY
            authkey = load_authkey() or DEFAULT_AUTHKEY
        host, _, port = address.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._authkey = authkey
        self._dial_timeout = timeout
        # secure_transport.dial: mTLS under RAY_TPU_USE_TLS (the server refuses
        # plaintext there), plain mp Client otherwise
        from ray_tpu.core.secure_transport import dial

        self._conn = dial(self._addr, authkey=authkey, timeout=timeout)
        self._conn_gen = 0
        self._client_id = secrets.token_hex(8)
        self._req_counter = itertools.count()
        self._pending: Dict[int, Tuple[threading.Event, list]] = {}
        self._sent_gen: Dict[int, int] = {}  # req_id -> conn generation it left on
        self._pending_lock = threading.Lock()
        self._closed = False
        # head-outage bookkeeping (read by _closed_error for typed raises)
        self._head_lost_at: Optional[float] = None
        self._gave_up_attempts = 0
        self._cv = threading.Condition()  # guards _conn/_conn_gen transitions
        # sequence-numbered replay outbox for loss-intolerant casts
        self._seq = itertools.count()
        self._replay: "collections.deque" = collections.deque()
        self._replay_lock = threading.Lock()
        # all sends go through the outbox: SimpleQueue.put is reentrant, so GC
        # finalizers (ObjectRef.__del__ -> decref) can enqueue from any thread —
        # including mid-send or on the recv thread — without deadlock/corruption
        self._outbox: "queue.SimpleQueue" = queue.SimpleQueue()
        self._send_thread = threading.Thread(
            target=self._send_loop, daemon=True, name="ray-tpu-client-send")
        self._send_thread.start()
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="ray-tpu-client-recv")
        self._recv_thread.start()
        # register this client's identity first so the server anchors leases
        # and the seq-dedup high-water to it (survives reconnects)
        self._cast("_hello", self._client_id)
        assert self._call("_ping") == "pong"
        info = self._call("runtime_context")
        self.node_id_hex = info["node_id"]
        # job-level runtime_env default for specs built by THIS client driver
        # (set by ray_tpu.init(address=..., runtime_env=...)); object-scoped so
        # concurrent contexts in one process don't share defaults
        self.default_runtime_env = None
        self.accel = "client-driver"

    # -- transport -------------------------------------------------------------
    def _closed_error(self) -> Exception:
        if self._head_lost_at is not None:
            return HeadUnavailableError(self._head_lost_at,
                                        self._gave_up_attempts,
                                        "client gave up redialing the head")
        return ConnectionError("client connection is closed")

    def _fail_all_pending(self, exc: Exception) -> None:
        with self._pending_lock:
            # _closed flips under the same lock _call registers under, so a call
            # either sees closed and raises, or registers in time to be failed here
            self._closed = True
            pending, self._pending = self._pending, {}
            self._sent_gen.clear()
        for ev, out in pending.values():
            out.extend((False, exc))
            ev.set()

    def _fail_sent_pending(self, dead_gen: int, exc: Exception) -> None:
        """Fail only requests whose frames left on a now-dead connection: their
        replies are unrecoverable. Requests still queued in the outbox survive
        the outage and complete after reconnect."""
        with self._pending_lock:
            doomed = [rid for rid, g in self._sent_gen.items() if g <= dead_gen]
            slots = [self._pending.pop(rid, None) for rid in doomed]
            for rid in doomed:
                self._sent_gen.pop(rid, None)
        for slot in slots:
            if slot is not None:
                ev, out = slot
                out.extend((False, exc))
                ev.set()

    def _trim_replay(self, upto_seq: int) -> None:
        """Server acked application through upto_seq: those casts are durable
        and leave the replay window."""
        with self._replay_lock:
            while self._replay and self._replay[0][0] <= upto_seq:
                self._replay.popleft()

    def _handshake(self, conn) -> None:
        """Run on a FRESH connection before publishing it: re-identify, replay
        the in-doubt cast window in order, and confirm liveness — all inline
        (the recv loop is parked until the new generation is published)."""
        conn.send((None, "_hello", (self._client_id,), {}))
        with self._replay_lock:
            backlog = list(self._replay)
        for seq, method, args, kwargs in backlog:
            conn.send((None, "_seq_cast",
                       (self._client_id, seq, method, args), kwargs))
        conn.send((_HANDSHAKE_ID, "_ping", (), {}))
        while True:  # acks for the replayed window may precede the ping reply
            if hasattr(conn, "poll") and not conn.poll(5.0):
                raise ConnectionError("handshake timed out")
            rid, ok, value = conn.recv()
            if rid == _ACK_ID:
                self._trim_replay(value)
                continue
            if rid == _HANDSHAKE_ID:
                if not ok or value != "pong":
                    raise ConnectionError(f"handshake failed: {value!r}")
                return

    def _reconnect(self, dead_conn) -> bool:
        """Bounded redial with jittered backoff (send-loop only). Returns True
        once a fresh connection is published; False when the window expired —
        the context is then closed and every pending call fails typed."""
        from ray_tpu.config import CONFIG
        from ray_tpu.core.secure_transport import dial

        with self._cv:
            if self._closed:
                return False
            if self._conn is not dead_conn:
                return self._conn is not None  # already replaced
            dead_gen = self._conn_gen
            self._conn = None
            if self._head_lost_at is None:
                self._head_lost_at = time.time()
        try:
            dead_conn.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        # frames already on the dead wire lost their replies: fail those calls
        # typed NOW (serve's retry plane classifies this and resends), rather
        # than leaving them to hang through the whole outage
        self._fail_sent_pending(dead_gen, HeadUnavailableError(
            self._head_lost_at or time.time(), 0,
            "head connection lost with the reply outstanding"))
        deadline = time.monotonic() + CONFIG.head_reconnect_timeout_s
        backoff = CONFIG.head_reconnect_backoff_s
        attempts = 0
        while time.monotonic() < deadline and not self._closed:
            attempts += 1
            try:
                conn = dial(self._addr, authkey=self._authkey,
                            timeout=min(5.0, CONFIG.head_reconnect_timeout_s))
                self._handshake(conn)
            # graftlint: allow[swallowed-exception] redial loop: failures retry with backoff until the reconnect deadline
            except Exception:  # noqa: BLE001 — redial failures drive the backoff
                delay = min(backoff, max(0.0, deadline - time.monotonic()))
                backoff = min(backoff * 2, CONFIG.head_reconnect_backoff_max_s)
                time.sleep(delay * (0.5 + random.random() * 0.5))
                continue
            with self._cv:
                self._conn = conn
                self._conn_gen += 1
                self._head_lost_at = None
                self._cv.notify_all()
            return True
        # window expired: the head is durably gone for this context
        self._gave_up_attempts = attempts
        with self._cv:
            self._cv.notify_all()
        self._fail_all_pending(HeadUnavailableError(
            self._head_lost_at or time.time(), attempts,
            "reconnect window expired"))
        return False

    def _send_loop(self) -> None:
        while True:
            msg = self._outbox.get()
            if msg is None:
                break
            if msg[0] == "__reconnect__":
                # recv-loop poke: the transport died while this loop was
                # parked on an empty outbox — reconnect now
                with self._cv:
                    conn, gen = self._conn, self._conn_gen
                if conn is not None and gen == msg[1]:
                    if not self._reconnect(conn):
                        break
                continue
            while True:
                with self._cv:
                    conn, gen = self._conn, self._conn_gen
                if conn is None:
                    if self._closed:
                        if msg[0] is not None:
                            self._fail_req(msg[0], self._closed_error())
                        break
                    # mid-reconnect (recv poke raced us): retry shortly
                    time.sleep(0.02)
                    continue
                try:
                    conn.send(msg)
                    if msg[0] is not None:
                        with self._pending_lock:
                            if msg[0] in self._pending:
                                self._sent_gen[msg[0]] = gen
                    break
                except BaseException as e:  # noqa: BLE001
                    if not isinstance(e, (OSError, EOFError, BrokenPipeError)):
                        # a request failed to serialize: fail just that call,
                        # the channel itself is still fine for picklable traffic
                        if msg[0] is not None:
                            self._fail_req(msg[0], e)
                        break
                    # transport is dead: redial (bounded), then re-send this
                    # frame — it never left, so the retry is at-most-once
                    if not self._reconnect(conn):
                        if msg[0] is not None:
                            self._fail_req(msg[0], self._closed_error())
                        break
            if self._closed and self._conn is None:
                break

    def _fail_req(self, req_id: int, exc: BaseException) -> None:
        with self._pending_lock:
            slot = self._pending.pop(req_id, None)
            self._sent_gen.pop(req_id, None)
        if slot is not None:
            ev, out = slot
            out.extend((False, exc))
            ev.set()

    def _recv_loop(self) -> None:
        while True:
            with self._cv:
                while self._conn is None and not self._closed:
                    self._cv.wait(timeout=0.1)
                if self._closed:
                    break
                conn, gen = self._conn, self._conn_gen
            try:
                req_id, ok, value = conn.recv()
            # graftlint: allow[swallowed-exception] peer closed mid-recv; reconnection is poked below and the loop re-parks
            except Exception:
                # EOF/OSError/unpicklable reply: this connection is done. Poke
                # the send loop to redial and park until a new generation (or
                # permanent closure) appears.
                if self._closed:
                    break
                self._outbox.put(("__reconnect__", gen))
                with self._cv:
                    while self._conn_gen == gen and not self._closed:
                        self._cv.wait(timeout=0.1)
                continue
            if req_id == _ACK_ID:
                self._trim_replay(value)
                continue
            with self._pending_lock:
                slot = self._pending.pop(req_id, None)
                self._sent_gen.pop(req_id, None)
            if slot is not None:
                ev, out = slot
                out.extend((ok, value))
                ev.set()
        self._fail_all_pending(self._closed_error())

    def _call(self, method: str, *args, **kwargs):
        req_id = next(self._req_counter)
        ev: threading.Event = threading.Event()
        out: list = []
        with self._pending_lock:
            if self._closed:
                raise self._closed_error()
            self._pending[req_id] = (ev, out)
        self._outbox.put((req_id, method, args, kwargs))
        ev.wait()
        ok, value = out
        if not ok:
            raise value
        if method in _REF_RETURNING:
            from .server import set_ref_ownership

            set_ref_ownership(value, True)
        return value

    def _cast(self, method: str, *args, **kwargs) -> None:
        """Fire-and-forget (no response; safe from GC finalizers). The
        loss-intolerant subset is sequence-numbered into the replay outbox so
        a head outage delays it instead of dropping it."""
        if method in _REPLAYABLE:
            from ray_tpu.config import CONFIG

            limit = CONFIG.head_outbox_limit
            with self._replay_lock:
                seq = next(self._seq)
                self._replay.append((seq, method, args, kwargs))
                while limit > 0 and len(self._replay) > limit:
                    self._replay.popleft()  # oldest in-doubt entries fall off
            self._outbox.put((None, "_seq_cast",
                              (self._client_id, seq, method, args), kwargs))
        else:
            self._outbox.put((None, method, args, kwargs))

    # -- runtime API -----------------------------------------------------------
    def __getattr__(self, name: str):
        if name in _FORWARDED:
            return lambda *a, **k: self._call(name, *a, **k)
        if name in _NO_REPLY:
            return lambda *a, **k: self._cast(name, *a, **k)
        raise AttributeError(name)

    def runtime_context(self) -> Dict[str, Any]:
        ctx = self._call("runtime_context")
        ctx["worker_id"] = "client-driver"
        return ctx

    def as_future(self, ref):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="client-async-get").start()
        return fut

    def close(self) -> None:
        with self._cv:
            # graftlint: allow[lock-hygiene] monotonic shutdown latch: every writer only sets True
            self._closed = True
            self._head_lost_at = None  # explicit close, not an outage
            conn, self._conn = self._conn, None
            self._cv.notify_all()
        self._outbox.put(None)  # unblock the sender
        try:
            if conn is not None:
                conn.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        self._fail_all_pending(ConnectionError("client connection is closed"))


def connect(address: str, authkey: Optional[bytes] = None) -> ClientContext:
    """Connect this process as a remote driver (reference ray.init('ray://...'))."""
    from ray_tpu.core import global_state

    ctx = ClientContext(address, authkey)
    global_state.set_worker(ctx)
    return ctx


def disconnect() -> None:
    from ray_tpu.core import global_state

    w = global_state.try_worker()
    if isinstance(w, ClientContext):
        w.close()
        global_state.set_worker(None)
