"""Client server: listens on the head and forwards calls to the local runtime.

Capability parity: reference python/ray/util/client/server/ — one server process
on the head node, N remote clients. Each accepted connection gets a demux thread;
each request runs on its own dispatch thread so a blocking get() from one client
doesn't starve others on the same connection.
"""
from __future__ import annotations

import threading
from typing import Optional

DEFAULT_AUTHKEY = b"ray-tpu-client"

# methods whose replies carry NEW ObjectRefs with ownership transferring to the
# client; replies from other methods (get/wait/...) contain only borrows and
# must NOT be leased — leasing them would reclaim objects the head still owns
REF_RETURNING = frozenset({"submit", "put", "pg_ready_ref"})


def set_ref_ownership(value, owned: bool) -> list:
    """Walk a reply value and flip ObjectRef ownership; returns the ids touched.

    Server side (owned=False): the pickled copies on the client take over the
    refcount (client __del__ forwards decref), so the server-side temporaries
    must NOT decref when the dispatch thread drops them — otherwise a fast task
    result can be freed before the client's get arrives. Client side
    (owned=True): the unpickled borrows become the owning copies."""
    from ray_tpu.core.object_ref import ObjectRef

    touched = []
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, ObjectRef):
            v._owned = owned
            touched.append(v.id)
        elif isinstance(v, (list, tuple, set)):
            stack.extend(v)
        elif isinstance(v, dict):
            stack.extend(v.values())
    return touched


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 10001,
                 authkey: bytes = DEFAULT_AUTHKEY):
        from multiprocessing.connection import Listener

        self._listener = Listener((host, port), authkey=authkey)  # port 0 = ephemeral
        self.address = self._listener.address
        self.port = self.address[1]
        self._shutdown = False
        self._conns: list = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="client-server-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                break
            self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="client-server-conn").start()

    def _serve_conn(self, conn) -> None:
        from ray_tpu.core import global_state

        send_lock = threading.Lock()
        # ownership leased to this client: reclaimed if it disconnects uncleanly
        leak_lock = threading.Lock()
        leased_refs: set = set()
        leased_actors: set = set()

        def dispatch(req_id, method, args, kwargs):
            try:
                if method == "_ping":
                    ok, value = True, "pong"
                else:
                    ctx = global_state.worker()
                    ok = True
                    value = getattr(ctx, method)(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                ok, value = False, e
            if req_id is None:
                if method == "decref" and args:
                    with leak_lock:
                        leased_refs.discard(args[0])
                elif method == "kill_actor" and args:
                    with leak_lock:
                        leased_actors.discard(args[0])
                return
            if ok and method in REF_RETURNING:
                # lease BEFORE the reply goes out so a fast client decref can
                # never race ahead of the lease record
                touched = set_ref_ownership(value, False)
                if touched:
                    with leak_lock:
                        leased_refs.update(touched)
                if method == "submit" and args and getattr(args[0], "kind", "") == "actor_creation":
                    with leak_lock:
                        leased_actors.add(args[0].actor_id)
            try:
                with send_lock:
                    conn.send((req_id, ok, value))
            except Exception:
                # reply unpicklable: send a describable error instead of leaving
                # the client's _call waiting forever (leases stay recorded and
                # are reclaimed on disconnect)
                try:
                    with send_lock:
                        conn.send((req_id, False,
                                   RuntimeError(f"client-server reply failed to serialize: {value!r:.500}")))
                except Exception:
                    pass

        while not self._shutdown:
            try:
                req_id, method, args, kwargs = conn.recv()
            except Exception:  # EOF/OSError/malformed frame all end the session
                break
            if req_id is None:
                dispatch(req_id, method, args, kwargs)  # casts are quick: run inline
            else:
                threading.Thread(target=dispatch, args=(req_id, method, args, kwargs),
                                 daemon=True).start()
        try:
            conn.close()
        except Exception:
            pass
        # reclaim whatever the client still owned (crash / dropped connection)
        ctx = global_state.try_worker()
        if ctx is None:
            return
        with leak_lock:
            refs, actors = list(leased_refs), list(leased_actors)
            leased_refs.clear()
            leased_actors.clear()
        for oid in refs:
            try:
                ctx.decref(oid)
            except Exception:
                pass
        for aid in actors:
            try:
                ctx.kill_actor(aid, no_restart=True, from_gc=True)
            except Exception:
                pass

    def close(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except Exception:
            pass
        for c in self._conns:
            try:
                c.close()
            except Exception:
                pass


_server: Optional[ClientServer] = None


def start_client_server(host: str = "127.0.0.1", port: int = 10001,
                        authkey: bytes = DEFAULT_AUTHKEY) -> ClientServer:
    """Start (or return) the head-side client server (driver process)."""
    global _server
    if _server is None:
        _server = ClientServer(host, port, authkey)
    return _server


def stop_client_server() -> None:
    global _server
    if _server is not None:
        _server.close()
        _server = None
