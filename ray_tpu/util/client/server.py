"""Client server: listens on the head and forwards calls to the local runtime.

Capability parity: reference python/ray/util/client/server/ — one server process
on the head node, N remote clients. Each accepted connection gets a demux thread;
each request runs on its own dispatch thread so a blocking get() from one client
doesn't starve others on the same connection.
"""
from __future__ import annotations

import multiprocessing as _mp
import os
import secrets
import threading
import time
from typing import Optional

# legacy well-known key: acceptable only on loopback (anyone reaching the port
# speaks a pickle protocol with driver-level privileges, so a fixed key on a
# routable interface is remote code execution for the whole network)
DEFAULT_AUTHKEY = b"ray-tpu-client"

_LOOPBACK_HOSTS = frozenset({"127.0.0.1", "localhost", "::1"})


def _authkey_file() -> str:
    from ray_tpu.job.manager import default_session_dir

    return os.path.join(default_session_dir(), "client_authkey")


def _persist_authkey(key: bytes) -> None:
    """Write the cluster authkey to the session dir (mode 0600) so same-host
    clients and `ray-tpu` CLI tooling pick it up; always written, so a restart
    with a different (e.g. explicit) key never leaves a stale file behind."""
    path = _authkey_file()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "wb") as f:
        f.write(key)


def generate_authkey() -> bytes:
    key = secrets.token_hex(32).encode()
    _persist_authkey(key)
    return key


def load_authkey() -> Optional[bytes]:
    """Resolve the cluster authkey: RAY_TPU_CLIENT_AUTHKEY env, then session dir."""
    from ray_tpu.config import CONFIG

    key = CONFIG.client_authkey
    if key:
        return key.encode()
    try:
        with open(_authkey_file(), "rb") as f:
            return f.read().strip()
    except OSError:
        return None

# methods whose replies carry NEW ObjectRefs with ownership transferring to the
# client; replies from other methods (get/wait/...) contain only borrows and
# must NOT be leased — leasing them would reclaim objects the head still owns
REF_RETURNING = frozenset({"submit", "put", "pg_ready_ref"})

# -- reconnect support (head fault tolerance) ---------------------------------
# A client that redials after a transport blip announces itself with _hello
# (a stable per-context client id). Leases are anchored to that id so the OLD
# connection's teardown never reclaims refs/actors a live, reconnected client
# still owns; sequence-numbered casts (_seq_cast) dedup against a per-client
# high-water mark and are acked so the client can trim its replay outbox.
_client_state_lock = threading.Lock()
_client_sessions: dict = {}  # client_id -> {"refs", "actors", "gen", "seq_hw"}


def _adopt_session(client_id: str) -> tuple:
    """Register a (re)connection for client_id; returns (session, generation).
    The newest generation owns the leases — an older connection's disconnect
    cleanup sees a newer gen and skips reclaim."""
    with _client_state_lock:
        sess = _client_sessions.setdefault(
            client_id, {"refs": set(), "actors": set(), "gen": 0, "seq_hw": -1})
        sess["gen"] += 1
        return sess, sess["gen"]


def _retire_session(client_id: str, gen: int) -> bool:
    """True when this connection was the client's LAST (no newer reconnect
    adopted the leases): the caller must reclaim. Drops the session record."""
    with _client_state_lock:
        sess = _client_sessions.get(client_id)
        if sess is None or sess["gen"] != gen:
            return False
        del _client_sessions[client_id]
        return True


def set_ref_ownership(value, owned: bool) -> list:
    """Walk a reply value and flip ObjectRef ownership; returns the ids touched.

    Server side (owned=False): the pickled copies on the client take over the
    refcount (client __del__ forwards decref), so the server-side temporaries
    must NOT decref when the dispatch thread drops them — otherwise a fast task
    result can be freed before the client's get arrives. Client side
    (owned=True): the unpickled borrows become the owning copies."""
    from ray_tpu.core.object_ref import ObjectRef

    touched = []
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, ObjectRef):
            v._owned = owned
            touched.append(v.id)
        elif isinstance(v, (list, tuple, set)):
            stack.extend(v)
        elif isinstance(v, dict):
            stack.extend(v.values())
    return touched


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 10001,
                 authkey: Optional[bytes] = None):
        from multiprocessing.connection import Listener

        if authkey is None:
            # reuse the cluster's existing session key when one is already
            # persisted (e.g. the node server bound it first) — generating a
            # fresh key here would overwrite the file and lock out node agents
            authkey = load_authkey() or generate_authkey()
        else:
            if authkey == DEFAULT_AUTHKEY and host not in _LOOPBACK_HOSTS:
                raise ValueError(
                    f"refusing to bind the client server on {host!r} with the default "
                    "authkey: the wire protocol is pickle (driver-level code execution). "
                    "Omit authkey to generate a per-cluster random key (written to the "
                    "session dir; share via RAY_TPU_CLIENT_AUTHKEY on remote drivers).")
            _persist_authkey(authkey)  # keep session-dir discovery in sync
        self.authkey = authkey
        from ray_tpu.core import tls_utils

        # Under RAY_TPU_USE_TLS the ray-tpu:// port speaks mTLS like every
        # other inter-node plane (reference: the gRPC client proxy inherits
        # RAY_USE_TLS, python/ray/_private/tls_utils.py:68); plaintext dials
        # fail the handshake before a single protocol byte. The mp challenge
        # auth still runs over the encrypted channel.
        self._tls = tls_utils.use_tls()
        if self._tls:
            from ray_tpu.core.secure_transport import make_listener

            self._listener = make_listener((host, port))
        else:
            self._listener = Listener((host, port), authkey=authkey)  # port 0 = ephemeral
        self.address = self._listener.address
        self.port = self.address[1]
        self._shutdown = False
        self._conns: list = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="client-server-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError, _mp.AuthenticationError):
                if self._shutdown:
                    break
                time.sleep(0.05)  # bad dial / wrong key: keep serving others
                continue
            self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="client-server-conn").start()

    def _serve_conn(self, conn) -> None:
        if self._tls:
            # the deferred TLS handshake + mp challenge run HERE, on the
            # per-connection thread — a silent or plaintext dialer must stall
            # only its own connection, never the accept loop (mp.Listener runs
            # the challenge inside accept(); the TLS listener defers it).
            try:
                from multiprocessing.connection import (
                    answer_challenge, deliver_challenge)

                deliver_challenge(conn, self.authkey)
                answer_challenge(conn, self.authkey)
            except (OSError, EOFError, _mp.AuthenticationError):
                # close the half-open socket so the failed dialer sees EOF
                # instead of blocking forever
                try:
                    conn.close()
                # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
                except Exception:
                    pass
                return
        self._serve_authed(conn)

    def _serve_authed(self, conn) -> None:
        from ray_tpu.core import global_state

        send_lock = threading.Lock()
        # ownership leased to this client: reclaimed if it disconnects uncleanly.
        # A _hello from a reconnect-capable client swaps these for the
        # session-registry sets anchored to its client id, so leases survive
        # transport blips (see _adopt_session/_retire_session).
        leak_lock = threading.Lock()
        sess = {"refs": set(), "actors": set(), "cid": None, "gen": 0}

        def _ack(seq: int) -> None:
            try:
                with send_lock:
                    conn.send(("_seq_ack", True, seq))
            # graftlint: allow[swallowed-exception] best-effort ack; an unacked cast stays in the client's replay outbox and re-applies dedup'd
            except Exception:
                pass

        def dispatch(req_id, method, args, kwargs):
            if method == "_hello" and args:
                registry_sess, gen = _adopt_session(args[0])
                with leak_lock:
                    # migrate any leases taken before the hello (normally none)
                    registry_sess["refs"].update(sess["refs"])
                    registry_sess["actors"].update(sess["actors"])
                    sess["refs"] = registry_sess["refs"]
                    sess["actors"] = registry_sess["actors"]
                    sess["cid"], sess["gen"] = args[0], gen
                return
            if method == "_seq_cast" and args:
                cid, seq, inner, inner_args = args
                with _client_state_lock:
                    reg = _client_sessions.get(cid)
                    fresh = reg is None or seq > reg["seq_hw"]
                    if reg is not None and fresh:
                        reg["seq_hw"] = seq
                if fresh:
                    dispatch(None, inner, inner_args, kwargs)
                _ack(seq)  # re-ack duplicates too, so the client trims
                return
            try:
                if method == "_ping":
                    ok, value = True, "pong"
                else:
                    ctx = global_state.worker()
                    ok = True
                    value = getattr(ctx, method)(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                ok, value = False, e
            if req_id is None:
                if method == "decref" and args:
                    with leak_lock:
                        sess["refs"].discard(args[0])
                elif method == "kill_actor" and args:
                    with leak_lock:
                        sess["actors"].discard(args[0])
                return
            if ok and method in REF_RETURNING:
                # lease BEFORE the reply goes out so a fast client decref can
                # never race ahead of the lease record
                touched = set_ref_ownership(value, False)
                if touched:
                    with leak_lock:
                        sess["refs"].update(touched)
                if method == "submit" and args and getattr(args[0], "kind", "") == "actor_creation":
                    with leak_lock:
                        sess["actors"].add(args[0].actor_id)
            try:
                with send_lock:
                    conn.send((req_id, ok, value))
            # graftlint: allow[swallowed-exception] error reply failed: client is gone, nothing to tell it
            except Exception:
                # reply unpicklable: send a describable error instead of leaving
                # the client's _call waiting forever (leases stay recorded and
                # are reclaimed on disconnect)
                try:
                    with send_lock:
                        conn.send((req_id, False,
                                   RuntimeError(f"client-server reply failed to serialize: {value!r:.500}")))
                # graftlint: allow[swallowed-exception] best-effort send to a possibly-dead peer; death is handled by heartbeat/reaper, not here
                except Exception:
                    pass

        while not self._shutdown:
            try:
                req_id, method, args, kwargs = conn.recv()
            # graftlint: allow[swallowed-exception] peer closed mid-recv; the connection handler unwinds
            except Exception:  # EOF/OSError/malformed frame all end the session
                break
            if req_id is None:
                dispatch(req_id, method, args, kwargs)  # casts are quick: run inline
            else:
                threading.Thread(target=dispatch, args=(req_id, method, args, kwargs),
                                 daemon=True, name="client-server-dispatch").start()
        try:
            conn.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        # reclaim whatever the client still owned (crash / dropped connection).
        # A reconnect-capable client whose NEWER connection adopted the leases
        # must NOT be reclaimed here — that would free objects and kill actors
        # a live client still holds through a transport blip.
        with leak_lock:
            cid, gen = sess["cid"], sess["gen"]
        if cid is not None and not _retire_session(cid, gen):
            return
        ctx = global_state.try_worker()
        if ctx is None:
            return
        with leak_lock:
            refs, actors = list(sess["refs"]), list(sess["actors"])
            sess["refs"] = set()
            sess["actors"] = set()
        for oid in refs:
            try:
                ctx.decref(oid)
            # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
            except Exception:
                pass
        for aid in actors:
            try:
                ctx.kill_actor(aid, no_restart=True, from_gc=True)
            # graftlint: allow[swallowed-exception] GC/decref during teardown: the runtime may already be torn down
            except Exception:
                pass

    def close(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
        except Exception:
            pass
        for c in self._conns:
            try:
                c.close()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass


_server: Optional[ClientServer] = None


def start_client_server(host: str = "127.0.0.1", port: int = 10001,
                        authkey: Optional[bytes] = None) -> ClientServer:
    """Start (or return) the head-side client server (driver process)."""
    global _server
    if _server is None:
        _server = ClientServer(host, port, authkey)
    return _server


def stop_client_server() -> None:
    global _server
    if _server is not None:
        _server.close()
        _server = None
