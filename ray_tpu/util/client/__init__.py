"""Ray Client equivalent: drive a running cluster from a remote process.

Capability parity: reference python/ray/util/client/ (gRPC proxy for remote
drivers; ARCHITECTURE.md). TPU-native design: instead of a gRPC schema, the
runtime-API surface (submit/get/put/wait/actors/PGs — the same methods
DriverContext exposes) is forwarded over an authenticated
multiprocessing.connection channel; ObjectRefs/ActorHandles pickle by id and
re-bind to the client context on arrival, so `ray_tpu.remote/get/put` work
unchanged in the remote driver. Connect with
`ray_tpu.init(address="ray-tpu://host:port")` or `client.connect(...)`.
"""
from .client import ClientContext, connect, disconnect  # noqa: F401
from .server import ClientServer  # noqa: F401
