"""Group coordinator actor: rendezvous + host-plane collective metadata exchange.

Reference analogue: the named NCCLUniqueIDStore actor (python/ray/util/collective/util.py:9)
and the Rendezvous class (collective_group/nccl_collective_group.py:29). Here the coordinator
does double duty: (1) rendezvous/bootstrap metadata (world size, jax.distributed coordinator
address for the XLA backend, the data-plane authkey for the ring path), (2) a poll-based
exchange board for SHM-backend collectives.

The board is a CONTROL-plane surface: above the ring size threshold ranks post only tiny
metadata records (data-plane address + buffer key) and move tensor bytes rank-to-rank over
the data plane (ring.py); below it the tensor itself rides the board (small-tensor fast
path). `contribute` sizes every payload so tests (and operators) can assert that no
tensor-sized payload transits this single-threaded actor.

Clients never block inside coordinator methods (the actor is single-threaded FIFO); they
poll. Entries are garbage-collected once every participant has fetched them.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple


def _payload_nbytes(payload: Any) -> int:
    """Approximate wire size of a board payload — exact for the cases that
    matter (numpy tensors, raw bytes); containers recurse one level deep
    because ring metadata is flat."""
    try:
        if payload is None or isinstance(payload, (bool, int, float)):
            return 8
        if isinstance(payload, (bytes, bytearray, memoryview, str)):
            return len(payload)
        nbytes = getattr(payload, "nbytes", None)
        if isinstance(nbytes, int):
            return nbytes
        if isinstance(payload, dict):
            return sum(_payload_nbytes(k) + _payload_nbytes(v)
                       for k, v in payload.items())
        if isinstance(payload, (list, tuple)):
            return sum(_payload_nbytes(v) for v in payload)
    except Exception:
        pass
    return 64  # opaque object: count something


class GroupCoordinator:
    """Per-collective-group named actor. Name: `ray_tpu.collective.<group_name>`."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        # key -> {rank: payload}
        self._boards: Dict[str, Dict[int, Any]] = {}
        # key -> set of ranks that have fetched the completed board
        self._fetched: Dict[str, set] = {}
        self._meta: Dict[str, Any] = {}
        # shared secret for the group's rank-to-rank data plane: members fetch
        # it once at group init and use it for their DataServer/DataClient
        # pair, so ring pulls are authenticated without any cluster-wide key
        # distribution (the coordinator IS the group's trust anchor).
        self._data_authkey = os.urandom(16)
        # instrumentation: the board must carry metadata, not tensors, above
        # the ring threshold — these let tests assert exactly that.
        self._max_contrib_bytes = 0
        self._total_contrib_bytes = 0
        self._num_contribs = 0

    # -- metadata (rendezvous) ---------------------------------------------------------
    def set_meta(self, key: str, value: Any) -> None:
        self._meta[key] = value

    def get_meta(self, key: str) -> Any:
        return self._meta.get(key)

    def data_authkey(self) -> bytes:
        return self._data_authkey

    # -- exchange board ----------------------------------------------------------------
    def contribute(self, key: str, rank: int, payload: Any) -> None:
        n = _payload_nbytes(payload)
        self._num_contribs += 1
        self._total_contrib_bytes += n
        if n > self._max_contrib_bytes:
            self._max_contrib_bytes = n
        self._boards.setdefault(key, {})[rank] = payload

    def board_stats(self) -> Dict[str, int]:
        """Bytes that transited this actor's board (tensor bytes on the old
        path, metadata-only above the ring threshold on the new one)."""
        return {
            "max_contrib_bytes": self._max_contrib_bytes,
            "total_contrib_bytes": self._total_contrib_bytes,
            "num_contribs": self._num_contribs,
        }

    def poll(self, key: str, rank: int, expected: Optional[int] = None) -> Tuple[bool, Optional[List[Any]]]:
        """Return (ready, payload-list-in-rank-order). Marks `rank` as fetched when ready."""
        want = expected if expected is not None else self.world_size
        board = self._boards.get(key)
        if board is None or len(board) < want:
            return False, None
        out = [board[r] for r in sorted(board)]
        fetched = self._fetched.setdefault(key, set())
        fetched.add(rank)
        # Every group member fetches the completed board (even ops with one contributor,
        # e.g. broadcast), so GC only once all world_size ranks have read it.
        if len(fetched) >= self.world_size:
            self._boards.pop(key, None)
            self._fetched.pop(key, None)
        return True, out

    def poll_one(self, key: str, rank: int, src_rank: int) -> Tuple[bool, Any]:
        """Point-to-point fetch: wait for src_rank's payload only (send/recv)."""
        board = self._boards.get(key)
        if board is None or src_rank not in board:
            return False, None
        payload = board.pop(src_rank)
        if not board:
            self._boards.pop(key, None)
        return True, payload

    def world(self) -> int:
        return self.world_size


def wait_poll(coordinator, key: str, rank: int, timeout_s: float, expected: Optional[int] = None):
    """Client-side poll loop against the coordinator actor handle."""
    from ... import get  # late import to avoid cycle

    deadline = time.monotonic() + timeout_s
    sleep = 0.0005
    while True:
        ready, out = get(coordinator.poll.remote(key, rank, expected))
        if ready:
            return out
        if time.monotonic() > deadline:
            raise TimeoutError(f"collective op {key!r} timed out after {timeout_s}s (rank {rank})")
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.01)


def wait_poll_one(coordinator, key: str, rank: int, src_rank: int, timeout_s: float):
    from ... import get

    deadline = time.monotonic() + timeout_s
    sleep = 0.0005
    while True:
        ready, out = get(coordinator.poll_one.remote(key, rank, src_rank))
        if ready:
            return out
        if time.monotonic() > deadline:
            raise TimeoutError(f"recv {key!r} from rank {src_rank} timed out (rank {rank})")
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.01)
