"""Group coordinator actor: rendezvous + host-plane collective metadata exchange.

Reference analogue: the named NCCLUniqueIDStore actor (python/ray/util/collective/util.py:9)
and the Rendezvous class (collective_group/nccl_collective_group.py:29). Here the coordinator
does triple duty: (1) rendezvous/bootstrap metadata (world size, jax.distributed coordinator
address for the XLA backend, the data-plane authkey for the ring path), (2) a poll-based
exchange board for SHM-backend collectives, (3) the group's failure authority: per-rank
membership (liveness), an abort poison flag, and an epoch counter.

The board is a CONTROL-plane surface: above the ring size threshold ranks post only tiny
metadata records (data-plane address + buffer key) and move tensor bytes rank-to-rank over
the data plane (ring.py); below it the tensor itself rides the board (small-tensor fast
path). `contribute` sizes every payload so tests (and operators) can assert that no
tensor-sized payload transits this single-threaded actor.

Failure model: when a member rank dies mid-op, core worker-death cleanup (core/node.py)
calls `abort(reason, failed_rank, epoch)`. From then on every `poll`/`poll_one` answers
with an abort verdict instead of "pending", so blocked members fail fast with
CollectiveAbortError within one client poll interval — not after the full op timeout.
Members re-initializing the group `join()` again; the first join of a new cycle advances
the epoch, clears the boards and the poison flag, and everything still tagged with the
old epoch is rejected (stale contributions dropped, stale polls answered with an abort
verdict) so a half-dead previous incarnation can never corrupt the new group's boards.

Clients never block inside coordinator methods (the actor is single-threaded FIFO); they
poll. Entries are garbage-collected once every participant has fetched them; entries of
ops that never completed (a timed-out or aborted op whose key is abandoned) fall to a TTL
sweep so a long-lived group does not accumulate dead boards.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util.hot_path import hot_path


def _payload_nbytes(payload: Any) -> int:
    """Approximate wire size of a board payload — exact for the cases that
    matter (numpy tensors, raw bytes); containers recurse one level deep
    because ring metadata is flat."""
    try:
        if payload is None or isinstance(payload, (bool, int, float)):
            return 8
        if isinstance(payload, (bytes, bytearray, memoryview, str)):
            return len(payload)
        nbytes = getattr(payload, "nbytes", None)
        if isinstance(nbytes, int):
            return nbytes
        if isinstance(payload, dict):
            return sum(_payload_nbytes(k) + _payload_nbytes(v)
                       for k, v in payload.items())
        if isinstance(payload, (list, tuple)):
            return sum(_payload_nbytes(v) for v in payload)
    # graftlint: allow[swallowed-exception] size probe of arbitrary payloads; the 64-byte floor covers opaque objects
    except Exception:
        pass
    return 64  # opaque object: count something


class GroupCoordinator:
    """Per-collective-group named actor. Name: `ray_tpu.collective.<group_name>`."""

    def __init__(self, world_size: int, name: str = "default"):
        self.world_size = world_size
        self.name = name
        # key -> {rank: payload}
        self._boards: Dict[str, Dict[int, Any]] = {}
        # key -> set of ranks that have fetched the completed board
        self._fetched: Dict[str, set] = {}
        # key -> creation time, for the abandoned-op TTL sweep
        self._board_born: Dict[str, float] = {}
        self._meta: Dict[str, Any] = {}
        # shared secret for the group's rank-to-rank data plane: members fetch
        # it once at group init and use it for their DataServer/DataClient
        # pair, so ring pulls are authenticated without any cluster-wide key
        # distribution (the coordinator IS the group's trust anchor).
        self._data_authkey = os.urandom(16)
        # -- failure authority state
        # The epoch starts at a per-incarnation nonce, not 0: a kill-and-
        # recreate of the coordinator under the same name (Train group
        # restart) must not let a delayed death notice scoped to the retired
        # incarnation's epoch match the fresh one and spuriously poison it —
        # every epoch comparison is equality-only, so any non-colliding start
        # value works.
        self._epoch = int.from_bytes(os.urandom(4), "little")
        # rank -> opaque member tag (worker id hex for actor members): the
        # group's per-rank liveness roster for the CURRENT epoch
        self._members: Dict[int, Any] = {}
        self._cycle_complete = False
        self._abort: Optional[Dict[str, Any]] = None
        # instrumentation: the board must carry metadata, not tensors, above
        # the ring threshold — these let tests assert exactly that.
        self._max_contrib_bytes = 0
        self._total_contrib_bytes = 0
        self._num_contribs = 0

    # -- metadata (rendezvous) ---------------------------------------------------------
    def set_meta(self, key: str, value: Any) -> None:
        self._meta[key] = value

    def get_meta(self, key: str) -> Any:
        return self._meta.get(key)

    def data_authkey(self) -> bytes:
        return self._data_authkey

    # -- membership / epochs -----------------------------------------------------------
    def join(self, rank: int, member: Any = None) -> int:
        """Declare membership; returns the epoch the caller belongs to.

        The first join after a completed cycle, after an abort, or by a rank
        already present in the current roster starts a NEW epoch: boards and
        the poison flag are cleared, and everything tagged with the old epoch
        is rejected from here on. Concurrent joins of the same incarnation all
        land in the same epoch (only the first one rolls it over)."""
        if self._cycle_complete or self._abort is not None or rank in self._members:
            self._epoch += 1
            self._members = {}
            self._boards.clear()
            self._fetched.clear()
            self._board_born.clear()
            self._abort = None
            self._cycle_complete = False
            try:
                from ray_tpu.util import telemetry

                telemetry.get_counter(
                    "collective_epoch_rollovers_total",
                    "collective group epoch rollovers (re-inits)",
                    tag_keys=("group",)).inc(1.0, tags={"group": self.name})
                telemetry.event("collective.epoch_rollover", "collective",
                                group=self.name, epoch=self._epoch)
            # graftlint: allow[swallowed-exception] telemetry emission is best-effort and must never take the data path down
            except Exception:
                pass  # telemetry must never fail a group re-init
        self._members[rank] = member
        if len(self._members) >= self.world_size:
            self._cycle_complete = True
        return self._epoch

    def leave(self, rank: int, epoch: int) -> None:
        """Retract a rank from the current roster (destroy_collective_group's
        one-way note, epoch-scoped like the head-registry retraction). Without
        this, a PARTIAL roster from a failed init survives the destroy, and
        the retry's joins land in it out of order — the first re-joiner gets
        stranded in the stale epoch when a later re-join rolls it over."""
        if epoch == self._epoch:
            self._members.pop(rank, None)

    def members(self) -> Dict[int, Any]:
        """Current-epoch roster: rank -> member tag (per-rank liveness view)."""
        return dict(self._members)

    def current_epoch(self) -> int:
        return self._epoch

    def abort(self, reason: str, failed_rank: Optional[int] = None,
              epoch: Optional[int] = None) -> bool:
        """Poison the group: every subsequent poll answers with this verdict.

        `epoch` scopes the abort: a late death notification for a rank of an
        already-retired incarnation must not poison the re-initialized group.
        Returns False when the abort was stale and ignored."""
        if epoch is not None and epoch != self._epoch:
            return False
        if self._abort is None:  # first verdict wins (first failure is the cause)
            self._abort = {"reason": str(reason), "failed_rank": failed_rank,
                           "epoch": self._epoch}
        return True

    def check_abort(self, epoch: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """The abort verdict for a caller participating at `epoch`, else None.
        A caller from a retired epoch gets a stale-epoch verdict even after
        the poison flag was cleared by a re-init."""
        if epoch is not None and epoch != self._epoch:
            return {"reason": f"group re-initialized (stale epoch {epoch}, "
                              f"current {self._epoch})",
                    "failed_rank": None, "epoch": self._epoch, "stale": True}
        return self._abort

    # -- exchange board ----------------------------------------------------------------
    def contribute(self, key: str, rank: int, payload: Any,
                   epoch: Optional[int] = None) -> None:
        if epoch is not None and epoch != self._epoch:
            return  # stale incarnation: must not corrupt the new group's board
        self._gc_abandoned()
        n = _payload_nbytes(payload)
        self._num_contribs += 1
        self._total_contrib_bytes += n
        if n > self._max_contrib_bytes:
            self._max_contrib_bytes = n
        if key not in self._boards:
            self._boards[key] = {}
            self._board_born[key] = time.monotonic()
        self._boards[key][rank] = payload

    def board_stats(self) -> Dict[str, int]:
        """Bytes that transited this actor's board (tensor bytes on the old
        path, metadata-only above the ring threshold on the new one)."""
        return {
            "max_contrib_bytes": self._max_contrib_bytes,
            "total_contrib_bytes": self._total_contrib_bytes,
            "num_contribs": self._num_contribs,
        }

    def board_keys(self) -> List[str]:
        """Live board keys (test/debug introspection: board-cleanup audits)."""
        return sorted(self._boards)

    def poll(self, key: str, rank: int, expected: Optional[int] = None,
             epoch: Optional[int] = None) -> Tuple[str, Any]:
        """Returns one of:
          ("ready", payload-list-in-rank-order)  — marks `rank` as fetched
          ("pending", arrived-rank-list)         — for debuggable timeouts
          ("abort", verdict-dict)                — group poisoned / stale epoch
        """
        verdict = self.check_abort(epoch)
        if verdict is not None:
            return "abort", verdict
        want = expected if expected is not None else self.world_size
        board = self._boards.get(key)
        if board is None or len(board) < want:
            return "pending", sorted(board) if board else []
        out = [board[r] for r in sorted(board)]
        fetched = self._fetched.setdefault(key, set())
        fetched.add(rank)
        # Every group member fetches the completed board (even ops with one contributor,
        # e.g. broadcast), so GC only once all world_size ranks have read it.
        if len(fetched) >= self.world_size:
            self._boards.pop(key, None)
            self._fetched.pop(key, None)
            self._board_born.pop(key, None)
        return "ready", out

    def poll_one(self, key: str, rank: int, src_rank: int,
                 epoch: Optional[int] = None) -> Tuple[str, Any]:
        """Point-to-point fetch: wait for src_rank's payload only (send/recv).
        Same status contract as poll()."""
        verdict = self.check_abort(epoch)
        if verdict is not None:
            return "abort", verdict
        board = self._boards.get(key)
        if board is None or src_rank not in board:
            return "pending", sorted(board) if board else []
        payload = board.pop(src_rank)
        if not board:
            self._boards.pop(key, None)
            self._board_born.pop(key, None)
        return "ready", payload

    def world(self) -> int:
        return self.world_size

    def _gc_abandoned(self) -> None:
        """Reap boards of ops that never completed (timed out / aborted and
        the key abandoned): without this a long-lived group accumulates one
        dead board per failed op. Epoch rollovers clear everything anyway;
        this covers within-epoch retries under fresh keys."""
        try:
            from ray_tpu.config import CONFIG

            ttl = max(60.0, 4 * CONFIG.collective_op_timeout_s)
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (ttl = 120.0) by design
        except Exception:
            ttl = 120.0
        now = time.monotonic()
        for key in [k for k, born in self._board_born.items() if now - born > ttl]:
            self._boards.pop(key, None)
            self._fetched.pop(key, None)
            self._board_born.pop(key, None)


def _abort_error(st, verdict: Dict[str, Any], key: str):
    from ray_tpu.core.exceptions import CollectiveAbortError

    err = CollectiveAbortError(
        getattr(st, "name", "?"),
        f"op {key!r} aborted: {verdict.get('reason', 'unknown')}",
        failed_rank=verdict.get("failed_rank"),
        epoch=verdict.get("epoch", getattr(st, "epoch", None)),
    )
    # stale-epoch verdicts are retryable (the group moved on without us);
    # init_collective_group re-joins on them instead of failing the member
    err.stale = bool(verdict.get("stale"))
    return err


def _coordinator_lost_error(st, key: str, e: BaseException):
    from ray_tpu.core.exceptions import CollectiveAbortError

    return CollectiveAbortError(
        getattr(st, "name", "?"),
        f"group coordinator unreachable during op {key!r}: {e}",
        epoch=getattr(st, "epoch", None), cause=e,
    )


@hot_path
def wait_poll(st, key: str, timeout_s: float, expected: Optional[int] = None):
    """Client-side poll loop against the group's coordinator actor.

    `st` is the caller's group state (coordinator handle, rank, name,
    world_size, epoch). Fails fast with CollectiveAbortError on an abort
    verdict or coordinator death; a genuine timeout names the group, world
    size, epoch, and the ranks that HAD arrived, so a stuck op is debuggable
    from the exception alone."""
    from ray_tpu.core.exceptions import ActorError
    from ray_tpu.util import fault_injection

    from ... import get  # late import to avoid cycle

    fault_injection.fail_point("collective.wait", key=key,
                               rank=getattr(st, "rank", None),
                               group=getattr(st, "name", None))
    deadline = time.monotonic() + timeout_s
    sleep = 0.0005
    epoch = getattr(st, "epoch", None)
    arrived: List[int] = []
    while True:
        try:
            status, out = get(st.coordinator.poll.remote(key, st.rank, expected, epoch))
        except (ActorError, ConnectionError, OSError) as e:
            raise _coordinator_lost_error(st, key, e) from e
        if status == "ready":
            return out
        if status == "abort":
            raise _abort_error(st, out, key)
        arrived = out
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"collective op {key!r} in group {getattr(st, 'name', '?')!r} "
                f"timed out after {timeout_s}s (rank {st.rank}, "
                f"world_size {getattr(st, 'world_size', '?')}, epoch {epoch}; "
                f"arrived ranks: {arrived})")
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.01)


@hot_path
def wait_poll_one(st, key: str, src_rank: int, timeout_s: float):
    """wait_poll for point-to-point recv: same fail-fast and timeout contract."""
    from ray_tpu.core.exceptions import ActorError
    from ray_tpu.util import fault_injection

    from ... import get

    fault_injection.fail_point("collective.wait", key=key,
                               rank=getattr(st, "rank", None),
                               group=getattr(st, "name", None))
    deadline = time.monotonic() + timeout_s
    sleep = 0.0005
    epoch = getattr(st, "epoch", None)
    while True:
        try:
            status, out = get(st.coordinator.poll_one.remote(key, st.rank, src_rank, epoch))
        except (ActorError, ConnectionError, OSError) as e:
            raise _coordinator_lost_error(st, key, e) from e
        if status == "ready":
            return out
        if status == "abort":
            raise _abort_error(st, out, key)
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"recv {key!r} from rank {src_rank} in group "
                f"{getattr(st, 'name', '?')!r} timed out after {timeout_s}s "
                f"(rank {st.rank}, world_size {getattr(st, 'world_size', '?')}, "
                f"epoch {epoch}; arrived ranks: {out})")
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.01)
