"""Collective communication API on ray_tpu actors.

Reference capability: python/ray/util/collective/collective.py — init_collective_group
(:150), create_collective_group (:187), allreduce (:295), barrier (:335), broadcast (:410),
allgather (:460), reducescatter (:509), send/recv (:568/:631). Same call shapes, TPU-native
backends (see types.py).

Design: the hot tensor path on TPU is NOT this API — it is XLA collectives compiled into
pjit programs (psum over ICI). This API covers what the reference uses NCCL/Gloo process
groups for *outside* compiled code: weight broadcast to env-runners, metric reduction,
rendezvous. The SHM backend exchanges tensors over the rank-to-rank data plane with the
coordinator actor carrying metadata only (ring.py; payloads under the ring threshold ride
the coordinator board directly); the XLA backend additionally bootstraps `jax.distributed`
across member processes so members can jointly build multi-host meshes.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from . import ring
from .coordinator import GroupCoordinator, wait_poll
from .types import Backend, Compression, ReduceOp

_NAMESPACE = "ray_tpu.collective"

logger = logging.getLogger("ray_tpu.collective")


def _op_timeout() -> float:
    from ray_tpu.config import CONFIG

    return CONFIG.collective_op_timeout_s


@dataclass
class _GroupState:
    name: str
    world_size: int
    rank: int
    backend: Backend
    coordinator: Any
    # ring-path knobs (ring.py): wire compression for large payloads and an
    # optional per-group override of the board/ring size threshold
    compression: Optional[str] = None
    ring_threshold: Optional[int] = None
    data_plane: Any = None  # lazy ring._Plane (server started on first use)
    seq: Dict[str, int] = field(default_factory=dict)
    # True only when EVERY member of the group joined one jax.distributed universe
    # (agreed collectively at bootstrap) — the gate for device-path collectives.
    xla_device_plane: bool = False
    # the coordinator epoch this member belongs to (assigned by join() at
    # init): every contribute/poll is tagged with it so stale members of a
    # destroyed-and-recreated group are rejected instead of corrupting boards
    epoch: int = 0

    def next_key(self, op: str, extra: str = "") -> str:
        # sequence per (op, extra), not per op: p2p send/recv counters must
        # advance per src->dst PAIR, or a rank talking to two peers desyncs
        # its key stream from each of them
        k = f"{op}:{extra}" if extra else op
        n = self.seq.get(k, 0)
        self.seq[k] = n + 1
        return f"{op}:{extra}:{n}" if extra else f"{op}:{n}"


_groups: Dict[str, _GroupState] = {}
_lock = threading.Lock()


def _coordinator_name(group_name: str) -> str:
    return f"coordinator.{group_name}"


def _get_or_create_coordinator(group_name: str, world_size: int, rank: int):
    """Rank 0 creates the group's detached coordinator; everyone else polls for
    the name. Deterministic creator > create-race: the loser of a name race
    pays an ActorDiedError round-trip on a doomed handle (and, worse, a worker
    spawn), so with W ranks racing, init cost scales with the race width."""
    import ray_tpu

    name = _coordinator_name(group_name)
    try:
        return ray_tpu.get_actor(name, namespace=_NAMESPACE)
    # graftlint: allow[swallowed-exception] named-actor probe: not-found falls through to coordinator creation
    except Exception:
        pass
    if rank == 0:
        coord_cls = ray_tpu.remote(GroupCoordinator)
        try:
            coord = coord_cls.options(
                name=name, namespace=_NAMESPACE, lifetime="detached", num_cpus=0
            ).remote(world_size, group_name)
            # Name collisions surface on the first method call, not at .remote() —
            # round-trip before trusting the handle (a stale detached coordinator
            # may still own the name).
            ray_tpu.get(coord.world.remote(), timeout=30)
            return coord
        # graftlint: allow[swallowed-exception] lost the creation race: adopt the coordinator the winning rank registered
        except Exception:
            return ray_tpu.get_actor(name, namespace=_NAMESPACE)
    # non-zero ranks: wait for rank 0's coordinator to register
    import time

    deadline = time.monotonic() + 2 * _op_timeout()
    while True:
        try:
            return ray_tpu.get_actor(name, namespace=_NAMESPACE)
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.02)


def init_collective_group(
    world_size: int,
    rank: int,
    backend: "Backend | str" = Backend.SHM,
    group_name: str = "default",
    compression: "Compression | str | None" = None,
    ring_threshold_bytes: Optional[int] = None,
) -> None:
    """Declare membership of the calling process in a collective group.

    Reference: collective.py:150. Must be called by every member (typically inside an
    actor method) before any collective op.

    compression: opt-in int8 wire compression for ring-path payloads (lossy;
    see types.Compression). ring_threshold_bytes: per-group override of
    CONFIG.collective_ring_threshold_bytes (payloads at/above it move
    peer-to-peer over the data plane; smaller ones ride the coordinator
    board). Both must be passed uniformly by every member.
    """
    backend = Backend.parse(backend)
    comp = Compression.parse(compression)
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    with _lock:
        if group_name in _groups:
            raise RuntimeError(f"collective group {group_name!r} already initialized here")
    import ray_tpu

    coord = _get_or_create_coordinator(group_name, world_size, rank)
    state = _GroupState(
        group_name, world_size, rank, backend, coord,
        compression=None if comp is Compression.NONE else comp.value,
        ring_threshold=ring_threshold_bytes,
    )
    # Join the coordinator's roster. The returned epoch tags every board
    # exchange of this incarnation; a destroy + re-init cycle advances it, so
    # stragglers of the old incarnation fail fast instead of poisoning the new
    # group's boards. The member tag (worker id) is the liveness hook core
    # worker-death cleanup keys abort propagation on.
    #
    # The join/barrier pair retries on a STALE-epoch abort: when a previous
    # init died half-joined, the retry's re-joins can arrive in an order where
    # a later join rolls the epoch over an earlier one — the stranded member
    # re-joins the fresh epoch instead of failing, so concurrent re-inits
    # converge regardless of join order.
    from ray_tpu.core.exceptions import CollectiveAbortError

    deadline = time.monotonic() + 2 * _op_timeout()
    try:
        while True:
            state.epoch = ray_tpu.get(
                coord.join.remote(rank, _member_tag()), timeout=2 * _op_timeout())
            # Tell the head which worker holds this rank: process death then
            # aborts the group within one poll interval instead of burning the
            # op timeout.
            _notify_head("collective_join", group_name, rank, state.epoch)
            try:
                if backend is Backend.XLA:
                    _bootstrap_xla(state)
                with _lock:
                    _groups[group_name] = state
                # Rendezvous barrier: nobody proceeds until all members declared.
                _barrier_impl(state, key=f"__init__:{group_name}")
                return
            except CollectiveAbortError as e:
                if not getattr(e, "stale", False) or time.monotonic() > deadline:
                    raise
    except BaseException:
        # a failed init must leave no half-registered group behind: the caller
        # can retry init_collective_group without hitting "already initialized"
        with _lock:
            _groups.pop(group_name, None)
        raise


def _member_tag() -> Optional[str]:
    """This process's worker id (None on the driver): the coordinator's
    per-rank liveness roster entry."""
    from ray_tpu.core import global_state

    return getattr(global_state.try_worker(), "worker_id_hex", None)


def _notify_head(kind: str, group_name: str, rank: int, epoch: int) -> None:
    """One-way membership note to the node service (worker processes only —
    the driver's memberships die with the cluster itself). Best-effort: a
    race with worker shutdown must not fail the collective op."""
    from ray_tpu.core import global_state

    w = global_state.try_worker()
    notify = getattr(w, "collective_notify", None)
    if notify is None:
        return
    try:
        notify(kind, group_name, rank, epoch)
    except Exception as e:
        # an unrecorded membership note means worker-death cleanup cannot
        # resolve this rank later — keep going (the op itself still works)
        # but say so, or the next abort investigation starts blind
        logger.warning("collective membership note %s for %s rank %s failed "
                       "(%r)", kind, group_name, rank, e)


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: "Backend | str" = Backend.SHM,
    group_name: str = "default",
    compression: "Compression | str | None" = None,
    ring_threshold_bytes: Optional[int] = None,
) -> None:
    """Driver-side declarative form (reference collective.py:187): makes each actor in
    `actors` call `init_collective_group` with its rank."""
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have equal length")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size - 1}")
    import ray_tpu

    b = str(Backend.parse(backend).value)
    comp = Compression.parse(compression)
    if comp is Compression.NONE and ring_threshold_bytes is None:
        # positional 4-arg call: compatible with actors that define their own
        # _ray_tpu_collective_init without the ring knobs
        refs = [
            actor._ray_tpu_collective_init.remote(world_size, rank, b, group_name)
            for actor, rank in zip(actors, ranks)
        ]
    else:
        refs = [
            actor._ray_tpu_collective_init.remote(
                world_size, rank, b, group_name, comp.value, ring_threshold_bytes)
            for actor, rank in zip(actors, ranks)
        ]
    ray_tpu.get(refs)


declare_collective_group = create_collective_group


class CollectiveActorMixin:
    """Mix into an actor class to make it addressable by create_collective_group()."""

    def _ray_tpu_collective_init(self, world_size: int, rank: int, backend: str,
                                 group_name: str, compression: Optional[str] = None,
                                 ring_threshold_bytes: Optional[int] = None) -> None:
        init_collective_group(world_size, rank, backend, group_name,
                              compression=compression,
                              ring_threshold_bytes=ring_threshold_bytes)


def destroy_collective_group(group_name: str = "default") -> None:
    """Idempotent and non-blocking: safe to call twice, from a finally block,
    or while the group is mid-abort — teardown is local state plus one one-way
    membership note; it never waits on peers or the coordinator."""
    with _lock:
        st = _groups.pop(group_name, None)
    if st is None:
        return  # already destroyed (double-destroy, destroy-during-abort)
    _notify_head("collective_leave", group_name, st.rank, st.epoch)
    # Epoch-scoped roster retraction on the coordinator itself (fire-and-
    # forget — destroy never blocks): without it, a PARTIAL roster from a
    # failed init survives the destroy, and a retry's joins landing in it out
    # of order strand the first re-joiner in the stale epoch.
    try:
        st.coordinator.leave.remote(st.rank, st.epoch)
    # graftlint: allow[swallowed-exception] best-effort board cleanup on destroy; TTL reaping is the backstop
    except Exception:
        pass  # coordinator already gone — nothing to retract
    # release the group's ring data plane (listener thread + port + pooled
    # sockets): planes are keyed by the group's coordinator-issued authkey, so
    # no other group can share one; callers destroy after their last
    # collective op, so no peer still pulls from us
    if st.data_plane is not None:
        ring.release_plane(st.data_plane)


def abort_collective_group(group_name: str = "default",
                           reason: str = "aborted by operator",
                           failed_rank: Optional[int] = None,
                           wait: bool = True) -> bool:
    """Poison a group's coordinator: every member's pending and future board
    waits fail fast with CollectiveAbortError instead of burning the op
    timeout. Core worker-death cleanup uses the same coordinator entry point;
    this is the operator/driver-side handle (e.g. a supervisor that decided a
    training run is wedged). Returns False when the coordinator is already
    gone — nothing left to poison.

    wait=False fires the poison one-way and returns as soon as the message is
    posted: failure paths that must not stall behind a wedged coordinator host
    (Backend.on_failure's contract) use it; True additionally confirms the
    verdict landed in the current epoch."""
    import ray_tpu

    try:
        coord = ray_tpu.get_actor(_coordinator_name(group_name), namespace=_NAMESPACE)
        ref = coord.abort.remote(reason, failed_rank)
        if not wait:
            return True
        return bool(ray_tpu.get(ref, timeout=_op_timeout()))
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
    except Exception:
        return False


def kill_coordinator(group_name: str = "default") -> None:
    """Driver-side teardown of a group's detached coordinator actor. Call after all
    members are done (e.g. worker-group shutdown) so the name can be reused with a
    different world size."""
    import ray_tpu

    try:
        coord = ray_tpu.get_actor(_coordinator_name(group_name), namespace=_NAMESPACE)
        ray_tpu.kill(coord)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass


def is_group_initialized(group_name: str = "default") -> bool:
    with _lock:
        return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _state(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _state(group_name).world_size


def _state(group_name: str) -> _GroupState:
    with _lock:
        st = _groups.get(group_name)
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this process; "
            "call init_collective_group() first"
        )
    return st


# -- ops -------------------------------------------------------------------------------
# Both the board fast path and the ring path reduce through ring.reduce_parts,
# so results are bit-exact across paths (compression off). Kept under the old
# name for callers that reached into the module.
_reduce = ring.reduce_parts


import contextlib


@contextlib.contextmanager
def _instrumented(op: str, st: _GroupState, tensor):
    """Per-op load signals: ops/aborts counters + per-op latency histogram
    (the `ray-tpu status` collective row), and — when telemetry is on — one
    timeline span per op; ring.py adds the phase sub-spans inside it."""
    from ray_tpu.core.exceptions import CollectiveAbortError
    from ray_tpu.util import telemetry

    # getattr, NOT np.asarray: asarray on an XLA-backend device array would
    # force a blocking device->host copy of the whole tensor per op just to
    # label a span; numpy and jax arrays both expose nbytes directly
    nbytes = int(getattr(tensor, "nbytes", 0) or 0) if tensor is not None else 0
    t0 = time.perf_counter()
    try:
        with telemetry.span(f"collective.{op}", "collective", group=st.name,
                            rank=st.rank, world=st.world_size, bytes=nbytes):
            yield
    except CollectiveAbortError as e:
        # the head counts one abort per poisoned group; this counts each
        # surviving rank's observation (rates how much work aborts interrupt)
        telemetry.get_counter(
            "collective_aborts_observed_total",
            "collective ops that failed with CollectiveAbortError",
            tag_keys=("group",)).inc(1.0, tags={"group": st.name})
        if telemetry.enabled():
            telemetry.event("collective.abort_observed", "collective",
                            group=st.name, epoch=e.epoch,
                            failed_rank=e.failed_rank, op=op, rank=st.rank)
        raise
    else:
        telemetry.get_counter(
            "collective_ops_total", "completed host-plane collective ops",
            tag_keys=("op",)).inc(1.0, tags={"op": op})
        telemetry.get_histogram(
            "collective_op_seconds", "host-plane collective op wall time",
            tag_keys=("op",)).observe(time.perf_counter() - t0, tags={"op": op})


def _to_host(tensor) -> np.ndarray:
    return np.asarray(tensor)


def _like(result: np.ndarray, tensor):
    """Return `result` in the same container type as `tensor`; mutate numpy in-place."""
    if isinstance(tensor, np.ndarray):
        tensor[...] = result
        return tensor
    mod = type(tensor).__module__
    if mod.startswith("jax"):
        import jax.numpy as jnp

        return jnp.asarray(result)
    return result


import functools


@functools.lru_cache(maxsize=None)
def _xla_reduce_program(world_size: int, op: ReduceOp, ndim: int):
    """(mesh, jitted-reducer) for a one-device-per-process mesh — cached so steady-state
    allreduce calls hit the jit cache instead of recompiling a cross-process program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = []
    for rank in range(world_size):
        d = next((d for d in jax.devices() if d.process_index == rank), None)
        if d is None:
            return None, None
        devs.append(d)
    mesh = Mesh(np.array(devs), ("rank",))
    fn = {
        ReduceOp.SUM: jnp.sum, ReduceOp.PRODUCT: jnp.prod,
        ReduceOp.MIN: jnp.min, ReduceOp.MAX: jnp.max,
    }[op]
    prog = jax.jit(
        lambda x: fn(x, axis=0),
        out_shardings=NamedSharding(mesh, PartitionSpec(*([None] * ndim))),
    )
    return mesh, prog


def _xla_device_allreduce(tensor, st: _GroupState, op: ReduceOp):
    """Device-path all-reduce for the XLA backend: a compiled reduction over a mesh
    with one device per member process (collectives ride ICI/DCN, not the host
    coordinator). Returns None when the group didn't uniformly join one
    jax.distributed universe (then the caller falls back to the shm plane) or when
    the dtype needs 64-bit (jax x64 is off; the shm plane preserves dtype).

    Reference capability: NCCL allreduce in python/ray/util/collective/collective.py:295;
    here the ring is XLA's, launched from one jitted program all members enter.
    """
    # Collectively-agreed at bootstrap: EVERY member joined the universe, or NOBODY
    # takes the device path — a per-call jax.process_count() probe could split the
    # group across planes and deadlock the compiled reduction.
    if not st.xla_device_plane:
        return None
    t = np.asarray(tensor)
    if t.dtype.itemsize >= 8:  # float64/int64 would silently downcast under no-x64
        return None
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh, prog = _xla_reduce_program(st.world_size, op, t.ndim)
    if mesh is None:
        return None
    stacked = NamedSharding(mesh, PartitionSpec("rank", *([None] * t.ndim)))
    local = jax.device_put(t[None], mesh.devices.flat[st.rank])
    garr = jax.make_array_from_single_device_arrays(
        (st.world_size,) + t.shape, stacked, [local])
    try:
        return np.asarray(jax.device_get(prog(garr)))
    except Exception as e:
        # Narrow fallback: only a backend-capability rejection ("Multiprocess
        # computations aren't implemented on the CPU backend") is demoted to
        # the shm plane — that launch check fails identically on every member,
        # so all ranks demote together and stay on one plane. Any other
        # runtime error (rank-local OOM, preemption) must surface: silently
        # falling back on one rank would strand the peers inside the compiled
        # reduction.
        msg = str(e).lower()
        if "multiprocess" in msg and "implemented" in msg:
            st.xla_device_plane = False
            return None
        raise


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    st = _state(group_name)
    with _instrumented("allreduce", st, tensor):
        if st.backend is Backend.XLA:
            out = _xla_device_allreduce(tensor, st, op)
            if out is not None:
                return _like(out, tensor)
        return _like(ring.allreduce(st, _to_host(tensor), op), tensor)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    st = _state(group_name)
    with _instrumented("reduce", st, tensor):
        out = ring.reduce(st, _to_host(tensor), dst_rank, op)
    if st.rank == dst_rank and out is not None:
        return _like(out, tensor)
    return tensor


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    st = _state(group_name)
    with _instrumented("broadcast", st, tensor):
        return _like(np.asarray(ring.broadcast(st, _to_host(tensor), src_rank)), tensor)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """Returns the list of every rank's tensor (rank order). The reference fills a
    caller-provided tensor_list (torch idiom); returning is the functional idiom here."""
    st = _state(group_name)
    with _instrumented("allgather", st, tensor):
        return ring.allgather(st, _to_host(tensor))


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    """Reduce across ranks, then scatter equal chunks along axis 0; returns this rank's chunk."""
    st = _state(group_name)
    with _instrumented("reducescatter", st, tensor):
        return ring.reducescatter(st, _to_host(tensor), op)


def barrier(group_name: str = "default") -> None:
    st = _state(group_name)
    with _instrumented("barrier", st, None):
        _barrier_impl(st)


def _barrier_impl(st: _GroupState, key: Optional[str] = None) -> None:
    key = key or st.next_key("barrier")
    st.coordinator.contribute.remote(key, st.rank, None, st.epoch)
    wait_poll(st, key, timeout_s=2 * _op_timeout())


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    st = _state(group_name)
    ring.send(st, _to_host(tensor), dst_rank)


def recv(tensor, src_rank: int, group_name: str = "default"):
    st = _state(group_name)
    return _like(np.asarray(ring.recv(st, src_rank)), tensor)


# -- XLA backend bootstrap -------------------------------------------------------------
def _jax_distributed_initialized() -> bool:
    """jax.distributed.is_initialized() exists only in some jax versions
    (absent in 0.4.37); fall back to the runtime state's client handle."""
    import jax

    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
    except Exception:
        return False


def _bootstrap_xla(st: _GroupState) -> None:
    """Bootstrap a jax.distributed universe across group members (multi-host TPU).

    Rank 0 publishes a coordinator address; all members call
    `jax.distributed.initialize(addr, world, rank)`. After this, members can build a global
    Mesh over all pod devices and run pjit programs whose collectives ride ICI/DCN — that
    compiled path IS the tensor plane (reference's NCCL ring analogue).

    On a single process-universe (world_size == 1) or when jax.distributed is already
    initialized, this is a no-op.
    """
    if st.world_size <= 1:
        return
    import jax

    import ray_tpu

    # Probe WITHOUT touching the backend: jax.process_count() would itself initialize
    # XLA, after which jax.distributed.initialize() refuses to run.
    if not _jax_distributed_initialized():  # else already bootstrapped (JaxBackend)
        if st.rank == 0:
            import socket

            sock = socket.socket()
            sock.bind(("", 0))
            port = sock.getsockname()[1]
            sock.close()
            addr = f"{socket.gethostbyname(socket.gethostname())}:{port}"
            ray_tpu.get(st.coordinator.set_meta.remote("xla_coordinator", addr))
        else:
            import time

            deadline = time.monotonic() + 60
            addr = None
            while addr is None:
                addr = ray_tpu.get(st.coordinator.get_meta.remote("xla_coordinator"))
                if addr is None:
                    if time.monotonic() > deadline:
                        raise TimeoutError("xla backend rendezvous timed out")
                    time.sleep(0.05)
        try:
            jax.distributed.initialize(
                coordinator_address=addr, num_processes=st.world_size, process_id=st.rank
            )
        except RuntimeError:
            # Single shared runtime (e.g. all members are threads of one process in
            # tests, or distributed already initialized by the launcher) — collectives
            # still work via the shm plane; compiled-path meshes use local devices.
            pass

    # Agree on the device plane COLLECTIVELY: every member reports whether it joined a
    # universe whose size matches the group; all must agree or nobody uses the device
    # path (a split would deadlock the compiled reduction against the shm plane).
    joined = _jax_distributed_initialized() and jax.process_count() == st.world_size
    key = f"__xla_plane__:{st.name}"
    st.coordinator.contribute.remote(key, st.rank, bool(joined), st.epoch)
    flags = wait_poll(st, key, timeout_s=2 * _op_timeout())
    st.xla_device_plane = all(bool(f) for f in flags)
