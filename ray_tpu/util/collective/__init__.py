"""ray_tpu.util.collective — collective communication on actors.

Reference capability: python/ray/util/collective/. See collective.py module docstring for
the TPU-native backend design.
"""
from ray_tpu.core.exceptions import CollectiveAbortError  # noqa: F401

from .collective import (  # noqa: F401
    CollectiveActorMixin,
    abort_collective_group,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    declare_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    kill_coordinator,
    recv,
    reduce,
    reducescatter,
    send,
)
from .types import Backend, Compression, ReduceOp  # noqa: F401
