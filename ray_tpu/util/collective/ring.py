"""Chunked peer-to-peer collectives over the data plane (the "ring" path).

The original SHM-backend collectives shipped every rank's full tensor through
the single-threaded GroupCoordinator actor, and every rank then fetched all
world_size payloads back — O(W²·bytes) through one Python process, whole
tensors, with poll sleeps in between. Here the coordinator's board carries only
tiny metadata (data-plane addresses + buffer keys, see coordinator.py); tensor
bytes move rank-to-rank through a per-rank DataServer/DataClient pair
(core/data_plane.py — the same chunked, admission-controlled transport the
cross-host object plane uses), in transfer_chunk_bytes-sized slices, so the
bytes through any single process drop to O(W·bytes/W) = O(bytes) and the
transfer of part k+1 overlaps the reduce of part k.

Algorithms (W = world_size, N = payload bytes):

  allreduce      ring reduce-scatter + allgather. Rank r owns flat chunk r:
                 it pulls the peers' slices of that chunk concurrently, with
                 start order staggered ring-wise (rank r starts at peer r+1,
                 r+2, ... — biasing load away from any single server) and
                 reduces them IN RANK ORDER as they
                 stream in; then every rank pulls each reduced chunk straight
                 from its owner. Per-rank traffic: 2·N·(W-1)/W in and out.
  reduce         dst pulls every peer's payload (staggered), rank-order reduce.
  broadcast      binomial tree over the data plane: each non-source rank pulls
                 from its tree parent chunk-by-chunk and republishes every
                 chunk as it lands (store-and-forward per CHUNK, not per
                 tensor), so deep subtrees stream concurrently.
  allgather      every rank publishes its payload; peers pull directly from
                 the owner in staggered ring order.
  reducescatter  each rank pulls only its axis-0 slice from every peer and
                 reduces in rank order.
  send/recv      the receiver pulls straight from the sender.

Rank-order reduction (not hop-order accumulation) is deliberate: it makes the
peer-to-peer path bit-exact with the coordinator-board path — both funnel
through reduce_parts() over rank-ordered parts — which a hop-accumulating ring
cannot guarantee for floating-point SUM/PRODUCT. Per-rank byte and FLOP totals
are identical to the textbook accumulating ring; only the association order of
the reduction differs.

Payloads below CONFIG.collective_ring_threshold_bytes keep the coordinator
board as a fast path: one actor round-trip beats peer rendezvous for
control-plane-sized tensors (a barrier flag, a scalar metric).

Opt-in wire compression (init_collective_group(..., compression="int8")):
floating-point payloads on the ring path are blockwise-symmetric-int8
quantized before publishing (ops/quant.py quantize_np — the same scheme the
serving stack uses for weights; EQuARX-style compressed all-reduce, arxiv
2506.17615): ~4x fewer wire bytes for float32 at ~1% error per quantization
stage (allreduce has two stages: inputs, then reduced chunks). Off by default;
integer/bool payloads always travel raw.
"""
from __future__ import annotations

import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util import telemetry
from ray_tpu.util.hot_path import hot_path

from .coordinator import wait_poll, wait_poll_one
from .types import ReduceOp

# board payload marker: ranks post (RING_META, {addr, dtype, shape, enc, ...})
# instead of the tensor when the payload takes the ring path.
RING_META = "__ring_meta__"

_QMAGIC = b"RQ1\0"
_QBLOCK = 4096  # elements per int8 scale block (~0.1% scale overhead at f32)


def _op_timeout() -> float:
    from ray_tpu.config import CONFIG

    return CONFIG.collective_op_timeout_s


def _chunk_bytes() -> int:
    from ray_tpu.config import CONFIG

    return max(1, CONFIG.transfer_chunk_bytes)


def _threshold(st) -> int:
    t = getattr(st, "ring_threshold", None)
    if t is not None:
        return t
    from ray_tpu.config import CONFIG

    return CONFIG.collective_ring_threshold_bytes


# -- reduction kernels (shared by the board and ring paths: bit-exact) -----------------
def accumulate(out: np.ndarray, a: np.ndarray, op: ReduceOp) -> None:
    if op is ReduceOp.SUM:
        out += a
    elif op is ReduceOp.PRODUCT:
        out *= a
    elif op is ReduceOp.MIN:
        np.minimum(out, a, out=out)
    elif op is ReduceOp.MAX:
        np.maximum(out, a, out=out)


def reduce_parts(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    """Reduce rank-ordered parts; the ONE reduction both paths share."""
    out = np.asarray(arrays[0]).copy()
    for a in arrays[1:]:
        accumulate(out, np.asarray(a), op)
    return out


# -- published-buffer store ------------------------------------------------------------
class _Buf:
    __slots__ = ("data", "total", "avail", "exp", "done", "born")

    def __init__(self, data, total, avail, exp):
        self.data = data
        self.total = total
        self.avail = avail
        self.exp = exp  # expected bytes read by peers; 0 = TTL-GC only
        self.done = 0
        self.born = time.monotonic()


class _BufStore:
    """Keyed raw buffers a rank serves to its peers.

    Readers block until the requested byte range is published — that blocking
    read IS the ring's step synchronization (no second coordinator round-trip
    for reduced chunks or tree relays). Buffers auto-retract once peers have
    read the expected number of bytes; a TTL sweep reaps anything a dead peer
    never finished reading.
    """

    def __init__(self):
        self._bufs: Dict[str, _Buf] = {}
        self._cond = threading.Condition()

    def publish(self, key: str, data, expected_read_bytes: int) -> None:
        """Publish a complete buffer (bytes/bytearray/memoryview)."""
        with self._cond:
            self._gc_locked()
            self._bufs[key] = _Buf(data, len(data), len(data), expected_read_bytes)
            self._cond.notify_all()

    def publish_stream(self, key: str, buf: bytearray, expected_read_bytes: int) -> None:
        """Publish an incrementally-filled buffer: the writer owns `buf`,
        fills it front-to-back, and calls advance() as ranges land (chunked
        tree relay). Readers of a not-yet-available range block."""
        with self._cond:
            self._gc_locked()
            self._bufs[key] = _Buf(buf, len(buf), 0, expected_read_bytes)
            self._cond.notify_all()

    def advance(self, key: str, avail: int) -> None:
        with self._cond:
            b = self._bufs.get(key)
            if b is not None and avail > b.avail:
                b.avail = avail
                self._cond.notify_all()

    def read(self, key: str, offset: int, length: int, timeout: float):
        """Read [offset, offset+length); length < 0 = the whole buffer.
        Blocks until the range is available (publication IS the sync).
        Returns a memoryview of the published buffer — zero-copy to serve: a
        range at or below `avail` is never rewritten (stream writers only
        append), and retraction just drops the store's reference, which the
        view outlives."""
        deadline = time.monotonic() + timeout
        with self._cond:
            # sweep here too: publish() alone can't reap a failed op's buffers
            # in a process that stops publishing (tensor-sized pins otherwise
            # survive until the next collective, maybe forever)
            self._gc_locked()
            while True:
                b = self._bufs.get(key)
                if b is not None:
                    if length < 0:
                        if b.avail >= b.total:
                            return self._take_locked(key, b, 0, b.total)
                    else:
                        if offset + length > b.total:
                            raise ValueError(
                                f"read past end of {key!r}: [{offset}, {offset + length}) "
                                f"of {b.total}")
                        if b.avail >= offset + length:
                            return self._take_locked(key, b, offset, length)
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"collective buffer {key!r} not published within {timeout}s")
                self._cond.wait(min(left, 1.0))

    def _take_locked(self, key: str, b: _Buf, offset: int, length: int):
        out = memoryview(b.data)[offset:offset + length]
        b.done += length
        if b.exp and b.done >= b.exp:
            self._bufs.pop(key, None)
        return out

    def retract(self, key: str) -> None:
        """Explicitly drop a published buffer. Publishers that announce with
        expected_read_bytes=0 (consumer count unknown up front — e.g. paged
        P/D KV exports re-read under retry) own their buffer's lifetime and
        must retract it; the TTL sweep is only the dead-publisher backstop."""
        with self._cond:
            self._bufs.pop(key, None)
            self._cond.notify_all()

    def _gc_locked(self) -> None:
        ttl = 4 * _op_timeout()
        now = time.monotonic()
        for key in [k for k, b in self._bufs.items() if now - b.born > ttl]:
            self._bufs.pop(key, None)


# -- per-group data plane --------------------------------------------------------------
def _local_ip() -> str:
    """The address peers dial for ring pulls — same resolution (including the
    RAY_TPU_NODE_IP operator override) as the device transfer plane, so both
    data planes advertise the same fabric interface."""
    from ray_tpu.core.device_plane import _node_ip

    return _node_ip()


class _Plane:
    """One rank's slice of the collective data plane: a DataServer serving its
    published buffers + a DataClient pulling from peers. Auth rides the
    group's coordinator-issued key, so only group members can pull."""

    def __init__(self, authkey: bytes, min_streams: int = 0):
        from ray_tpu.config import CONFIG
        from ray_tpu.core.data_plane import DataClient, DataServer

        self.authkey = authkey
        self.store = _BufStore()
        # sized to the group: at world W a server can hold W-1 blocked gather
        # readers AND W-1 reduce-scatter pulls at once — a fixed cap below
        # 2(W-1) would let blocked readers starve the pulls that unblock them
        self.server = DataServer(
            authkey, self._read,
            max_streams=max(CONFIG.collective_server_streams, min_streams))
        self.client = DataClient(authkey, stats_path="collective")
        self.addr: Tuple[str, int] = (_local_ip(), self.server.port)

    def _read(self, loc: Tuple) -> Tuple[bytes, bool]:
        if not (isinstance(loc, tuple) and len(loc) in (4, 5) and loc[0] == "cbuf"):
            raise ValueError(f"bad collective pull location {loc!r}")
        _, key, offset, length = loc[:4]
        # 5-tuple = bounded probe: wait at most loc[4] for the range, then
        # answer "not published yet" (empty frame) instead of erroring — the
        # store took no bytes, so the caller re-asks without double-counting
        # toward exp-based retraction. Tree-relay children use this so a
        # stalled upstream costs them one abort poll interval per probe, not
        # the full op timeout pinned inside a single pull.
        timeout = float(loc[4]) if len(loc) == 5 else _op_timeout()
        try:
            return self.store.read(key, int(offset), int(length), timeout), False
        except TimeoutError:
            if len(loc) == 5 and int(length) > 0:
                return b"", False
            raise

    def publish(self, key: str, data, expected_read_bytes: int = 0) -> None:
        """Publish a buffer for peers to pull. exp=0 buffers live until
        retract() (or the TTL backstop) — used by the paged P/D KV handoff,
        whose consumer may legitimately re-pull ranges on retry."""
        self.store.publish(key, data, expected_read_bytes)

    def retract(self, key: str) -> None:
        self.store.retract(key)

    def pull(self, addr, key: str, offset: int, length: int,
             timeout: Optional[float] = None) -> Optional[bytes]:
        """Pull [offset, offset+length) from a peer. With `timeout` set, the
        server waits at most that long for the range and this returns None if
        it wasn't published yet (bounded probe, see _read)."""
        if length == 0:
            return b""
        loc = ("cbuf", key, int(offset), int(length))
        if timeout is not None:
            loc += (float(timeout),)
        # retry=False: _BufStore reads count toward exp-based retraction, so a
        # replayed range would double-count and retract the buffer early
        data, _ = self.client.pull((addr[0], int(addr[1])), loc, retry=False)
        if timeout is not None and length > 0 and len(data) == 0:
            return None
        if length > 0 and len(data) != length:
            raise OSError(f"short collective pull of {key!r} from {addr}: "
                          f"{len(data)} != {length}")
        return data

    def pull_all(self, addr, key: str) -> bytes:
        data, _ = self.client.pull((addr[0], int(addr[1])), ("cbuf", key, 0, -1),
                                   retry=False)
        return data

    def pull_into(self, addr, key: str, offset: int, length: int,
                  out: memoryview, timeout: Optional[float] = None) -> Optional[int]:
        """Pull [offset, offset+length) from a peer straight into `out` (a
        writable memoryview of at least `length` bytes): chunk frames land via
        recv-into with no intermediate bytes object. Returns the byte count,
        or None on a bounded-probe miss (see _read) — nothing written then."""
        if length == 0:
            return 0
        got: Dict[str, int] = {}

        def sink(total: int, _is_err: bool) -> memoryview:
            got["n"] = total
            return out[:total]

        loc = ("cbuf", key, int(offset), int(length))
        if timeout is not None:
            loc += (float(timeout),)
        # retry=False: _BufStore reads count toward exp-based retraction, so a
        # replayed range would double-count and retract the buffer early
        self.client.pull((addr[0], int(addr[1])), loc, retry=False, into=sink)
        n = got.get("n", 0)
        if timeout is not None and n == 0:
            return None
        if n != length:
            raise OSError(f"short collective pull of {key!r} from {addr}: "
                          f"{n} != {length}")
        return n

    def pull_range(self, addr, key: str, offset: int, length: int, out=None):
        """Pull [offset, offset+length) in transfer_chunk_bytes slices so the
        caller can overlap downstream compute with the remaining transfer and
        no single frame materializes more than one chunk. Fills `out`
        (buffer-protocol writable, e.g. the destination ndarray's uint8 view)
        or returns a bytearray; either way every chunk recv's directly into
        the final buffer."""
        buf = out if out is not None else bytearray(length)
        mv = memoryview(buf)
        if mv.format != "B" or not mv.c_contiguous:
            mv = mv.cast("B")
        step = _chunk_bytes()
        pos = 0
        while pos < length:
            ln = min(step, length - pos)
            self.pull_into(addr, key, offset + pos, ln, mv[pos:pos + ln])
            pos += ln
        return buf


_planes: Dict[bytes, _Plane] = {}
_planes_lock = threading.Lock()


def get_plane(authkey: bytes, min_streams: int = 0) -> _Plane:
    with _planes_lock:
        plane = _planes.get(authkey)
        if plane is None:
            plane = _Plane(authkey, min_streams)
            _planes[authkey] = plane
        return plane


def release_plane(plane: _Plane) -> None:
    """Tear down a group's data plane (listener thread, pooled connections).
    Called by destroy_collective_group once no local group shares the plane —
    long-lived processes that cycle through many group names must not
    accumulate one bound port + server thread per retired group."""
    with _planes_lock:
        _planes.pop(plane.authkey, None)
    try:
        plane.server.close()
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass
    try:
        plane.client.close()
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass


def _ensure_plane(st) -> _Plane:
    plane = getattr(st, "data_plane", None)
    if plane is None:
        import ray_tpu

        key = ray_tpu.get(st.coordinator.data_authkey.remote(),
                          timeout=_op_timeout())
        plane = get_plane(bytes(key), min_streams=2 * (st.world_size - 1) + 4)
        st.data_plane = plane
    return plane


# -- wire compression ------------------------------------------------------------------
def _enc_for(st, arr: np.ndarray) -> str:
    comp = getattr(st, "compression", None)
    comp = getattr(comp, "value", comp)  # Compression enum -> str
    if comp == "int8" and arr.dtype.kind == "f" and arr.size:
        return "int8"
    return "raw"


def _compress(flat: np.ndarray) -> bytes:
    from ray_tpu.ops.quant import quantize_np

    q, scales = quantize_np(flat, block_elems=_QBLOCK)
    return b"".join([
        _QMAGIC, struct.pack("<IQ", _QBLOCK, flat.size),
        scales.tobytes(), q.tobytes(),
    ])


def _decompress(blob: bytes, dtype) -> np.ndarray:
    if blob[:4] != _QMAGIC:
        raise OSError("corrupt compressed collective payload")
    block, n = struct.unpack_from("<IQ", blob, 4)
    nblocks = -(-n // block) if n else 0
    off = 4 + 12
    scales = np.frombuffer(blob, np.float32, nblocks, off)
    q = np.frombuffer(blob, np.int8, n, off + 4 * nblocks)
    from ray_tpu.ops.quant import dequant_np

    return dequant_np(q, scales, block, dtype)


# -- abort fail-fast -------------------------------------------------------------------
class _AbortCheck:
    """Throttled abort probe for the ring path's data-plane waits.

    Board waits learn about an abort through poll() itself; the data-plane
    phases (stream reduce, gathers, tree relay) block on local conditions and
    peer sockets instead, so they consult the coordinator's poison flag at
    most once per CONFIG.collective_abort_poll_interval_s and raise
    CollectiveAbortError the moment a verdict lands — a dead peer costs one
    poll interval, not the full op timeout."""

    def __init__(self, st):
        from ray_tpu.config import CONFIG

        self.st = st
        self.interval = max(0.05, CONFIG.collective_abort_poll_interval_s)
        self._last = time.monotonic()

    @hot_path
    def check(self, force: bool = False, cause: Optional[BaseException] = None) -> None:
        """Raise CollectiveAbortError if the group is poisoned (or the
        coordinator itself died). `force` skips the throttle — used when a
        peer pull already failed, so the abort verdict (the disease) outranks
        the socket error (the symptom)."""
        now = time.monotonic()
        if not force and now - self._last < self.interval:
            return
        self._last = now
        from ray_tpu.core.exceptions import ActorError, CollectiveAbortError

        from ... import get

        epoch = getattr(self.st, "epoch", None)
        try:
            verdict = get(self.st.coordinator.check_abort.remote(epoch))
        except (ActorError, ConnectionError, OSError) as e:
            raise CollectiveAbortError(
                self.st.name, f"group coordinator unreachable: {e}",
                epoch=epoch, cause=e) from e
        if verdict is not None:
            raise CollectiveAbortError(
                self.st.name, verdict.get("reason", "aborted"),
                failed_rank=verdict.get("failed_rank"),
                epoch=verdict.get("epoch", epoch), cause=cause)


# -- board exchange helpers ------------------------------------------------------------
def _exchange(st, key: str, payload, expected: Optional[int] = None) -> List[Any]:
    st.coordinator.contribute.remote(key, st.rank, payload,
                                     getattr(st, "epoch", None))
    return wait_poll(st, key, timeout_s=_op_timeout(), expected=expected)


def _is_meta(entry) -> bool:
    return isinstance(entry, tuple) and len(entry) == 2 and entry[0] == RING_META


def _board_tensors(entries: List[Any], key: str) -> List[Any]:
    if any(_is_meta(e) for e in entries):
        raise RuntimeError(
            f"collective {key!r}: some ranks took the ring path and some the "
            "board path — member payload sizes must agree for this op")
    return entries


def _ring_metas(entries: List[Any], key: str,
                same_shape: Optional[np.ndarray] = None) -> List[Dict]:
    metas = []
    for rank, e in enumerate(entries):
        if not _is_meta(e):
            raise RuntimeError(
                f"collective {key!r}: rank {rank} took the board path while "
                "others took the ring path — member payload sizes must agree")
        metas.append(e[1])
    if same_shape is not None:
        want = (same_shape.dtype.str, tuple(same_shape.shape))
        for rank, m in enumerate(metas):
            if (m["dtype"], tuple(m["shape"])) != want:
                raise RuntimeError(
                    f"collective {key!r}: rank {rank} payload "
                    f"{m['dtype']}{tuple(m['shape'])} != local {want}")
    return metas


# -- shared op plumbing ----------------------------------------------------------------
def _flat(arr: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(arr).reshape(-1)


def _ring_capable(arr: np.ndarray) -> bool:
    """Raw-wire encodable: the dtype must round-trip through dtype.str.
    Exotic dtypes (ml_dtypes bfloat16/float8 stringify as raw void '<V2',
    object/structured dtypes) lose their semantics on a frombuffer rebuild —
    they keep the pickling board path at any size. The check is a pure
    function of dtype, so symmetric ops still agree on the path."""
    return arr.dtype.kind in "biufc" and np.dtype(arr.dtype.str) == arr.dtype


def _chunk_bounds(n: int, w: int) -> List[Tuple[int, int]]:
    base, rem = divmod(n, w)
    out, start = [], 0
    for i in range(w):
        ln = base + (1 if i < rem else 0)
        out.append((start, start + ln))
        start += ln
    return out


def _run_threads(fns, deadline: float, what: str, st=None) -> None:
    errs: List[BaseException] = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — propagated below
            errs.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,), daemon=True,
                                name=f"ring-par-{i}")
               for i, fn in enumerate(fns)]
    for t in threads:
        t.start()
    abort = _AbortCheck(st) if st is not None else None
    for t in threads:
        while t.is_alive():
            left = deadline - time.monotonic()
            if left <= 0:
                break
            if abort is not None:
                abort.check()  # raises: puller threads are daemons, safe to abandon
            t.join(min(left + 0.1, abort.interval if abort is not None else 1.0))
    if any(t.is_alive() for t in threads):
        raise TimeoutError(f"{what} timed out after {_op_timeout()}s")
    if errs:
        if abort is not None:
            # a failed peer pull may be the SYMPTOM of a rank death: prefer
            # the typed abort verdict when one is pending
            abort.check(force=True, cause=errs[0])
        raise errs[0]


def _staggered(rank: int, w: int) -> List[int]:
    """Peer start order (r+1, r+2, ..., r-1): pulls run concurrently, but the
    ring-staggered launch order biases the first wave so no single server is
    the initial target of every rank (server slots are sized for the
    worst-case 2(W-1) concurrent streams regardless; see _Plane)."""
    return [(rank + s) % w for s in range(1, w)]


def _ordered_stream_reduce(st, op, parts_src, my_part: np.ndarray,
                           deadline: float, what: str) -> np.ndarray:
    """Pull peer parts concurrently (staggered ring schedule) and reduce them
    in RANK order as they land: the reduce of part k overlaps the transfer of
    part k+1, and the association order matches the board path exactly.

    parts_src: callable(peer_rank) -> np.ndarray (runs on a puller thread).
    """
    w, r = st.world_size, st.rank
    slots: List[Optional[np.ndarray]] = [None] * w
    slots[r] = my_part
    cond = threading.Condition()
    errs: List[BaseException] = []

    def fetch(i):
        try:
            part = parts_src(i)
            with cond:
                slots[i] = part
                cond.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaced on the op thread
            with cond:
                errs.append(e)
                cond.notify_all()

    threads = [threading.Thread(target=fetch, args=(i,), daemon=True,
                                name=f"ring-fetch-{i}")
               for i in _staggered(r, w)]
    for t in threads:
        t.start()
    abort = _AbortCheck(st)
    acc: Optional[np.ndarray] = None
    for i in range(w):
        # The abort probe is a blocking coordinator RPC: it must run OUTSIDE
        # the parts lock, or every probe stalls puller threads trying to
        # deposit finished chunks (cond.wait already drops the lock; the RPC
        # would hold it for a control-plane round-trip per poll interval).
        part = err = None
        while part is None and err is None:
            with cond:
                if errs:
                    err = errs[0]
                elif slots[i] is not None:
                    part = slots[i]
                    slots[i] = None  # release as we go: peak extra mem < one input
                else:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(
                            f"{what}: rank {r} timed out waiting for rank {i}'s part")
                    cond.wait(min(left, abort.interval))
            if part is None:
                # fail fast on a dead peer (pullers are daemons, safe to abandon)
                abort.check(force=(err is not None), cause=err)
                if err is not None:
                    raise err
        if i == 0:
            acc = np.asarray(part).copy()
        else:
            accumulate(acc, np.asarray(part), op)
    return acc


def _meta(st, plane: _Plane, flat: np.ndarray, shape, enc: str, **extra) -> Tuple:
    m = {"addr": plane.addr, "dtype": flat.dtype.str, "shape": tuple(shape),
         "enc": enc}
    m.update(extra)
    return (RING_META, m)


def _pull_payload(plane: _Plane, meta: Dict, key: str,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
    """Fetch a peer's whole published payload described by its board meta."""
    dtype = np.dtype(meta["dtype"])
    shape = tuple(meta["shape"])
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if meta["enc"] == "int8":
        flat = _decompress(plane.pull_all(meta["addr"], key), dtype)
    else:
        nbytes = n * dtype.itemsize
        if nbytes == 0:
            flat = np.empty(0, dtype)
        else:
            # frombuffer over the pulled bytearray: writable (parity with the
            # board path's unpickled arrays) and no extra whole-payload copy
            flat = np.frombuffer(
                plane.pull_range(meta["addr"], key, 0, nbytes), dtype)
    arr = flat.reshape(shape)
    if out is not None:
        out[...] = arr
        return out
    return arr


# -- collective ops --------------------------------------------------------------------
def allreduce(st, tensor, op: ReduceOp) -> np.ndarray:
    arr = np.asarray(tensor)
    if st.world_size == 1:
        # purely local: a board round-trip would pickle the tensor through the
        # coordinator twice just to produce a copy
        return reduce_parts([arr], op)
    flat = _flat(arr)
    key = st.next_key("allreduce")
    if flat.nbytes < _threshold(st) or not _ring_capable(flat):
        return reduce_parts(_board_tensors(_exchange(st, key, arr), key), op)
    plane = _ensure_plane(st)
    w, r = st.world_size, st.rank
    item = flat.dtype.itemsize
    bounds = _chunk_bounds(flat.size, w)
    b0, b1 = bounds[r]
    nchunk = b1 - b0
    enc = _enc_for(st, flat)
    if enc == "int8":
        # per-chunk blobs: chunk i is read whole by rank i only
        for i, (c0, c1) in enumerate(bounds):
            if i == r or c1 == c0:
                continue
            blob = _compress(flat[c0:c1])
            plane.store.publish(f"{key}:in{i}", blob, len(blob))
    else:
        exp = (flat.size - nchunk) * item  # peers read all chunks but mine
        if exp:
            # zero-copy publish of the caller's buffer is safe for allreduce
            # only: this rank's gather completing proves every peer published
            # its reduced chunk, hence finished its reduce-scatter, hence will
            # never read this input again — so by the time allreduce returns
            # (and the caller may mutate the tensor) all :in reads are done.
            plane.store.publish(f"{key}:in", memoryview(flat).cast("B"), exp)
    metas = _ring_metas(_exchange(st, key, _meta(st, plane, flat, arr.shape, enc)),
                        key, same_shape=flat.reshape(arr.shape))
    deadline = time.monotonic() + _op_timeout()
    dtype = flat.dtype

    # -- ring reduce-scatter: stream peers' slices of MY chunk, rank-order reduce
    def part_src(i):
        if nchunk == 0:
            return np.empty(0, dtype)
        m = metas[i]
        if enc == "int8":
            return _decompress(plane.pull_all(m["addr"], f"{key}:in{r}"), dtype)
        raw = plane.pull_range(m["addr"], f"{key}:in", b0 * item, nchunk * item)
        return np.frombuffer(raw, dtype)

    with telemetry.span("collective.phase.reduce_scatter", "collective",
                        key=key, bytes=flat.nbytes, chunk_bytes=nchunk * item):
        reduced = _ordered_stream_reduce(st, op, part_src, flat[b0:b1],
                                         deadline, f"allreduce {key}")

    # -- allgather of reduced chunks straight from their owners
    if nchunk:
        if enc == "int8":
            blob = _compress(reduced)
            plane.store.publish(f"{key}:red", blob, (w - 1) * len(blob))
            # self-consistency: peers receive the quantize->dequantize round
            # trip of this chunk, so the owner must use the SAME values or
            # allreduce's all-ranks-identical postcondition breaks (replicas
            # synced through a compressed group would silently drift)
            reduced = _decompress(blob, dtype)
        else:
            # `reduced` is op-local (never handed to the caller): publish a
            # zero-copy view; the store entry keeps it alive until retraction
            plane.store.publish(f"{key}:red", memoryview(reduced).cast("B"),
                                (w - 1) * nchunk * item)
    out = np.empty(flat.size, dtype)
    out[b0:b1] = reduced
    out_bytes = out.view(np.uint8)

    def gather(j):
        j0, j1 = bounds[j]
        if j1 == j0:
            return
        m = metas[j]
        if enc == "int8":
            out[j0:j1] = _decompress(plane.pull_all(m["addr"], f"{key}:red"), dtype)
        else:
            plane.pull_range(m["addr"], f"{key}:red", 0, (j1 - j0) * item,
                             out=out_bytes[j0 * item:j1 * item])

    with telemetry.span("collective.phase.allgather", "collective",
                        key=key, bytes=flat.nbytes):
        _run_threads([lambda j=j: gather(j) for j in _staggered(r, w)], deadline,
                     f"allreduce gather {key}", st=st)
    return out.reshape(arr.shape)


def reduce(st, tensor, dst_rank: int, op: ReduceOp) -> Optional[np.ndarray]:
    """Returns the reduced tensor on dst_rank, None elsewhere."""
    arr = np.asarray(tensor)
    if st.world_size == 1:
        return reduce_parts([arr], op)
    key = st.next_key("reduce")
    flat = _flat(arr)
    if flat.nbytes < _threshold(st) or not _ring_capable(flat):
        parts = _board_tensors(_exchange(st, key, arr), key)
        return reduce_parts(parts, op) if st.rank == dst_rank else None
    plane = _ensure_plane(st)
    enc = _enc_for(st, flat)
    if st.rank != dst_rank:
        if enc == "int8":
            blob = _compress(flat)
            plane.store.publish(f"{key}:in", blob, len(blob))
        elif flat.nbytes:
            plane.store.publish(f"{key}:in", flat.tobytes(), flat.nbytes)
    metas = _ring_metas(_exchange(st, key, _meta(st, plane, flat, arr.shape, enc)),
                        key, same_shape=flat.reshape(arr.shape))
    if st.rank != dst_rank:
        return None
    deadline = time.monotonic() + _op_timeout()
    dtype = flat.dtype

    def part_src(i):
        m = metas[i]
        if enc == "int8":
            return _decompress(plane.pull_all(m["addr"], f"{key}:in"), dtype)
        if flat.nbytes == 0:
            return np.empty(0, dtype)
        raw = plane.pull_range(m["addr"], f"{key}:in", 0, flat.nbytes)
        return np.frombuffer(raw, dtype)

    acc = _ordered_stream_reduce(st, op, part_src, flat, deadline, f"reduce {key}")
    return acc.reshape(arr.shape)


def _tree_addrs(st, plane: _Plane, key: str) -> List[Tuple[str, int]]:
    """The tree needs every rank's data-plane address, not just the source's.
    Addresses are immutable for the planes' lifetime, so the O(W) board
    exchange runs once per group and is cached; every rank takes the same
    branch (all cache after their first ring broadcast together)."""
    addrs = getattr(st, "ring_addrs", None)
    if addrs is None:
        addrs = _exchange(st, f"{key}:addr", plane.addr)
        st.ring_addrs = addrs
    return addrs


def _tree_children(v: int, w: int) -> List[int]:
    """Binomial tree on src-relative labels: parent(v) clears v's highest set
    bit; children(v) = v + 2^k for 2^k above v's highest bit, while < w."""
    out = []
    bit = 1 << v.bit_length()
    while v + bit < w:
        out.append(v + bit)
        bit <<= 1
    return out


def broadcast(st, tensor, src_rank: int) -> np.ndarray:
    arr = np.asarray(tensor)
    key = st.next_key("broadcast")
    w = st.world_size
    if w == 1:
        return arr
    if st.rank == src_rank:
        flat = _flat(arr)
        if flat.nbytes < _threshold(st) or not _ring_capable(flat):
            _exchange(st, key, arr, expected=1)
            return arr
        plane = _ensure_plane(st)
        enc = _enc_for(st, flat)
        blob = _compress(flat) if enc == "int8" else flat.tobytes()
        nchild = len(_tree_children(0, w))
        plane.store.publish(f"{key}:bc", blob, nchild * len(blob))
        _exchange(st, key,
                  _meta(st, plane, flat, arr.shape, enc, blob_len=len(blob)),
                  expected=1)
        _tree_addrs(st, plane, key)
        return arr
    # non-source: the source alone decides board vs ring (it knows the size)
    entry = wait_poll(st, key, timeout_s=_op_timeout(), expected=1)[0]
    if not _is_meta(entry):
        return np.asarray(entry)
    meta = entry[1]
    plane = _ensure_plane(st)
    addrs = _tree_addrs(st, plane, key)
    v = (st.rank - src_rank) % w
    parent_v = v - (1 << (v.bit_length() - 1))
    parent_addr = addrs[(parent_v + src_rank) % w]
    nchild = len(_tree_children(v, w))
    total = int(meta["blob_len"])
    buf = bytearray(total)
    if nchild:
        plane.store.publish_stream(f"{key}:bc", buf, nchild * total)
    # chunked store-and-forward: republish each chunk as it lands so children
    # stream behind us instead of waiting for the whole payload
    step = _chunk_bytes()
    deadline = time.monotonic() + _op_timeout()
    abort = _AbortCheck(st)
    pos = 0
    with telemetry.span("collective.phase.relay", "collective", key=key,
                        bytes=total, children=nchild,
                        chunks=-(-total // step) if step else 0):
        while pos < total:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"broadcast {key}: relay from rank {(parent_v + src_rank) % w} "
                    f"exceeded {_op_timeout()}s at byte {pos}/{total}")
            abort.check()  # a dead relay parent must not cost the whole deadline
            ln = min(step, total - pos)
            try:
                # bounded probe (see _Plane.pull): an upstream death that stalls
                # the parent's stream must not pin us inside one pull for the op
                # timeout — the abort verdict has to win within ~one poll interval.
                # recv-into: the relayed chunk lands straight in the buffer the
                # children stream out of, no staging bytes
                n = plane.pull_into(parent_addr, f"{key}:bc", pos, ln,
                                    memoryview(buf)[pos:pos + ln],
                                    timeout=abort.interval)
            except (OSError, EOFError, TimeoutError) as e:
                abort.check(force=True, cause=e)
                raise
            if n is None:
                continue  # range not relayed yet: re-probe abort, then re-ask
            pos += ln
            if nchild:
                plane.store.advance(f"{key}:bc", pos)
    dtype = np.dtype(meta["dtype"])
    if meta["enc"] == "int8":
        flat = _decompress(buf, dtype)  # fresh array; buf stays children-only
    elif nchild:
        # children may still stream chunks out of buf: never hand the caller
        # a view of it (a non-numpy caller would get it back un-copied)
        flat = np.frombuffer(buf, dtype).copy()
    else:
        flat = np.frombuffer(buf, dtype)
    return flat.reshape(tuple(meta["shape"]))


def allgather(st, tensor) -> List[np.ndarray]:
    arr = np.asarray(tensor)
    w, r = st.world_size, st.rank
    if w == 1:
        return [np.asarray(arr).copy()]  # board path returned a copy too
    key = st.next_key("allgather")
    flat = _flat(arr)
    # per-rank decision: members may gather different-sized payloads, so small
    # ones ride the board while large ones go peer-to-peer, in the same op
    own = None  # compressed publish: the self-consistent (lossy) local value
    if flat.nbytes < _threshold(st) or not _ring_capable(flat):
        payload = arr
    else:
        plane = _ensure_plane(st)
        enc = _enc_for(st, flat)
        blob = _compress(flat) if enc == "int8" else flat.tobytes()
        plane.store.publish(f"{key}:in", blob, (w - 1) * len(blob))
        payload = _meta(st, plane, flat, arr.shape, enc)
        if enc == "int8":
            # peers decompress this blob; gather the same round-tripped
            # values locally so every rank's list is identical
            own = _decompress(blob, flat.dtype).reshape(arr.shape)
    entries = _exchange(st, key, payload)
    results: List[Optional[np.ndarray]] = [None] * w
    deadline = time.monotonic() + _op_timeout()

    def fetch(i):
        if i == r:
            # snapshot, not a reference: every other entry (and the board
            # path) is decoupled from the caller's buffer
            results[i] = own if own is not None else np.array(arr, copy=True)
        elif _is_meta(entries[i]):
            results[i] = _pull_payload(_ensure_plane(st), entries[i][1],
                                       f"{key}:in")
        else:
            results[i] = np.asarray(entries[i])

    fetch(r)
    with telemetry.span("collective.phase.gather", "collective", key=key,
                        bytes=flat.nbytes):
        _run_threads([lambda i=i: fetch(i) for i in _staggered(r, w)], deadline,
                     f"allgather {key}", st=st)
    return results


def reducescatter(st, tensor, op: ReduceOp) -> np.ndarray:
    arr = np.asarray(tensor)
    w, r = st.world_size, st.rank
    flat = _flat(arr)
    if w == 1:
        return reduce_parts([arr], op)
    key = st.next_key("reducescatter")
    if flat.nbytes < _threshold(st) or not _ring_capable(flat):
        full = reduce_parts(_board_tensors(_exchange(st, key, arr), key), op)
        if full.shape[0] % w != 0:
            raise ValueError(
                f"reducescatter: leading dim {full.shape[0]} not divisible by world_size {w}"
            )
        chunk = full.shape[0] // w
        return full[r * chunk: (r + 1) * chunk]
    if arr.shape[0] % w != 0:
        raise ValueError(
            f"reducescatter: leading dim {arr.shape[0]} not divisible by world_size {w}"
        )
    plane = _ensure_plane(st)
    enc = _enc_for(st, flat)
    per = flat.size // w  # axis-0 slices of a C-contiguous array are flat ranges
    item = flat.dtype.itemsize
    if enc == "int8":
        for i in range(w):
            if i == r or per == 0:
                continue
            blob = _compress(flat[i * per:(i + 1) * per])
            plane.store.publish(f"{key}:in{i}", blob, len(blob))
    elif flat.nbytes:
        plane.store.publish(f"{key}:in", flat.tobytes(), (w - 1) * per * item)
    metas = _ring_metas(_exchange(st, key, _meta(st, plane, flat, arr.shape, enc)),
                        key, same_shape=flat.reshape(arr.shape))
    deadline = time.monotonic() + _op_timeout()
    dtype = flat.dtype

    def part_src(i):
        if per == 0:
            return np.empty(0, dtype)
        m = metas[i]
        if enc == "int8":
            return _decompress(plane.pull_all(m["addr"], f"{key}:in{r}"), dtype)
        raw = plane.pull_range(m["addr"], f"{key}:in", r * per * item, per * item)
        return np.frombuffer(raw, dtype)

    with telemetry.span("collective.phase.reduce_scatter", "collective",
                        key=key, bytes=flat.nbytes, chunk_bytes=per * item):
        acc = _ordered_stream_reduce(st, op, part_src,
                                     flat[r * per:(r + 1) * per],
                                     deadline, f"reducescatter {key}")
    return acc.reshape((arr.shape[0] // w,) + arr.shape[1:])


def send(st, tensor, dst_rank: int) -> None:
    arr = np.asarray(tensor)
    key = st.next_key("p2p", extra=f"{st.rank}->{dst_rank}")
    flat = _flat(arr)
    epoch = getattr(st, "epoch", None)
    if flat.nbytes < _threshold(st) or not _ring_capable(flat):
        st.coordinator.contribute.remote(key, st.rank, arr, epoch)
        return
    plane = _ensure_plane(st)
    enc = _enc_for(st, flat)
    blob = _compress(flat) if enc == "int8" else flat.tobytes()
    plane.store.publish(f"{key}:in", blob, len(blob))
    st.coordinator.contribute.remote(key, st.rank,
                                     _meta(st, plane, flat, arr.shape, enc), epoch)


def recv(st, src_rank: int) -> np.ndarray:
    key = st.next_key("p2p", extra=f"{src_rank}->{st.rank}")
    payload = wait_poll_one(st, key, src_rank, timeout_s=_op_timeout())
    if _is_meta(payload):
        return _pull_payload(_ensure_plane(st), payload[1], f"{key}:in")
    return np.asarray(payload)
