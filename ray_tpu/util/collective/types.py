"""Collective types (reference capability: python/ray/util/collective/types.py).

Backends:
  - SHM: host-plane collectives over the cluster object store + a coordinator actor
    (the Gloo-analogue; reference gloo_collective_group.py). Works anywhere, meant for
    control-plane tensors (weight broadcast, metric reduction), NOT the training hot path.
  - XLA: tensor-plane collectives compiled by XLA over ICI (psum/all_gather/ppermute inside
    shard_map / pjit). Group init bootstraps `jax.distributed` across member processes
    (reference nccl_collective_group.py:128 rendezvous analogue). The hot path for tensors.
  - NCCL/GLOO/MPI: not supported on TPU (reference types.py:29-46 likewise raises on MPI).
"""
from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class Backend(str, Enum):
    SHM = "shm"
    XLA = "xla"
    NCCL = "nccl"
    GLOO = "gloo"
    MPI = "mpi"

    @classmethod
    def parse(cls, value: "Backend | str") -> "Backend":
        b = cls(value.lower()) if isinstance(value, str) else value
        if b in (Backend.NCCL, Backend.GLOO):
            raise ValueError(
                f"backend {b.value!r} is GPU/CPU-cluster specific and unsupported on TPU; "
                "use 'xla' (ICI tensor plane) or 'shm' (host plane)"
            )
        if b is Backend.MPI:
            raise NotImplementedError("MPI is not supported (matches reference behavior)")
        return b


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3


class Compression(str, Enum):
    """Opt-in wire compression for host-plane (ring-path) collective payloads.

    INT8: blockwise symmetric int8 quantization of floating-point payloads at
    or above the ring threshold (ops/quant.py scheme; EQuARX-style compressed
    all-reduce). Lossy (~1% per quantization stage) — off by default; results
    are bit-exact with the coordinator-board path only when compression is off.
    Integer/bool payloads always travel raw.
    """

    NONE = "none"
    INT8 = "int8"

    @classmethod
    def parse(cls, value: "Compression | str | None") -> "Compression":
        if value is None or value == "":
            return Compression.NONE
        c = cls(value.lower()) if isinstance(value, str) else value
        return c


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class BroadcastOptions:
    src_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000
