from .placement_group_api import (  # noqa: F401
    placement_group,
    remove_placement_group,
    placement_group_table,
)
