"""Declarative SLOs evaluated over the metrics history, with Google-SRE-style
multi-window burn rates.

An SLO says "objective fraction of events must be good over window seconds":

    SLO("ttft", metric="serve_ttft_seconds", objective=0.99,
        threshold=0.5, window_s=60.0)                      # latency: p99<=500ms
    SLO("errors", metric="serve_errors_total", objective=0.999,
        total_metric="serve_requests_total", kind="error_rate")
    SLO("queue", metric="serve_queue_depth", objective=0.9,
        threshold=16, kind="gauge")                        # saturation

Evaluation (util/metrics_history.py frames, refreshed by the head scraper):
the bad-event fraction over the window is divided by the error budget
(1 - objective) to give a BURN RATE — 1.0 means budget consumed exactly at
the sustainable pace, 10 means the budget gone in window/10. Following the
SRE-workbook multi-window rule, an SLO only flips to "burning" when BOTH the
long window (window_s) and the short window (window_s / 4, floor one scrape
interval) exceed burn_threshold — the short window makes the signal fast, the
long window keeps a single straggler from paging. This status is the control
input the serve autoscaler / router closed loop consumes: read it via
state.slo_status(), poll /api/slo, or register a subscribe_slo() callback to
get transitions pushed (called from the scraper thread, head process).
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("ray_tpu.slo")

VALID_KINDS = ("latency", "error_rate", "gauge")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One objective. kind:
    - "latency": `metric` is a histogram; good = observation <= threshold
      seconds. objective 0.99 + threshold 0.5 reads "p99 of TTFT <= 500 ms".
    - "error_rate": `metric` counts bad events, `total_metric` all events;
      good fraction = 1 - delta(metric)/delta(total_metric).
    - "gauge": good = frames where the (summed) gauge <= threshold;
      objective is the fraction of frames that must be good.
    `where` narrows to matching tag sets (e.g. {"route": "/chat"})."""

    name: str
    metric: str
    objective: float
    threshold: float = 0.0
    window_s: float = 60.0
    kind: str = "latency"
    total_metric: Optional[str] = None
    where: Optional[Dict[str, str]] = None
    burn_threshold: float = 1.0
    short_window_s: Optional[float] = None

    def __post_init__(self):
        if self.kind not in VALID_KINDS:
            raise ValueError(f"SLO kind must be one of {VALID_KINDS}, "
                             f"got {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1) — it is the GOOD "
                             "fraction, e.g. 0.99")
        if self.kind == "error_rate" and not self.total_metric:
            raise ValueError("error_rate SLOs need total_metric (the "
                             "denominator counter)")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def short_window(self, scrape_interval_s: float) -> float:
        if self.short_window_s is not None:
            return self.short_window_s
        # floor at one scrape interval: a shorter window than the frame
        # spacing would always difference the same two frames as "long"
        return max(self.window_s / 4.0, scrape_interval_s)


class SLOEngine:
    """Registry + evaluator. evaluate() runs after every scrape (head-side
    scraper thread); status transitions fan out to subscribe() callbacks."""

    def __init__(self, history):
        from ray_tpu.util.logutil import LogThrottle

        self._history = history
        self._lock = threading.Lock()
        self._slos: Dict[str, SLO] = {}
        self._subs: List[Callable[[dict], None]] = []
        self._status: Dict[str, Dict[str, Any]] = {}
        # per-subscriber warn throttle: transitions fire from the scraper
        # thread — the only heartbeat of every loop riding these signals — so
        # a persistently-broken callback logs once per window, not per flip
        self._sub_warn = LogThrottle(30.0)

    # ------------------------------------------------------------- registry

    def register(self, slo: SLO) -> SLO:
        with self._lock:
            self._slos[slo.name] = slo
            self._status.pop(slo.name, None)  # re-registering resets state
        return slo

    def remove(self, name: str) -> bool:
        with self._lock:
            self._status.pop(name, None)
            return self._slos.pop(name, None) is not None

    def slos(self) -> List[SLO]:
        with self._lock:
            return list(self._slos.values())

    def subscribe(self, callback: Callable[[dict], None]) -> Callable[[], None]:
        """callback(transition_dict) on every ok<->burning flip, invoked from
        the scraper thread. Returns an unsubscribe function."""
        with self._lock:
            self._subs.append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._subs.remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    # ------------------------------------------------------------ evaluation

    def _bad_fraction(self, slo: SLO, window_s: float) -> Optional[float]:
        """Fraction of bad events in the window, or None when the history
        has no signal for it (no traffic / not enough frames)."""
        h = self._history
        if slo.kind == "latency":
            split = h.counts_below(slo.metric, slo.threshold, window_s,
                                   where=slo.where)
            if split is None:
                return None
            good, total = split
            if total <= 0:
                return None
            return max(0.0, 1.0 - good / total)
        if slo.kind == "error_rate":
            bad = h.delta(slo.metric, window_s, where=slo.where)
            total = h.delta(slo.total_metric, window_s, where=slo.where)
            if bad is None or total is None or total <= 0:
                return None
            return min(1.0, bad / total)
        # gauge saturation: fraction of frames over the threshold
        vals = h.gauge_values(slo.metric, window_s, where=slo.where)
        if not vals:
            return None
        return sum(1 for v in vals if v > slo.threshold) / len(vals)

    def _evaluate_one(self, slo: SLO, scrape_interval_s: float
                      ) -> Dict[str, Any]:
        long_bad = self._bad_fraction(slo, slo.window_s)
        short_bad = self._bad_fraction(slo, slo.short_window(scrape_interval_s))
        budget = slo.budget

        def burn(bad):
            return None if bad is None else bad / budget

        burn_long, burn_short = burn(long_bad), burn(short_bad)
        if burn_long is None:
            state = "no_data"
        elif (burn_long >= slo.burn_threshold
              and burn_short is not None
              and burn_short >= slo.burn_threshold):
            # multi-window rule: BOTH windows must exceed the threshold. A
            # short window with no events means the burn is not still
            # happening — staying "burning" on long-window residue alone
            # would keep paging/scaling for a full window after recovery
            state = "burning"
        else:
            state = "ok"
        out: Dict[str, Any] = {
            "name": slo.name, "metric": slo.metric, "kind": slo.kind,
            "objective": slo.objective, "threshold": slo.threshold,
            "window_s": slo.window_s, "state": state,
            "burn_rate_long": burn_long, "burn_rate_short": burn_short,
            "bad_fraction": long_bad, "budget": budget,
        }
        if slo.kind == "latency":
            out["observed"] = self._history.quantile(
                slo.metric, slo.objective, slo.window_s, where=slo.where)
        return out

    def evaluate(self) -> Dict[str, Dict[str, Any]]:
        """Evaluate every registered SLO against the current history; fire
        subscriber callbacks for state transitions. Called by the scraper
        after each frame; safe to call ad hoc (tests, state API)."""
        t0 = time.perf_counter()
        try:
            from ray_tpu.config import CONFIG

            interval = max(0.05, float(CONFIG.metrics_scrape_interval_s))
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (interval = 5.0) by design
        except Exception:
            interval = 5.0
        with self._lock:
            slos = list(self._slos.values())
            prev = {k: v.get("state") for k, v in self._status.items()}
            subs = list(self._subs)
        transitions = []
        status = {}
        for slo in slos:
            try:
                row = self._evaluate_one(slo, interval)
            except Exception as e:  # a malformed metric must not stop the rest
                row = {"name": slo.name, "state": "error", "error": repr(e)}
            row["evaluated_at"] = time.time()
            status[slo.name] = row
            was, now = prev.get(slo.name), row["state"]
            # a just-registered SLO (was None) fires only when it lands
            # BURNING: registering mid-incident must reach the subscriber
            # immediately, while a healthy first evaluation stays quiet
            if was != now and (was is not None or now == "burning"):
                transitions.append({"name": slo.name, "from": was, "to": now,
                                    "at": row["evaluated_at"], "status": row})
        with self._lock:
            self._status = status
        from ray_tpu.util.logutil import guarded_fanout

        for t in transitions:
            # delivery rides the scraper thread — the heartbeat of every
            # control loop downstream — so each subscriber is individually
            # guarded with a throttled warning (logutil.guarded_fanout)
            guarded_fanout(subs, t, throttle=self._sub_warn, logger=logger,
                           what=f"slo subscriber ({t['name']})",
                           exc_info=True)
        # control-plane self-telemetry: how long one full SLO pass costs the
        # head (scales with registered SLOs x history window math)
        from ray_tpu.util import telemetry as _tel

        _tel.get_histogram(
            "control_decision_seconds",
            "wall time of one control-loop decision pass, by loop",
            tag_keys=("loop",),
        ).observe(time.perf_counter() - t0, tags={"loop": "slo"})
        return status

    def status(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._status)


# ------------------------------------------------------- module-level surface

def _engine() -> SLOEngine:
    from ray_tpu.core import global_state

    c = global_state.try_cluster()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized (SLOs are registered "
                           "on the head; call ray_tpu.init() first)")
    return c.slo_engine


def register(slo: SLO) -> SLO:
    """Register (or replace) an SLO on the head's engine."""
    return _engine().register(slo)


def remove(name: str) -> bool:
    return _engine().remove(name)


def subscribe_slo(callback: Callable[[dict], None]) -> Callable[[], None]:
    """Push-mode SLO transitions: callback({name, from, to, at, status}) on
    every ok<->burning flip (invoked from the head's scraper thread — keep it
    quick and never raise). The autoscaler/router closed loop hangs off this
    hook. Returns an unsubscribe function. Head-process only."""
    return _engine().subscribe(callback)


def slo_status() -> Dict[str, Dict[str, Any]]:
    return _engine().status()
