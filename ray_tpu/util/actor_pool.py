"""ActorPool: distribute work over a fixed set of actors.

Capability parity: reference python/ray/util/actor_pool.py — map/map_unordered/
submit/get_next(_unordered)/has_next/has_free plus push/pop_idle.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List = []

    # -- submission ------------------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queues if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref.id] = (actor, ref)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    # -- retrieval -------------------------------------------------------------
    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def get_next(self, timeout: float = None) -> Any:
        """Next result in submission order. On timeout the pool state is intact
        (reference semantics: the caller may retry the same get_next)."""
        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no more results to get")
        ref = self._index_to_future[self._next_return_index]
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out; call again to retry")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        actor, _ = self._future_to_actor.pop(ref.id)
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Next result in completion order."""
        if not self._future_to_actor:
            raise StopIteration("no more results to get")
        refs = [ref for _, ref in self._future_to_actor.values()]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        actor, _ = self._future_to_actor.pop(ref.id)
        for idx, f in list(self._index_to_future.items()):
            if f.id == ref.id:
                del self._index_to_future[idx]
                break
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(actor)

    # -- bulk ------------------------------------------------------------------
    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- membership ------------------------------------------------------------
    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def push(self, actor) -> None:
        self._return_actor(actor)

    def pop_idle(self):
        return self._idle.pop() if self.has_free() else None
