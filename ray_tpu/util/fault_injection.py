"""Deterministic fault injection: fail points + the ChaosController.

Reference analog: Ray's chaos suites wire kill *policies* around the system
(test_utils WorkerKiller / RayletKiller); production stacks add *fail points*
inside it (freebsd fail(9), tikv fail-rs, envoy fault filter). This module is
the unified registry both ride:

- ``fail_point(name, **ctx)`` — a named injection site compiled into hot
  paths (serve handle send, replica request loop, data-plane pull, collective
  waits). A no-op unless armed: the fast path is one dict check plus one
  memoized env read (~0.1us), cheap enough for per-request call sites.
  Registered sites: ``serve.handle.request`` / ``serve.handle.send`` /
  ``serve.replica.request`` / ``serve.replica.health`` /
  ``serve.autoscaler.decide`` (head-side control loop, top of every tick) /
  ``serve.controller.scale`` (controller apply RPC) / ``data_plane.pull`` /
  ``collective.wait`` / ``llm.pd.handoff`` (per-page paged KV pull on the
  decode side — P/D disaggregation's transfer hot path) /
  ``head.control.recv`` / ``head.control.send`` (the node agent's head
  connection: error mode simulates a head outage — the agent's bounded
  reconnect + reattach machinery runs against the live head, making
  head-death recovery testable without killing any process).
- Arming is per-process via :func:`arm`, or via the
  ``RAY_TPU_FAULT_INJECTION`` environment variable so spawned workers inherit
  specs (``site=mode[@p=0.5][@n=3][@delay=0.1][@seed=7][;site2=...]``).
  Modes: ``error`` raises :class:`FaultInjectedError`, ``delay`` sleeps
  ``delay_s``, ``kill`` SIGKILLs the calling process. ``p`` draws from a
  per-spec seeded RNG (deterministic sequences), ``n`` bounds total firings.
- :class:`ChaosController` — cluster-level orchestration: kill the worker
  holding a collective rank (subsumes the PR 3 ``CollectiveRankKiller``),
  kill a serve replica's process mid-request, arm/disarm fail points inside
  running replicas.

FaultInjectedError is classified by the serve retry plane like a replica
death, so ``error`` mode drives the same recovery machinery a real crash
would — deterministically, in-process, tier-1 fast.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core.exceptions import FaultInjectedError

logger = logging.getLogger("ray_tpu.fault_injection")

ENV_VAR = "RAY_TPU_FAULT_INJECTION"

MODES = ("error", "delay", "kill")


class _Spec:
    __slots__ = ("name", "mode", "prob", "count", "delay_s", "rng", "fired",
                 "skipped")

    def __init__(self, name: str, mode: str = "error", prob: float = 1.0,
                 count: Optional[int] = None, delay_s: float = 0.0,
                 seed: Optional[int] = None):
        if mode not in MODES:
            raise ValueError(f"fault mode must be one of {MODES}, got {mode!r}")
        self.name = name
        self.mode = mode
        self.prob = float(prob)
        self.count = count  # None = unlimited firings
        self.delay_s = float(delay_s)
        # per-spec RNG: seeded draws give the same hit/miss sequence on every
        # run — the point of a DETERMINISTIC chaos framework
        self.rng = random.Random(seed)
        self.fired = 0
        self.skipped = 0

    def should_fire(self) -> bool:
        if self.count is not None and self.fired >= self.count:
            return False
        if self.prob < 1.0 and self.rng.random() >= self.prob:
            self.skipped += 1
            return False
        self.fired += 1
        return True


_lock = threading.Lock()
_specs: Dict[str, _Spec] = {}  # API-armed (this process)
# env-armed specs: parsed lazily, cached against the raw env string so count
# budgets/RNG state persist while the variable is unchanged
_env_cache: tuple = (None, {})  # (raw_string, {name: _Spec})


def arm(name: str, mode: str = "error", prob: float = 1.0,
        count: Optional[int] = None, delay_s: float = 0.0,
        seed: Optional[int] = None) -> None:
    """Arm a fail point in THIS process. Replaces any existing spec for it."""
    spec = _Spec(name, mode, prob, count, delay_s, seed)
    with _lock:
        _specs[name] = spec


def disarm(name: Optional[str] = None) -> None:
    """Disarm one fail point (or all, with no argument) in this process."""
    with _lock:
        if name is None:
            _specs.clear()
        else:
            _specs.pop(name, None)


def _refresh_env_cache_locked() -> None:
    """Re-parse RAY_TPU_FAULT_INJECTION when the raw string changed (caller
    holds _lock): introspection must see env-armed sites before the first
    fail_point() call populates the cache."""
    global _env_cache
    raw = os.environ.get(ENV_VAR)
    if raw != _env_cache[0]:
        _env_cache = (raw, parse_env(raw) if raw else {})


def fired(name: str) -> int:
    """How many times the named fail point has fired in this process."""
    with _lock:
        _refresh_env_cache_locked()
        spec = _specs.get(name) or _env_cache[1].get(name)
    return spec.fired if spec is not None else 0


def armed() -> Dict[str, str]:
    """Introspection: {site: mode} for every armed spec in this process."""
    with _lock:
        _refresh_env_cache_locked()
        out = {n: s.mode for n, s in _env_cache[1].items()}
        out.update({n: s.mode for n, s in _specs.items()})
    return out


def parse_env(raw: str) -> Dict[str, _Spec]:
    """``site=mode[@p=][@n=][@delay=][@seed=][;...]`` -> specs. Bad entries
    are skipped with a warning — a typo'd chaos var must not take down the
    process it was supposed to test."""
    specs: Dict[str, _Spec] = {}
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, _, rest = entry.partition("=")
            parts = rest.split("@")
            kwargs: Dict[str, Any] = {"mode": parts[0].strip()}
            for p in parts[1:]:
                k, _, v = p.partition("=")
                k = k.strip()
                if k == "p":
                    kwargs["prob"] = float(v)
                elif k == "n":
                    kwargs["count"] = int(v)
                elif k == "delay":
                    kwargs["delay_s"] = float(v)
                elif k == "seed":
                    kwargs["seed"] = int(v)
                else:
                    raise ValueError(f"unknown key {k!r}")
            specs[site.strip()] = _Spec(site.strip(), **kwargs)
        except Exception as e:  # noqa: BLE001 — skip the bad entry, keep going
            logger.warning("ignoring unparseable %s entry %r: %r",
                           ENV_VAR, entry, e)
    return specs


def _lookup(name: str) -> Optional[_Spec]:
    with _lock:
        spec = _specs.get(name)
        if spec is not None:
            return spec
        _refresh_env_cache_locked()
        return _env_cache[1].get(name)


def fail_point(name: str, **context: Any) -> None:
    """The injection site. A no-op unless a spec for `name` is armed (API or
    env); armed, it errors/delays/kills per the spec. `context` rides the
    raised FaultInjectedError for assertions and log forensics."""
    if not _specs and os.environ.get(ENV_VAR) is None:
        return  # fast path: nothing armed anywhere
    spec = _lookup(name)
    if spec is None:
        return
    with _lock:
        fire = spec.should_fire()
    if not fire:
        return
    if spec.mode == "delay":
        logger.info("fail point %r: injecting %.3fs delay (%s)",
                    name, spec.delay_s, context)
        time.sleep(spec.delay_s)
        return
    if spec.mode == "kill":
        import signal

        logger.warning("fail point %r: SIGKILL pid %d (%s)",
                       name, os.getpid(), context)
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(10)  # never returns; parachute for exotic platforms
        return
    raise FaultInjectedError(name, context)


# ---------------------------------------------------------------- ChaosController

def _cluster():
    from ray_tpu.core import global_state

    c = global_state.try_cluster()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized")
    return c


class ChaosController:
    """Cluster-level chaos orchestration over the fail-point registry and the
    head's process registries. One object subsumes the ad-hoc kill kits:

    - collective ranks: ``kill_collective_rank(group, rank)`` resolves
      rank -> worker through the head's collective-membership registry (the
      PR 3 ``CollectiveRankKiller`` path) and SIGKILLs it mid-op.
    - serve replicas: ``kill_replica(app, deployment)`` SIGKILLs the worker
      process hosting a replica actor (truer chaos than ``ray_tpu.kill`` —
      no graceful teardown), ``arm_replica``/``disarm_replica`` arm fail
      points INSIDE running replica processes via an actor RPC.

    Driver/head-side only (it reads Cluster structures), like the test_utils
    kill kits it replaces.
    """

    # -- collective ranks (CollectiveRankKiller parity) ------------------------
    def _collective_member(self, group_name: str, rank: int):
        c = _cluster()
        with c._lock:
            entry = c._collective_members.get(group_name, {}).get(rank)
        return entry[0] if entry is not None else None

    def collective_rank_registered(self, group_name: str, rank: int) -> bool:
        """True once the rank has joined its group (a kill can land)."""
        return self._collective_member(group_name, rank) is not None

    def kill_collective_rank(self, group_name: str, rank: int) -> bool:
        """SIGKILL the worker holding `rank` of `group_name` (mid-op by
        design): survivors must observe a typed CollectiveAbortError fast."""
        w = self._collective_member(group_name, rank)
        if w is None:
            return False
        try:
            w.process.kill()
            return True
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:  # noqa: BLE001 — already dead / no local process
            return False

    def kill_collective_rank_when_registered(self, group_name: str, rank: int,
                                             timeout: float = 10.0) -> bool:
        from ray_tpu.test_utils import wait_for_condition

        wait_for_condition(
            lambda: self.collective_rank_registered(group_name, rank),
            timeout=timeout,
            message=f"rank {rank} never joined group {group_name!r}")
        return self.kill_collective_rank(group_name, rank)

    # -- serve replicas --------------------------------------------------------
    @staticmethod
    def _replica_actors(app_name: str, deployment_name: str):
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return ray_tpu.get(
            controller.get_replicas.remote(app_name, deployment_name))

    def kill_replica(self, app_name: str, deployment_name: str,
                     index: int = 0) -> bool:
        """SIGKILL the worker process hosting one running replica of the
        deployment (falls back to ray_tpu.kill when the process isn't local).
        In-flight requests fail with ActorDiedError — exactly what the
        handle's retry plane must absorb."""
        import ray_tpu

        actors = self._replica_actors(app_name, deployment_name)
        if not actors or index >= len(actors):
            return False
        actor = actors[index]
        c = _cluster()
        with c._lock:
            st = c.actors.get(actor._actor_id)
            proc = getattr(getattr(st, "worker", None), "process", None)
        if proc is not None:
            try:
                proc.kill()
                return True
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:  # noqa: BLE001 — fall through to the API kill
                pass
        try:
            ray_tpu.kill(actor, no_restart=True)
            return True
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:  # noqa: BLE001
            return False

    # -- arbitrary actors ------------------------------------------------------
    @staticmethod
    def kill_actor(actor: Any) -> bool:
        """SIGKILL the worker process hosting an arbitrary actor handle (no
        graceful teardown — truer chaos than ``ray_tpu.kill``). Used by the
        decoupled RL chaos gate to drop one env-runner worker or one learner
        rank mid-stream. Falls back to the API kill when the process isn't
        local."""
        import ray_tpu

        c = _cluster()
        with c._lock:
            st = c.actors.get(actor._actor_id)
            proc = getattr(getattr(st, "worker", None), "process", None)
        if proc is not None:
            try:
                proc.kill()
                return True
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:  # noqa: BLE001 — fall through to the API kill
                pass
        try:
            ray_tpu.kill(actor, no_restart=True)
            return True
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:  # noqa: BLE001
            return False

    def arm_replica(self, app_name: str, deployment_name: str, site: str,
                    mode: str = "error", prob: float = 1.0,
                    count: Optional[int] = None, delay_s: float = 0.0,
                    seed: Optional[int] = None,
                    index: Optional[int] = None) -> int:
        """Arm a fail point inside running replica processes (all of them, or
        just `index`). Returns how many replicas were armed. Replacement
        replicas start clean — arming does not survive a replica's death,
        which is what makes health-failure injection tests converge."""
        import ray_tpu

        actors = self._replica_actors(app_name, deployment_name)
        if index is not None:
            actors = actors[index:index + 1]
        refs = [a._arm_fault.remote(site, mode, prob, count, delay_s, seed)
                for a in actors]
        done = 0
        for r in refs:
            try:
                ray_tpu.get(r, timeout=10)
                done += 1
            # graftlint: allow[swallowed-exception] fail-point registry probe: unset/invalid spec means the site stays a no-op
            except Exception:  # noqa: BLE001 — replica died meanwhile
                pass
        return done

    # -- head process ----------------------------------------------------------
    @staticmethod
    def kill_head(head: Any = None) -> int:
        """SIGKILL the HEAD process — the whole point of the head-death chaos
        gate. `head` is a pid, or anything with a ``.pid`` (subprocess.Popen);
        when omitted, ``RAY_TPU_HEAD_PID`` names the target. Refuses to kill
        the calling process: an in-process head (driver owns the Cluster)
        dying WITH its driver is a different failure than a head outage, and
        silently killing the test harness helps nobody. Returns the pid."""
        import signal

        pid = getattr(head, "pid", head)
        if pid is None:
            raw = os.environ.get("RAY_TPU_HEAD_PID")
            pid = int(raw) if raw else None
        if pid is None:
            raise RuntimeError(
                "kill_head needs a target: pass a pid / Popen, or set "
                "RAY_TPU_HEAD_PID (an in-process head shares this process — "
                "run the head standalone to chaos-test it)")
        pid = int(pid)
        if pid == os.getpid():
            raise RuntimeError(
                "refusing to SIGKILL the calling process: the head is "
                "in-process here; run it standalone for head-death chaos")
        logger.warning("chaos: SIGKILL head pid %d", pid)
        os.kill(pid, signal.SIGKILL)
        return pid

    # -- serve control plane ---------------------------------------------------
    @staticmethod
    def _controller_actor():
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        return ray_tpu.get_actor(CONTROLLER_NAME)

    def arm_serve_controller(self, site: str = "serve.controller.scale",
                             mode: str = "error", prob: float = 1.0,
                             count: Optional[int] = None, delay_s: float = 0.0,
                             seed: Optional[int] = None) -> bool:
        """Arm a fail point inside the serve CONTROLLER actor process (e.g.
        ``serve.controller.scale``): chaos runs kill/deny the scale apply
        mid-decision and the autoscaler must retry next tick."""
        import ray_tpu

        ref = self._controller_actor()._arm_fault.remote(
            site, mode, prob, count, delay_s, seed)
        return bool(ray_tpu.get(ref, timeout=10))

    def disarm_serve_controller(self, site: Optional[str] = None) -> bool:
        import ray_tpu

        return bool(ray_tpu.get(
            self._controller_actor()._disarm_fault.remote(site), timeout=10))

    @staticmethod
    def arm_serve_autoscaler(mode: str = "error", prob: float = 1.0,
                             count: Optional[int] = None, delay_s: float = 0.0,
                             seed: Optional[int] = None) -> bool:
        """Arm ``serve.autoscaler.decide`` in the HEAD process (the loop runs
        here, not in an actor): error mode crashes the decision path — the
        loop must absorb and journal it, never die."""
        arm("serve.autoscaler.decide", mode, prob, count, delay_s, seed)
        return True

    @staticmethod
    def disarm_serve_autoscaler() -> None:
        disarm("serve.autoscaler.decide")

    def disarm_replica(self, app_name: str, deployment_name: str,
                       site: Optional[str] = None) -> int:
        import ray_tpu

        actors = self._replica_actors(app_name, deployment_name)
        refs = [a._disarm_fault.remote(site) for a in actors]
        done = 0
        for r in refs:
            try:
                ray_tpu.get(r, timeout=10)
                done += 1
            # graftlint: allow[swallowed-exception] fail-point registry probe: unset/invalid spec means the site stays a no-op
            except Exception:  # noqa: BLE001
                pass
        return done
