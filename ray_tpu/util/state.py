"""State API: list/get cluster entities + timeline export.

Capability parity: reference python/ray/util/state/ (api.py list_tasks/actors/
objects/nodes, state_cli.py `ray list ...`) backed by GcsTaskManager +
state_aggregator.py, and `ray.timeline` (python/ray/_private/state.py:986).
Here the cluster lives in the driver process, so the aggregator reads the
Cluster structures directly; worker metrics arrive via the pipe push
(core/node.py "metrics" message).
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import global_state


def _cluster():
    c = global_state.try_cluster()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized")
    return c


# names callable through state_request (client server + worker pipe); populated
# by the decorator so the dispatch gate and the decorated surface stay in lockstep
_REMOTEABLE_FNS: set = set()


def _remoteable(fn):
    """Run on the head when this process is a remote client driver (the state
    aggregator reads Cluster structures, which only exist head-side)."""
    import functools

    _REMOTEABLE_FNS.add(fn.__name__)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if global_state.try_cluster() is None:
            w = global_state.try_worker()
            if w is not None and hasattr(w, "state_request"):
                return w.state_request(fn.__name__, *args, **kwargs)
        return fn(*args, **kwargs)

    return wrapper


def dispatch_state_request(fn_name: str, args=(), kwargs=None):
    """THE gate for remote state calls (client server + coordinator pipe):
    only @_remoteable functions are reachable."""
    if fn_name not in _REMOTEABLE_FNS:
        raise ValueError(f"unknown state function {fn_name!r}")
    import sys

    return getattr(sys.modules[__name__], fn_name)(*args, **(kwargs or {}))


@_remoteable
def gcs_nodes() -> List[Dict[str, Any]]:
    """GCS node-table view backing ray_tpu.nodes() — including for remote
    client drivers (reference: ray.nodes() reading the GCS from any driver)."""
    c = _cluster()
    return [
        {
            "NodeID": info.node_id.hex(),
            "Alive": info.alive,
            "Resources": info.resources,
            "Labels": info.labels,
        }
        for info in c.gcs.nodes(alive_only=False)
    ]


@_remoteable
def list_nodes() -> List[Dict[str, Any]]:
    c = _cluster()
    out = []
    for node in c.nodes():
        out.append({
            "node_id": node.node_id.hex(),
            "alive": node.alive,
            "resources_total": dict(node.ledger.total),
            "resources_available": node.ledger.available(),
            "num_workers": len(node.workers),
        })
    return out


@_remoteable
def list_logs() -> List[Dict[str, Any]]:
    """Remote-worker log rings captured by the head (reference `ray logs` /
    log_monitor.py:105 — agents tail per-worker files to the head)."""
    c = _cluster()
    with c._worker_logs_lock:
        return [{"worker_id": wid, "node_id": ring["node"],
                 "num_lines": len(ring["lines"])}
                for wid, ring in c._worker_logs.items()]


@_remoteable
def get_log(worker_id: str, tail: int = 100) -> List[str]:
    """Last `tail` captured lines of one remote worker ("out|err: line")."""
    c = _cluster()
    if tail <= 0:
        return []
    with c._worker_logs_lock:
        ring = c._worker_logs.get(worker_id)
        lines = list(ring["lines"]) if ring is not None else []
    return [f"{stream}: {line}" for stream, line in lines[-tail:]]


@_remoteable
def list_workers() -> List[Dict[str, Any]]:
    c = _cluster()
    out = []
    with c._lock:
        for node in c._nodes.values():
            for w in node.workers.values():
                out.append({
                    "worker_id": w.worker_id.hex(),
                    "node_id": node.node_id.hex(),
                    "pid": w.process.pid,
                    "state": w.state,
                    "accelerator": w.accel,
                    "num_inflight": len(w.inflight),
                })
    return out


@_remoteable
def list_tasks(filters: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
    """Pending/running tasks plus recent finished ones (bounded ring)."""
    c = _cluster()
    out = []
    with c._lock:
        for ts in c.tasks.values():
            state = "RUNNING" if ts.dispatched_at else "PENDING"
            out.append({
                "task_id": ts.spec.task_id.hex(),
                "name": ts.spec.name,
                "kind": ts.spec.kind,
                "state": state,
                "submitted_at": ts.submitted_at,
            })
        for ev in c.task_events:
            out.append({
                "task_id": ev["task_id"],
                "name": ev["name"],
                "kind": ev["kind"],
                "state": "FAILED" if ev["error"] else "FINISHED",
                "submitted_at": ev["submitted_at"],
            })
    if filters:
        out = [t for t in out if all(t.get(k) == v for k, v in filters.items())]
    return out


@_remoteable
def list_actors() -> List[Dict[str, Any]]:
    c = _cluster()
    out = []
    with c._lock:
        for st in c.actors.values():
            out.append({
                "actor_id": st.actor_id.hex(),
                "class_name": st.creation_spec.name.replace(".__init__", ""),
                "state": st.state.upper(),
                "name": st.name,
                "namespace": st.namespace,
                "pid": st.worker.process.pid if st.worker else None,
                "node_id": st.worker.node.node_id.hex() if st.worker else None,
                "restarts": st.restarts_used,
            })
    return out


@_remoteable
def list_objects() -> List[Dict[str, Any]]:
    c = _cluster()
    store = c.store
    out = []
    with store._lock:
        for oid, loc in store._locations.items():
            kind = loc[0]
            size = (len(loc[1]) if kind == "inline"
                    else loc[3] if kind == "arena" else loc[2])
            out.append({
                "object_id": oid.hex(),
                "tier": kind,
                "size_bytes": size,
                "refcount": store._refcounts.get(oid, 0),
            })
    return out


@_remoteable
def list_placement_groups() -> List[Dict[str, Any]]:
    c = _cluster()
    out = []
    with c.pg_manager._lock:
        entries = list(c.pg_manager._groups.values())
    for pg, bundles in entries:
        out.append({
            "placement_group_id": pg.id.hex(),
            "ready": pg._ready_event.is_set(),
            "strategy": pg.strategy,
            "name": pg.name,
            "bundles": [dict(b.resources) for b in bundles],
        })
    return out


@_remoteable
def summarize_cluster() -> Dict[str, Any]:
    c = _cluster()
    return {
        "nodes": len(list_nodes()),
        "workers": len(list_workers()),
        "actors": len(list_actors()),
        "pending_tasks": len([t for t in list_tasks() if t["state"] == "PENDING"]),
        "objects": c.store.stats(),
    }


# -------------------------------------------------------------------- metrics

def get_metrics() -> Dict[str, dict]:
    """Aggregated metrics: driver registry + latest worker pushes."""
    from ray_tpu.util import metrics as m

    c = _cluster()
    snaps = [m._registry.snapshot()]
    snaps.extend(c.metrics_by_worker.values())
    return m.merge_snapshots(snaps)


def prometheus_metrics() -> str:
    from ray_tpu.util import metrics as m

    user_metrics = get_metrics()
    text = m.prometheus_text(user_metrics)
    # system series alongside the user registry (reference: ray_nodes /
    # ray_actors / ray_object_store_memory exported by the dashboard agent)
    s = summarize_cluster()
    lines = [text] if text else []
    gauges = {
        "cluster_nodes": s["nodes"],
        "cluster_workers": s["workers"],
        "cluster_actors": s["actors"],
        "cluster_pending_tasks": s["pending_tasks"],
    }
    gauges.update({f"object_store_{k}": v for k, v in s["objects"].items()})
    for name, value in gauges.items():
        if name in user_metrics:
            continue  # a user metric claimed this name; duplicate TYPE lines
                      # would invalidate the whole exposition
        full = f"ray_tpu_{name}"
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {value}")
    return "\n".join(lines) + "\n"


# -------------------------------------------------------------------- tracing

@_remoteable
def get_trace() -> List[Dict[str, Any]]:
    """All collected spans: worker-pushed + driver-local (util/tracing.py).

    Driver-local spans are folded into the cluster's persistent ring on read so
    repeated calls keep returning them."""
    from ray_tpu.util import tracing

    c = _cluster()
    local = tracing.drain_local_spans()
    with c._lock:
        c.trace_spans.extend(local)
        return list(c.trace_spans)


# ------------------------------------------------------------------- telemetry

@_remoteable
def head_clock_ns() -> int:
    """The head's wall clock, for the NTP-style offset handshake worker
    telemetry flushers run once per process (util/telemetry.clock_offset_ns):
    merged timeline timestamps are comparable because every worker batch is
    shifted onto THIS clock."""
    import time as _time

    return _time.time_ns()


@_remoteable
def get_telemetry() -> List[Dict[str, Any]]:
    """All collected hot-path telemetry events (util/telemetry.py), oldest
    first: worker-pushed batches (already clock-aligned and proc-tagged by the
    head) + the in-process driver's ring, folded in on read like get_trace."""
    from ray_tpu.util import telemetry

    c = _cluster()
    local = telemetry.align_batch(
        {"clock_offset_ns": 0, "events": telemetry.drain()}, "driver")
    with c._lock:
        c.telemetry_events.extend(local)
        return list(c.telemetry_events)


@_remoteable
def telemetry_timeline_events() -> List[Dict[str, Any]]:
    """Telemetry events rendered as chrome-trace events (no file IO — remotely
    callable). Spans become complete ('X') events, instants become 'i'; the
    `pid` lane is the producing process, the `tid` lane its thread."""
    events = []
    for ev in get_telemetry():
        out = {
            "cat": ev.get("cat", "app"),
            "name": ev.get("name", "?"),
            "pid": ev.get("proc", "driver"),
            "tid": ev.get("tid", "main"),
            "ts": ev["ts_ns"] / 1e3,  # chrome-trace microseconds
            "args": ev.get("args", {}),
        }
        if ev.get("dur_ns") is None:
            out["ph"] = "i"
            out["s"] = "p"  # instant scope: process
        else:
            out["ph"] = "X"
            out["dur"] = ev["dur_ns"] / 1e3
        events.append(out)
    return events


def telemetry_timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cross-worker chrome-trace timeline: hot-path telemetry spans (transfers,
    collective phases, serve/llm request lifecycles, train steps) merged with
    the task timeline, clocks aligned via the head handshake. Load the JSON in
    chrome://tracing / Perfetto. The file, if requested, is written by THIS
    process (a remote client's filename never touches the head's filesystem)."""
    events = telemetry_timeline_events() + timeline_events()
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


@_remoteable
def cluster_status() -> Dict[str, Any]:
    """Live load summary for `ray-tpu status` / the dashboard: per-path
    transfer GB/s, collective op/abort counts, serve TTFT p50/p99 + queue
    depths, llm engine gauges, train MFU — all derived from the merged metric
    registry, so it reflects every process that pushed within the report
    interval."""
    from ray_tpu.util import metrics as m

    merged = get_metrics()

    def counter_by_tag(name: str, tag: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for key, v in merged.get(name, {}).get("values", {}).items():
            label = dict(key).get(tag, "")
            out[label] = out.get(label, 0.0) + v
        return out

    def counter_total(name: str) -> float:
        return sum(merged.get(name, {}).get("values", {}).values())

    def gauges(name: str) -> Dict[str, float]:
        return {",".join(f"{k}={v}" for k, v in key) or "_": val
                for key, val in merged.get(name, {}).get("values", {}).items()}

    status: Dict[str, Any] = {"cluster": summarize_cluster()}

    # -- transfers: counters accumulate (bytes, busy-seconds) per path
    bytes_by_path = counter_by_tag("transfer_bytes_total", "path")
    secs_by_path = counter_by_tag("transfer_seconds_total", "path")
    pulls_by_path = counter_by_tag("transfer_pulls_total", "path")
    transfer = {}
    for path in sorted(set(bytes_by_path) | set(pulls_by_path)):
        b, s = bytes_by_path.get(path, 0.0), secs_by_path.get(path, 0.0)
        transfer[path] = {
            "pulls": int(pulls_by_path.get(path, 0)),
            "bytes": int(b),
            "gbps": round(b / s / 1e9, 3) if s > 0 else None,
        }
    status["transfer"] = transfer

    # -- collectives
    status["collective"] = {
        "ops": {k: int(v) for k, v in
                counter_by_tag("collective_ops_total", "op").items()},
        "aborts": int(counter_total("collective_aborts_total")),
        "aborts_observed": int(counter_total("collective_aborts_observed_total")),
        "epoch_rollovers": int(counter_total("collective_epoch_rollovers_total")),
    }

    # -- serve (queue depth: each process publishes its own proc-tagged gauge;
    # the cluster-wide depth is their SUM per deployment)
    depth_by_dep: Dict[str, float] = {}
    for key, v in merged.get("serve_queue_depth", {}).get("values", {}).items():
        tags = dict(key)
        label = f"{tags.get('app', '?')}/{tags.get('deployment', '?')}"
        depth_by_dep[label] = depth_by_dep.get(label, 0.0) + v
    ttft = merged.get("serve_ttft_seconds")
    status["serve"] = {
        "ttft_p50_s": m.histogram_quantile(ttft, 0.5) if ttft else None,
        "ttft_p99_s": m.histogram_quantile(ttft, 0.99) if ttft else None,
        "queue_depth": depth_by_dep,
        "requests": int(sum(v["count"] for v in merged.get(
            "serve_request_seconds", {}).get("values", {}).values())),
    }
    # -- serve autoscale loop (head-side): live targets + decision counters
    decisions_by_reason = {k: int(v) for k, v in counter_by_tag(
        "serve_autoscale_decisions_total", "reason").items()}
    autoscale: Dict[str, Any] = {}
    if global_state.try_cluster() is not None:
        from ray_tpu.serve.autoscaler import get_serve_autoscaler

        loop = get_serve_autoscaler()
        if loop is not None:
            st = loop.status()
            autoscale = {
                "alive": st["alive"],
                "ticks": st["ticks"],
                "targets": {k: {kk: v.get(kk) for kk in
                                ("target", "running", "queue_depth",
                                 "burning", "reason")}
                            for k, v in st["deployments"].items()},
                "last_decision": (st["decisions"][-1]
                                  if st["decisions"] else None),
            }
    if autoscale or decisions_by_reason:
        autoscale["decisions_by_reason"] = decisions_by_reason
        status["serve"]["autoscale"] = autoscale

    # -- llm engines
    llm_ttft = merged.get("llm_ttft_seconds")
    tok_rate = merged.get("llm_tokens_per_s")
    burst_rate = merged.get("llm_burst_tokens_per_s")
    kv_handoff = merged.get("llm_kv_handoff_gbps")
    status["llm"] = {
        "ttft_p50_s": m.histogram_quantile(llm_ttft, 0.5) if llm_ttft else None,
        "ttft_p99_s": m.histogram_quantile(llm_ttft, 0.99) if llm_ttft else None,
        "tokens_per_s_p50": m.histogram_quantile(tok_rate, 0.5) if tok_rate else None,
        # per-burst engine throughput (one observation per fused K-step burst
        # — truthful under fused decode, where per-host-step numbers would
        # overcount) + total tokens for windowed rates via metrics_history
        "burst_tokens_per_s_p50": (m.histogram_quantile(burst_rate, 0.5)
                                   if burst_rate else None),
        "generated_tokens": int(counter_total("llm_generated_tokens_total")),
        "fused_steps": gauges("llm_decode_fused_steps"),
        "host_sync_fraction": gauges("llm_decode_host_sync_fraction"),
        "pending": gauges("llm_num_pending"),
        "active": gauges("llm_num_active"),
        "prefix_cache_hits": int(counter_total("llm_prefix_cache_hits_total")),
        "prefix_cache_misses": int(counter_total("llm_prefix_cache_misses_total")),
        "prefix_cache_skipped": int(counter_total("llm_num_prefix_skipped")),
        # P/D disaggregation: per-handoff KV transfer rate (paged pulls and
        # monolithic fetches both observe; tagged by mode in the registry)
        "kv_handoff_gbps_p50": (m.histogram_quantile(kv_handoff, 0.5)
                                if kv_handoff else None),
        "kv_handoff_gbps_p99": (m.histogram_quantile(kv_handoff, 0.99)
                                if kv_handoff else None),
    }

    # -- control plane: the observability pipeline observing itself (PR 17).
    # Scrape/decision latency percentiles, inlet pressure, node-aggregation
    # coverage, cardinality-guard drops — the numbers that say whether the
    # head itself is the bottleneck at fleet scale.
    scrape = merged.get("control_scrape_seconds")
    decision = merged.get("control_decision_seconds")
    cp: Dict[str, Any] = {
        "scrape_p50_s": m.histogram_quantile(scrape, 0.5) if scrape else None,
        "scrape_p99_s": m.histogram_quantile(scrape, 0.99) if scrape else None,
        "decision_p99_s": {
            loop: m.histogram_quantile(decision, 0.99, where={"loop": loop})
            for loop in sorted({dict(key).get("loop", "?")
                                for key in (decision or {}).get("values", {})})
        } if decision else {},
        "inlet_frames": gauges("control_inlet_frames").get("_"),
        "backpressure_level": gauges("control_backpressure_level").get("_"),
        "backpressure_transitions": int(counter_total(
            "control_backpressure_transitions_total")),
        "inlet_shed": int(counter_total("control_inlet_shed_total")),
        "dropped_series": {k: int(v) for k, v in counter_by_tag(
            m.DROPPED_SERIES_METRIC, "metric").items()},
    }
    c = global_state.try_cluster()
    if c is not None:
        cp["nodes_aggregated"] = len(getattr(c, "metrics_by_node", {}) or {})
        cp["workers_direct"] = len(getattr(c, "metrics_by_worker", {}) or {})
    status["control_plane"] = cp

    # -- train
    status["train"] = {
        "mfu": gauges("train_mfu"),
        "tokens_per_s": gauges("train_tokens_per_s"),
        "step_phases_s": {
            dict(key).get("phase", "?"): round(v["sum"] / v["count"], 6)
            for key, v in merged.get("train_step_phase_seconds",
                                     {}).get("values", {}).items()
            if v["count"]
        },
        # grad-sync phase breakdown (train/grad_sync.py telemetry mode):
        # mean seconds per phase — forward_backward / bucket_wait / optimizer
        "grad_sync_phases_s": {
            dict(key).get("phase", "?"): round(v["sum"] / v["count"], 6)
            for key, v in merged.get("train_grad_sync_seconds",
                                     {}).get("values", {}).items()
            if v["count"]
        },
        # MPMD pipeline idle fraction per stage (+ mean), published from the
        # merged train.pipeline_stage span timeline (train/mpmd_pipeline.py)
        "pipeline_bubble_fraction": {
            dict(key).get("stage", "?"): round(v, 4)
            for key, v in merged.get("train_pipeline_bubble_fraction",
                                     {}).get("values", {}).items()
        },
    }

    # -- rl: decoupled rollout/learn plane (rllib/rollout_plane.py). Block
    # lifecycle counters, staleness distribution at take time, queue depth —
    # the numbers that say whether the learner or the env pool is the
    # bottleneck and whether stale data is being trained on or dropped.
    block_lag = merged.get("rl_block_lag")
    status["rl"] = {
        "env_steps": int(counter_total("rl_env_steps_total")),
        "learner_updates": int(counter_total("rl_learner_updates_total")),
        "weight_broadcasts": int(counter_total("rl_weight_broadcasts_total")),
        "blocks": {k: int(v) for k, v in
                   counter_by_tag("rl_blocks_total", "event").items()},
        "block_pulls": {k: int(v) for k, v in
                        counter_by_tag("rl_block_pulls_total", "path").items()},
        "queue_depth": gauges("rl_queue_depth").get("_"),
        "block_lag_p50": (m.histogram_quantile(block_lag, 0.5)
                          if block_lag else None),
        "block_lag_p99": (m.histogram_quantile(block_lag, 0.99)
                          if block_lag else None),
    }
    return status


# ------------------------------------------------------------ metrics history

@_remoteable
def metrics_history(window_s: float = 60.0) -> Dict[str, Any]:
    """The head's retained metrics-history frames plus the windowed signals
    derived from them (util/metrics_history.py). Each frame is one merged
    cross-worker snapshot sampled by the background scraper
    (RAY_TPU_METRICS_SCRAPE_INTERVAL_S); `windowed` carries the
    bucket-differenced quantiles/rates over the last `window_s` seconds —
    the recent regime, not the lifetime blur lifetime counters give."""
    from ray_tpu.config import CONFIG

    c = _cluster()
    h = c.metrics_history
    windowed = {
        "serve_ttft_p50_s": h.quantile("serve_ttft_seconds", 0.5, window_s),
        "serve_ttft_p99_s": h.quantile("serve_ttft_seconds", 0.99, window_s),
        "serve_requests_per_s": h.rate("serve_request_seconds", window_s),
        "llm_ttft_p99_s": h.quantile("llm_ttft_seconds", 0.99, window_s),
        "transfer_bytes_per_s": h.rate("transfer_bytes_total", window_s),
        "collective_ops_per_s": h.rate("collective_ops_total", window_s),
    }
    return {
        "frames": h.frames(),
        "scrape_interval_s": CONFIG.metrics_scrape_interval_s,
        "window_s": window_s,
        "windowed": windowed,
    }


@_remoteable
def serve_latency_hint(window_s: float = 60.0) -> Dict[str, Optional[float]]:
    """Tiny windowed latency summary for admission control: the p50/p99 of
    RECENT serve request/TTFT latency from the metrics-history ring, without
    shipping the full frame dump metrics_history() returns. The proxies
    derive Retry-After from this (one recent service time ~= how long until
    a replica slot frees), cached caller-side between sheds."""
    c = _cluster()
    h = c.metrics_history
    return {
        "serve_request_p50_s": h.quantile("serve_request_seconds", 0.5, window_s),
        "serve_request_p99_s": h.quantile("serve_request_seconds", 0.99, window_s),
        "serve_ttft_p50_s": h.quantile("serve_ttft_seconds", 0.5, window_s),
        "serve_ttft_p99_s": h.quantile("serve_ttft_seconds", 0.99, window_s),
    }


@_remoteable
def history_series(window_s: float = 300.0) -> Dict[str, Any]:
    """JSON-safe per-frame time series for dashboards/sparklines
    (`/api/history`, `ray-tpu status --watch`): one timestamp list plus one
    value list per signal (None where a frame has no data). Derived signals
    (rates, windowed quantiles) are computed FRAME-over-frame so the series
    shows load shifts, not lifetime averages. Payloads are BOUNDED: more
    in-window frames than RAY_TPU_CONTROL_HISTORY_MAX_POINTS are stride-
    downsampled (newest kept) and more series than
    RAY_TPU_CONTROL_HISTORY_MAX_SERIES are dropped, with `truncated` set —
    a --watch refresh against a 1k-replica fleet must never ship megabytes."""
    from ray_tpu.config import CONFIG
    from ray_tpu.util import metrics as m

    c = _cluster()
    h = c.metrics_history
    all_frames = h.frames()
    truncated = False
    # frame-over-frame values need each frame's PREDECESSOR, so include ONE
    # frame before the window as a differencing seed (its own output is
    # discarded) — without it the first in-window point would difference
    # against nothing and show a lifetime value (a phantom spike at the
    # window edge); deriving over the ENTIRE ring instead would do
    # history_size/window times the needed bucket-difference work per hit
    if all_frames:
        newest = all_frames[-1]["ts"]
        keep = [i for i, f in enumerate(all_frames)
                if f["ts"] >= newest - window_s]
    else:
        keep = []
    max_points = CONFIG.control_history_max_points
    if max_points > 0 and len(keep) > max_points:
        # stride-downsample anchored at the NEWEST frame: the most recent
        # point is always retained, older points thin out evenly
        stride = -(-len(keep) // max_points)  # ceil
        keep = keep[::-1][::stride][::-1]
        truncated = True
    start = max(0, keep[0] - 1) if keep else 0
    frames = all_frames[start:]
    keep = [i - start for i in keep]
    ts = [round(frames[i]["ts"], 3) for i in keep]

    def sliced(series):
        return [series[i] for i in keep]

    def counter_total(frame, name):
        mm = frame["metrics"].get(name)
        if mm is None:
            return None
        if mm["type"] == "histogram":
            return float(sum(v["count"] for v in mm["values"].values()))
        return float(sum(mm["values"].values()))

    def gauge_sum(frame, name):
        mm = frame["metrics"].get(name)
        if mm is None:
            return None
        return float(sum(mm["values"].values()))

    def per_s(name):
        out, prev = [], None
        for f in frames:
            cur = counter_total(f, name)
            if cur is None or prev is None or f["ts"] <= prev[0]:
                out.append(None)
            else:
                out.append(round(max(0.0, cur - prev[1]) / (f["ts"] - prev[0]), 3))
            if cur is not None:
                prev = (f["ts"], cur)
        return out

    def frame_quantile(name, q):
        """q-quantile of each frame's NEW observations (bucket difference
        against the previous frame that carried the histogram — ONE shared
        implementation: metrics_history.diff_histogram). The very first
        retained frame has no predecessor -> None, never a lifetime value; a
        metric first appearing later differences against the implicit zero
        of "didn't exist yet", which is exact."""
        from ray_tpu.util.metrics_history import diff_histogram

        out, prev = [], None
        for i, f in enumerate(frames):
            mm = f["metrics"].get(name)
            if mm is None or mm.get("type") != "histogram":
                out.append(None)
                continue
            if prev is None and i == 0:
                # the ring may have evicted history: differencing the first
                # retained frame would show a lifetime value
                out.append(None)
                prev = mm
                continue
            q_v = m.histogram_quantile(diff_histogram(mm, prev), q)
            out.append(round(q_v, 6) if q_v is not None else None)
            prev = mm
        return out

    series = {
        "serve_ttft_p99_s": sliced(frame_quantile("serve_ttft_seconds", 0.99)),
        "serve_requests_per_s": sliced(per_s("serve_request_seconds")),
        "llm_ttft_p99_s": sliced(frame_quantile("llm_ttft_seconds", 0.99)),
        "transfer_bytes_per_s": sliced(per_s("transfer_bytes_total")),
        "collective_ops_per_s": sliced(per_s("collective_ops_total")),
        "serve_queue_depth": sliced([gauge_sum(f, "serve_queue_depth")
                                     for f in frames]),
    }
    max_series = CONFIG.control_history_max_series
    if max_series > 0 and len(series) > max_series:
        series = dict(list(series.items())[:max_series])
        truncated = True
    return {"ts": ts, "series": series, "truncated": truncated}


@_remoteable
def slo_status() -> Dict[str, Dict[str, Any]]:
    """Current state of every registered SLO (util/slo.py): burn rates over
    the long/short windows, ok|burning|no_data, the windowed observed value.
    The autoscaler/router closed loop polls this (or subscribes head-side via
    slo.subscribe_slo)."""
    return _cluster().slo_engine.status()


@_remoteable
def serve_autoscaler_status() -> Dict[str, Any]:
    """The serve autoscaling loop's introspection surface: whether the loop
    is alive, the last-seen per-deployment view (target/running/queue-depth/
    burning + the latest decision and reason), and the bounded decision
    journal — `ray-tpu status` and the chaos bench read this to explain WHY
    the fleet resized."""
    _cluster()  # head-side state only
    from ray_tpu.serve.autoscaler import get_serve_autoscaler

    loop = get_serve_autoscaler()
    if loop is None:
        return {"alive": False, "ticks": 0, "deployments": {}, "decisions": []}
    return loop.status()


# -------------------------------------------------------- request-scoped trace

_PHASES = ("queue", "prefill", "decode", "transfer")


def _phase_of(name: str, cat: str = "") -> Optional[str]:
    """Critical-path bucket for a span/event name. Container spans (serve
    ingress, task execution) stay None — they ARE the wall clock being
    attributed, not a phase of it."""
    if name == "llm.queue":
        return "queue"
    if name == "llm.prefill":
        return "prefill"
    if name == "llm.decode":
        return "decode"
    if name.startswith("transfer.") or cat == "transfer":
        return "transfer"
    return None


def _attribute(intervals: List, t0: float, t1: float) -> Dict[str, float]:
    """Sweep [t0, t1]: each elementary segment is charged to the
    highest-priority phase covering it (queue > prefill > decode > transfer),
    remainder to "other" — phases stay disjoint, so the attribution sums to
    the window EXACTLY even when phase spans overlap."""
    marks = {t0, t1}
    clipped = []
    for s, e, phase in intervals:
        s, e = max(s, t0), min(e, t1)
        if e > s:
            clipped.append((s, e, phase))
            marks.add(s)
            marks.add(e)
    pts = sorted(marks)
    out = {p: 0.0 for p in _PHASES}
    out["other"] = 0.0
    prio = {p: i for i, p in enumerate(_PHASES)}
    for a, b in zip(pts, pts[1:]):
        mid = (a + b) / 2
        covering = [phase for s, e, phase in clipped if s <= mid < e]
        phase = min(covering, key=lambda p: prio[p]) if covering else "other"
        out[phase] += b - a
    return {k: round(v, 6) for k, v in out.items()}


@_remoteable
def request_trace(trace_id: str) -> Dict[str, Any]:
    """Reconstruct one request's critical path: every tracing span with this
    trace_id (proxy ingress -> handle -> replica -> engine, across
    processes), every telemetry event tagged with it (data-plane pulls,
    engine queue/prefill/decode phases), the span tree, and a wall-time
    attribution over queue/prefill/decode/transfer/other that sums to the
    root span's duration. `ray-tpu trace <trace_id>` renders this."""
    spans = [s for s in get_trace() if s.get("trace_id") == trace_id]
    events = [e for e in get_telemetry()
              if (e.get("args") or {}).get("trace_id") == trace_id]
    if not spans and not events:
        return {"trace_id": trace_id, "found": False, "spans": [],
                "events": [], "processes": [], "attribution": {},
                "total_s": 0.0}

    by_id = {s["span_id"]: s for s in spans}
    children: Dict[str, List[dict]] = {}
    roots = []
    for s in spans:
        parent = s.get("parent_span_id", "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s["start_time"])
    roots.sort(key=lambda s: s["start_time"])

    # the attribution window: the earliest root span (the ingress) — or the
    # envelope of everything collected when only telemetry events matched
    ev_bounds = [(e["ts_ns"] / 1e9, (e["ts_ns"] + (e["dur_ns"] or 0)) / 1e9)
                 for e in events]
    if roots:
        t0 = roots[0]["start_time"]
        t1 = max(r.get("end_time", t0) for r in roots)
    else:
        t0 = min(b[0] for b in ev_bounds)
        t1 = max(b[1] for b in ev_bounds)

    intervals = []
    for e in events:
        phase = _phase_of(e.get("name", ""), e.get("cat", ""))
        if phase and e.get("dur_ns"):
            s = e["ts_ns"] / 1e9
            intervals.append((s, s + e["dur_ns"] / 1e9, phase))
    for s in spans:
        phase = _phase_of(s.get("name", ""))
        if phase and "end_time" in s:
            intervals.append((s["start_time"], s["end_time"], phase))

    tree = []

    def walk(span, depth):
        tree.append({
            "name": span["name"], "span_id": span["span_id"],
            "parent_span_id": span.get("parent_span_id", ""),
            "depth": depth, "pid": span.get("pid"),
            "start_s": round(span["start_time"] - t0, 6),
            "dur_s": round(span.get("end_time", span["start_time"])
                           - span["start_time"], 6),
            "attributes": span.get("attributes", {}),
        })
        for kid in children.get(span["span_id"], ()):
            walk(kid, depth + 1)

    for r in roots:
        walk(r, 0)

    procs = sorted({f"pid-{s['pid']}" for s in spans if s.get("pid")}
                   | {e["proc"] for e in events if e.get("proc")})
    return {
        "trace_id": trace_id,
        "found": True,
        "total_s": round(t1 - t0, 6),
        "attribution": _attribute(intervals, t0, t1),
        "spans": tree,
        "events": [{"name": e.get("name"), "cat": e.get("cat"),
                    "proc": e.get("proc"), "start_s": round(e["ts_ns"] / 1e9 - t0, 6),
                    "dur_s": round((e.get("dur_ns") or 0) / 1e9, 6),
                    "phase": _phase_of(e.get("name", ""), e.get("cat", ""))}
                   for e in sorted(events, key=lambda e: e["ts_ns"])],
        "processes": procs,
    }


# -------------------------------------------------------------------- timeline

@_remoteable
def timeline_events() -> List[Dict[str, Any]]:
    """Chrome-trace events for finished tasks (no file IO — remotely callable)."""
    c = _cluster()
    events = []
    with c._lock:
        evs = list(c.task_events)
    for ev in evs:
        if ev["dispatched_at"] is None:
            continue
        events.append({
            "cat": "task",
            "ph": "X",  # complete event
            "name": ev["name"],
            "pid": ev["node_id"][:8],
            "tid": ev["worker_id"][:8],
            "ts": ev["dispatched_at"] * 1e6,
            "dur": (ev["finished_at"] - ev["dispatched_at"]) * 1e6,
            "args": {"task_id": ev["task_id"], "error": ev["error"]},
        })
    return events


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace export (reference ray.timeline, python/ray/_private/
    state.py:986). The file, if requested, is written by THIS process — a remote
    client's filename never touches the head's filesystem."""
    events = timeline_events()
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


@_remoteable
def get_worker_stacks(timeout_s: float = 5.0) -> Dict[str, str]:
    """Per-process thread stack dumps (reference: py-spy via the dashboard
    reporter module, python/ray/dashboard/modules/reporter/) — dependency-free:
    workers introspect sys._current_frames() on their recv thread."""
    return _cluster().dump_worker_stacks(timeout_s)


@_remoteable
def profile_workers(duration_s: float = 2.0, hz: float = 100.0) -> Dict[str, Dict[str, int]]:
    """Sampling profile of every live worker + driver: collapsed stacks
    ("thread;frame;frame" -> sample count, flamegraph.pl format). The
    `py-spy record` analogue of the reference's reporter profiling endpoints."""
    return _cluster().profile_workers(duration_s=duration_s, hz=hz)


def profile_to_speedscope(profiles: Dict[str, Dict[str, int]]) -> Dict[str, Any]:
    """Render profile_workers() output as a speedscope-importable document
    (one 'sampled' profile per process; https://speedscope.app file format)."""
    frames: List[Dict[str, str]] = []
    index: Dict[str, int] = {}

    def fid(name: str) -> int:
        if name not in index:
            index[name] = len(frames)
            frames.append({"name": name})
        return index[name]

    profs = []
    for proc, counts in sorted(profiles.items()):
        samples, weights = [], []
        for collapsed, n in counts.items():
            stack = [fid(part) for part in collapsed.split(";")]
            samples.append(stack)
            weights.append(n)
        profs.append({
            "type": "sampled", "name": proc, "unit": "none",
            "startValue": 0, "endValue": sum(weights) or 1,
            "samples": samples, "weights": weights,
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profs,
    }
