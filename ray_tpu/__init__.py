"""ray_tpu: a TPU-native distributed compute framework.

Tasks / actors / objects core runtime (reference capability: Ray Core), with JAX/XLA as
the tensor substrate: collectives ride ICI inside compiled programs instead of NCCL, and
the AI libraries (train/ data/ rllib/ serve/ tune/) are JAX-first.

NOTE: importing ray_tpu does NOT import jax — the core runtime is accelerator-agnostic
and worker processes decide platform visibility at spawn time.
"""
from ._version import __version__  # noqa: F401
from .core.actor import ActorClass, ActorHandle, method  # noqa: F401
from .core.api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from .core.exceptions import (  # noqa: F401
    ActorDiedError,
    ActorError,
    BackPressureError,
    FaultInjectedError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    RayTpuError,
    ReplicaUnavailableError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .core.object_ref import ObjectRef  # noqa: F401
from .core.runtime_context import get_runtime_context  # noqa: F401
from .core.task_spec import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from .runtime_env import RuntimeEnv  # noqa: F401

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
    "TaskError",
    "ActorError",
    "ActorDiedError",
    "WorkerCrashedError",
    "GetTimeoutError",
    "TaskCancelledError",
    "ObjectLostError",
    "OutOfMemoryError",
    "RayTpuError",
    "ReplicaUnavailableError",
    "BackPressureError",
    "FaultInjectedError",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
