"""Batch LLM inference over ray_tpu.data Datasets.

Capability parity: reference python/ray/llm/_internal/batch/processor/base.py:107
(``Processor`` — a chain of stages applied to a Dataset) and stages/ (chat template,
tokenize, engine, detokenize). The engine stage is a stateful actor UDF holding a
``JaxLLMEngine`` (reference vllm_engine_stage.py), so the model loads once per
actor and each data block rides the continuous batcher.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .config import LLMConfig, SamplingParams
from .server import render_chat_template


class ChatTemplateStage:
    """messages -> prompt string (reference chat_template_stage.py)."""

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        prompts = [render_chat_template(m) for m in batch["messages"]]
        out = dict(batch)
        out["prompt"] = np.array(prompts, dtype=object)
        return out


class TokenizeStage:
    """prompt -> input token ids (reference tokenize_stage.py). Stateful actor
    UDF so the tokenizer loads once per actor."""

    def __init__(self, tokenizer_spec: str):
        from .tokenizer import get_tokenizer

        self.tokenizer = get_tokenizer(tokenizer_spec)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = dict(batch)
        ids = [self.tokenizer.encode(str(p)) for p in batch["prompt"]]
        # np.array(list, dtype=object) silently coerces equal-length lists to 2-D,
        # which would emit fixed_size_list arrow columns that can't concat with
        # ragged batches — fill an object array per element instead
        col = np.empty(len(ids), dtype=object)
        for i, t in enumerate(ids):
            col[i] = np.asarray(t, np.int32)
        out["tokenized_prompt"] = col
        out["num_prompt_tokens"] = np.array([len(i) for i in ids], np.int64)
        return out


class DetokenizeStage:
    """generated token ids -> text (reference detokenize_stage.py)."""

    def __init__(self, tokenizer_spec: str):
        from .tokenizer import get_tokenizer

        self.tokenizer = get_tokenizer(tokenizer_spec)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = dict(batch)
        out["generated_text"] = np.array(
            [self.tokenizer.decode(list(ids)) for ids in batch["generated_tokens"]],
            dtype=object,
        )
        return out


class HttpRequestStage:
    """POST each row to an OpenAI-compatible endpoint (reference
    http_request_stage.py) — batch inference against an already-running
    server (e.g. a serve.run(build_openai_app(...)) deployment) instead of an
    in-actor engine."""

    def __init__(self, url: str, *, model: str = "", sampling_params: Optional[Dict[str, Any]] = None,
                 headers: Optional[Dict[str, str]] = None, timeout_s: float = 120.0,
                 concurrency: int = 8, max_retries: int = 2):
        self.url = url
        self.model = model
        self.sampling_params = dict(sampling_params or {})
        self.headers = {"Content-Type": "application/json", **(headers or {})}
        self.timeout_s = timeout_s
        self.concurrency = max(1, concurrency)
        self.max_retries = max_retries

    def _post(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        import json
        import time
        import urllib.error
        import urllib.request

        for attempt in range(self.max_retries + 1):
            try:
                req = urllib.request.Request(
                    self.url, data=json.dumps(payload).encode(), headers=self.headers)
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read())
            except (urllib.error.URLError, OSError):
                if attempt == self.max_retries:
                    raise
                time.sleep(0.5 * 2**attempt)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        import concurrent.futures

        def one(prompt) -> str:
            payload = {"model": self.model, "prompt": str(prompt), **self.sampling_params}
            resp = self._post(payload)
            choice = resp["choices"][0]
            return choice.get("text") or choice.get("message", {}).get("content", "")

        # I/O-bound: the serving side batches concurrent requests, so fan out
        with concurrent.futures.ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            texts = list(pool.map(one, batch["prompt"]))
        out = dict(batch)
        out["generated_text"] = np.array(texts, dtype=object)
        return out


class PrepareImageStage:
    """Resolve image references into fixed-size pixel tensors for a VLM engine
    (reference batch/stages/prepare_image_stage.py — ImageProcessor resolving
    http/data-URI/PIL/ndarray image refs out of chat messages).

    Sources handled, per row: an ``image`` column (ndarray / raw encoded bytes
    / file path / data URI), or OpenAI-vision ``messages`` content parts
    ({"type": "image_url", "image_url": {"url": ...}}). Every image lands as a
    float32 [H, W, 3] tensor in [0, 1] at a fixed ``size`` — static shapes so
    the downstream engine stage jits one program (TPU-shaped batching, unlike
    the reference's variable-size PIL passthrough)."""

    def __init__(self, size=(224, 224), mode: str = "RGB"):
        self.size = tuple(size)
        self.mode = mode

    def _decode(self, ref) -> np.ndarray:
        import base64
        import io

        from PIL import Image

        if isinstance(ref, np.ndarray) and ref.ndim >= 2:
            a = ref
            if a.dtype.kind == "f":
                # scale-aware: [0,1] floats (this stage's own output format)
                # must not truncate to all-black via a blind uint8 cast
                a = a * 255.0 if float(a.max(initial=0.0)) <= 1.0 else a
            img = Image.fromarray(np.clip(a, 0, 255).astype(np.uint8))
        elif isinstance(ref, (bytes, bytearray)):
            img = Image.open(io.BytesIO(ref))
        elif isinstance(ref, str) and ref.startswith("data:"):
            b64 = ref.split(",", 1)[1]
            img = Image.open(io.BytesIO(base64.b64decode(b64)))
        elif isinstance(ref, str) and ref.startswith(("http://", "https://")):
            import urllib.request

            with urllib.request.urlopen(ref, timeout=30) as r:
                img = Image.open(io.BytesIO(r.read()))
        elif isinstance(ref, str):
            img = Image.open(ref)
        else:
            raise TypeError(f"unsupported image reference {type(ref)!r}")
        img = img.convert(self.mode).resize((self.size[1], self.size[0]))
        return np.asarray(img, np.float32) / 255.0

    @staticmethod
    def _refs_from_messages(messages) -> List[Any]:
        refs = []
        for m in messages:
            content = m.get("content")
            if not isinstance(content, (list, tuple)):
                continue
            for part in content:
                if isinstance(part, dict) and part.get("type") == "image_url":
                    url = part.get("image_url")
                    refs.append(url.get("url") if isinstance(url, dict) else url)
        return refs

    @staticmethod
    def to_tensor(images, size=(224, 224)) -> np.ndarray:
        """Re-materialize one row's ``images`` value as a dense
        [n, H, W, 3] float32 tensor — after a block boundary the column
        round-trips as nested lists (and empty rows as shape (0,))."""
        return np.asarray(images, np.float32).reshape(-1, *size, 3)

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        n = len(next(iter(batch.values())))
        col = np.empty(n, dtype=object)
        counts = np.zeros(n, np.int64)
        for i in range(n):
            refs: List[Any] = []
            if "image" in batch:
                refs.append(batch["image"][i])
            if "messages" in batch:
                refs.extend(self._refs_from_messages(batch["messages"][i]))
            pixels = [self._decode(r) for r in refs]
            # NOTE a block boundary stores this ragged tensor column as nested
            # lists; consumers re-materialize with to_tensor() (zero-image rows
            # round-trip as shape (0,), hence the reshape there)
            col[i] = (np.stack(pixels) if pixels
                      else np.zeros((0, *self.size, 3), np.float32))
            counts[i] = len(pixels)
        out = dict(batch)
        out["images"] = col
        out["num_images"] = counts
        return out


class LLMEngineStage:
    """Stateful actor UDF running generation (reference vllm_engine_stage.py)."""

    def __init__(self, llm_config: LLMConfig, sampling_params: Optional[Dict[str, Any]] = None):
        from .engine import JaxLLMEngine

        self.engine = JaxLLMEngine(llm_config)
        self.engine.start()
        self.params = SamplingParams(**(sampling_params or {}))

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        import concurrent.futures

        prompts = list(batch["prompt"])
        if not prompts:  # an upstream filter can empty a block
            out = dict(batch)
            out["generated_text"] = np.array([], dtype=object)
            out["num_generated_tokens"] = np.array([], np.int64)
            return out

        # Feed prompts concurrently so the continuous batcher fills its slots,
        # but bound the fan-out: the engine admits at burst boundaries, so 2x
        # the slot count keeps every freed slot instantly refillable while a
        # 10k-row block doesn't spawn 10k parked threads.
        workers = min(len(prompts),
                      max(1, 2 * self.engine.config.max_num_seqs))
        with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as pool:
            results = list(pool.map(
                lambda p: self.engine.generate_sync(str(p), self.params),
                prompts))
        out = dict(batch)
        out["generated_text"] = np.array([r.text for r in results], dtype=object)
        out["num_generated_tokens"] = np.array(
            [r.num_generated_tokens for r in results], np.int64
        )
        return out


class Processor:
    """A configured chain of stages over a Dataset (reference base.py:107)."""

    def __init__(self, stages: List[Any]):
        self.stages = stages

    def __call__(self, dataset):
        for stage in self.stages:
            dataset = stage(dataset)
        return dataset


def build_llm_processor(
    llm_config: LLMConfig,
    *,
    sampling_params: Optional[Dict[str, Any]] = None,
    preprocess: Optional[Callable] = None,
    postprocess: Optional[Callable] = None,
    batch_size: int = 16,
    concurrency: int = 1,
    has_messages: bool = False,
    prepare_images: bool = False,
    image_size=(224, 224),
) -> Processor:
    """Build the standard chat->generate processor (reference build_llm_processor).
    prepare_images=True inserts the VLM image stage (pixel tensors resolved
    from image refs / vision messages) ahead of generation."""

    stages: List[Any] = []
    if preprocess is not None:
        stages.append(lambda ds: ds.map(preprocess))
    if prepare_images:
        stages.append(lambda ds: ds.map_batches(
            PrepareImageStage(size=image_size), batch_size=batch_size))
    if has_messages:
        stages.append(lambda ds: ds.map_batches(ChatTemplateStage(), batch_size=batch_size))
    stages.append(
        lambda ds: ds.map_batches(
            LLMEngineStage,
            fn_constructor_args=(llm_config, sampling_params),
            batch_size=batch_size,
            concurrency=concurrency,
        )
    )
    if postprocess is not None:
        stages.append(lambda ds: ds.map(postprocess))
    return Processor(stages)
