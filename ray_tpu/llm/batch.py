"""Batch LLM inference over ray_tpu.data Datasets.

Capability parity: reference python/ray/llm/_internal/batch/processor/base.py:107
(``Processor`` — a chain of stages applied to a Dataset) and stages/ (chat template,
tokenize, engine, detokenize). The engine stage is a stateful actor UDF holding a
``JaxLLMEngine`` (reference vllm_engine_stage.py), so the model loads once per
actor and each data block rides the continuous batcher.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .config import LLMConfig, SamplingParams
from .server import render_chat_template


class ChatTemplateStage:
    """messages -> prompt string (reference chat_template_stage.py)."""

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        prompts = [render_chat_template(m) for m in batch["messages"]]
        out = dict(batch)
        out["prompt"] = np.array(prompts, dtype=object)
        return out


class LLMEngineStage:
    """Stateful actor UDF running generation (reference vllm_engine_stage.py)."""

    def __init__(self, llm_config: LLMConfig, sampling_params: Optional[Dict[str, Any]] = None):
        from .engine import JaxLLMEngine

        self.engine = JaxLLMEngine(llm_config)
        self.engine.start()
        self.params = SamplingParams(**(sampling_params or {}))

    def __call__(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        import queue as _q
        import threading

        prompts = list(batch["prompt"])
        results: List[Any] = [None] * len(prompts)

        # Feed all prompts concurrently so the continuous batcher fills its slots.
        def worker(i):
            results[i] = self.engine.generate_sync(str(prompts[i]), self.params)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        out = dict(batch)
        out["generated_text"] = np.array([r.text for r in results], dtype=object)
        out["num_generated_tokens"] = np.array(
            [r.num_generated_tokens for r in results], np.int64
        )
        return out


class Processor:
    """A configured chain of stages over a Dataset (reference base.py:107)."""

    def __init__(self, stages: List[Any]):
        self.stages = stages

    def __call__(self, dataset):
        for stage in self.stages:
            dataset = stage(dataset)
        return dataset


def build_llm_processor(
    llm_config: LLMConfig,
    *,
    sampling_params: Optional[Dict[str, Any]] = None,
    preprocess: Optional[Callable] = None,
    postprocess: Optional[Callable] = None,
    batch_size: int = 16,
    concurrency: int = 1,
    has_messages: bool = False,
) -> Processor:
    """Build the standard chat->generate processor (reference build_llm_processor)."""

    stages: List[Any] = []
    if preprocess is not None:
        stages.append(lambda ds: ds.map(preprocess))
    if has_messages:
        stages.append(lambda ds: ds.map_batches(ChatTemplateStage(), batch_size=batch_size))
    stages.append(
        lambda ds: ds.map_batches(
            LLMEngineStage,
            fn_constructor_args=(llm_config, sampling_params),
            batch_size=batch_size,
            concurrency=concurrency,
        )
    )
    if postprocess is not None:
        stages.append(lambda ds: ds.map(postprocess))
    return Processor(stages)
