"""Paged KV cache: block-granular memory for continuous batching.

Reference capability: vLLM's PagedAttention block tables (the engine the
reference wraps, vllm_models.py:125-139) — the slot cache reserves
max_model_len tokens per slot up front, so HBM caps max_num_seqs at
slots x max_model_len x layers; paging shares one block pool across slots and
allocates per BLOCK_SIZE tokens, so many short sequences (or few long ones) fit
the same memory. All shapes stay static for XLA: the pool is
[L, num_blocks, block, kv_heads, head_dim], each slot owns a fixed-width block
table [max_blocks] of pool indices, and reads gather / writes scatter through
the table.

Host-side: _BlockManager hands out pool indices; when the pool is exhausted the
engine preempts the youngest request and re-prefills it later (vLLM's
recompute preemption).
"""
from __future__ import annotations

import functools
import hashlib
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.ops.quant import as_weight as _qw
from ray_tpu.models.config import ModelConfig

from . import sampling


class PagedState(NamedTuple):
    """Device-resident paged serving state.

    k/v: [L, num_blocks, block_size, kv_heads, head_dim] — the shared pool.
    block_tables: [slots, max_blocks] int32 pool indices (junk entries are
        masked by lengths at read time).
    lengths: [slots] int32 tokens cached per slot.
    """

    k: jax.Array
    v: jax.Array
    block_tables: jax.Array
    lengths: jax.Array


POOL_SPEC = P(None, None, None, "tp", None)


def init_paged_state(cfg: ModelConfig, slots: int, max_len: int, num_blocks: int,
                     block_size: int, mesh: Optional[Mesh] = None) -> PagedState:
    """The pool gets ONE extra physical block (index num_blocks): inactive slots'
    decode writes are redirected there — their block-table entries may reference
    blocks already released and re-owned by other requests."""
    max_blocks = max_len // block_size
    shape = (cfg.n_layers, num_blocks + 1, block_size, cfg.n_kv_heads, cfg.head_dim)
    dtype = cfg.activation_dtype
    k = jnp.zeros(shape, dtype)
    v = jnp.zeros(shape, dtype)
    bt = jnp.zeros((slots, max_blocks), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    if mesh is not None:
        k = jax.device_put(k, NamedSharding(mesh, POOL_SPEC))
        v = jax.device_put(v, NamedSharding(mesh, POOL_SPEC))
        bt = jax.device_put(bt, NamedSharding(mesh, P()))
        lengths = jax.device_put(lengths, NamedSharding(mesh, P()))
    return PagedState(k=k, v=v, block_tables=bt, lengths=lengths)


class _BlockManager:
    """Host-side free list + per-slot allocation bookkeeping, with a
    prefix cache (reference: vLLM automatic prefix caching): full prompt
    blocks are content-addressed by a hash CHAIN (block key = H(parent key,
    block tokens)), shared across slots via refcounts, and kept around at
    refcount 0 until the pool needs the space (LRU eviction)."""

    def __init__(self, num_blocks: int, block_size: int, max_blocks_per_slot: int,
                 slots: int, enable_prefix_caching: bool = True):
        self.block_size = block_size
        self.max_blocks = max_blocks_per_slot
        self.total_blocks = num_blocks
        self.free: List[int] = list(range(num_blocks))
        self.owned: List[List[int]] = [[] for _ in range(slots)]  # includes shared
        self.shared: List[List[int]] = [[] for _ in range(slots)]  # shared subset
        self.enable_prefix_caching = enable_prefix_caching
        self.cached: Dict[bytes, int] = {}  # chain key -> block id
        self.block_key: Dict[int, bytes] = {}
        self.refs: Dict[int, int] = {}  # cached block id -> live references
        self._lru: Dict[int, int] = {}  # ref-0 cached block -> last-use tick
        self._tick = 0
        self.hit_tokens = 0  # metrics: prompt tokens served from the cache

    @staticmethod
    def chain_keys(prompt: List[int], block_size: int) -> List[bytes]:
        """Hash-chain keys for each FULL block of the prompt."""
        keys = []
        parent = b""
        for start in range(0, (len(prompt) // block_size) * block_size, block_size):
            h = hashlib.sha256(parent)
            h.update(np.asarray(prompt[start:start + block_size], np.int64).tobytes())
            parent = h.digest()
            keys.append(parent)
        return keys

    @property
    def num_free(self) -> int:
        # ref-0 cached blocks are reclaimable on demand
        return len(self.free) + len(self._lru)

    def blocks_needed(self, tokens: int) -> int:
        return -(-tokens // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return self.num_free >= n

    def _take_free(self) -> int:
        if self.free:
            return self.free.pop()
        # evict the least-recently-used unreferenced cached block
        victim = min(self._lru, key=self._lru.get)
        self._lru.pop(victim)
        key = self.block_key.pop(victim)
        self.cached.pop(key, None)
        self.refs.pop(victim, None)
        return victim

    def allocate(self, slot: int, n: int) -> List[int]:
        assert self.num_free >= n, "pool exhausted (caller must check/preempt)"
        got = [self._take_free() for _ in range(n)]
        self.owned[slot].extend(got)
        return got

    def match_prefix(self, slot: int, prompt: List[int]) -> List[int]:
        """Attach the longest cached block chain for this prompt to the slot
        (bumping refcounts); returns the matched block ids in order. Always
        leaves >= 1 prompt token uncached so prefill still produces the
        last-token logits."""
        if not self.enable_prefix_caching:
            return []
        usable = len(prompt) - 1  # the final token must be computed
        matched: List[int] = []
        for key in self.chain_keys(prompt[:usable] if usable > 0 else [],
                                   self.block_size):
            bid = self.cached.get(key)
            if bid is None:
                break
            matched.append(bid)
        if matched:
            # round DOWN to a power of two of blocks: every distinct attached
            # count is a fresh XLA specialization of the gather/suffix-prefill
            # programs, so bound them like the prefill buckets do
            matched = matched[: 1 << (len(matched).bit_length() - 1)]
        for bid in matched:
            if self.refs.get(bid, 0) == 0:
                self._lru.pop(bid, None)
            self.refs[bid] = self.refs.get(bid, 0) + 1
        self.owned[slot].extend(matched)
        self.shared[slot].extend(matched)
        return matched

    def register_blocks(self, slot: int, prompt: List[int],
                        block_ids: List[int], skip_blocks: int) -> None:
        """Publish a slot's freshly filled FULL prompt blocks into the cache
        (the slot keeps them as shared from now on)."""
        if not self.enable_prefix_caching:
            return
        keys = self.chain_keys(prompt, self.block_size)
        for i, key in enumerate(keys):
            if i < skip_blocks:
                continue  # already cached (matched prefix)
            if i >= len(block_ids):
                break
            bid = block_ids[i]
            if key in self.cached:
                continue  # raced by an identical prompt; keep ours private
            self.cached[key] = bid
            self.block_key[bid] = key
            self.refs[bid] = self.refs.get(bid, 0) + 1
            if bid in self.owned[slot] and bid not in self.shared[slot]:
                self.shared[slot].append(bid)

    def release(self, slot: int) -> None:
        shared = set(self.shared[slot])
        self._tick += 1
        for bid in self.owned[slot]:
            if bid in shared:
                self.refs[bid] = self.refs.get(bid, 1) - 1
                if self.refs[bid] <= 0:
                    self.refs[bid] = 0
                    self._lru[bid] = self._tick  # reclaimable, still cached
            else:
                self.free.append(bid)
        self.owned[slot] = []
        self.shared[slot] = []

    def slot_capacity(self, slot: int) -> int:
        return len(self.owned[slot]) * self.block_size


# ----------------------------------------------------------------- prefill install

@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("n_blocks",))
def install_prefill(
    state: PagedState,
    k: jax.Array,  # [L, 1, S_pad, KV, HD] from prefill_detached
    v: jax.Array,
    block_ids: jax.Array,  # [n_blocks] int32 pool indices (S_pad = n_blocks*bs)
    true_len: jax.Array,  # scalar int32
    slot: jax.Array,  # scalar int32
    n_blocks: int,
) -> PagedState:
    """Scatter a prompt's KV into its allocated blocks and fill the block table."""
    L = state.k.shape[0]
    bs = state.k.shape[2]
    kb = k[:, 0].reshape(L, n_blocks, bs, *k.shape[3:]).astype(state.k.dtype)
    vb = v[:, 0].reshape(L, n_blocks, bs, *v.shape[3:]).astype(state.v.dtype)
    nk = state.k.at[:, block_ids].set(kb)
    nv = state.v.at[:, block_ids].set(vb)
    table_row = jnp.zeros((state.block_tables.shape[1],), jnp.int32)
    table_row = jax.lax.dynamic_update_slice(table_row, block_ids, (0,))
    bt = state.block_tables.at[slot].set(table_row)
    lengths = state.lengths.at[slot].set(true_len)
    return PagedState(k=nk, v=nv, block_tables=bt, lengths=lengths)


@functools.partial(jax.jit, donate_argnames=("state",))
def append_block(state: PagedState, slot: jax.Array, index: jax.Array,
                 block_id: jax.Array) -> PagedState:
    """Record a newly allocated decode block in a slot's table."""
    bt = state.block_tables.at[slot, index].set(block_id)
    return state._replace(block_tables=bt)


# ----------------------------------------------------------------- prefix cache

@functools.partial(jax.jit, static_argnames=("n_blocks",))
def gather_blocks(state: PagedState, block_ids: jax.Array, n_blocks: int):
    """Cached prefix blocks -> contiguous KV context [L, 1, n*bs, KV, HD]."""
    kb = state.k[:, block_ids]  # [L, n, bs, KV, HD]
    vb = state.v[:, block_ids]
    L, _, bs = kb.shape[0], kb.shape[1], kb.shape[2]
    shape = (L, 1, n_blocks * bs) + kb.shape[3:]
    return kb.reshape(shape), vb.reshape(shape)


@functools.partial(jax.jit, static_argnames=("cfg", "n_blocks"))
def prefill_suffix_from_state(params, state: PagedState, block_ids: jax.Array,
                              tokens, true_suffix_len, cfg: ModelConfig,
                              n_blocks: int):
    """gather_blocks + prefill_suffix fused into ONE program: the warm
    (prefix-hit) path previously dispatched gather and suffix separately —
    an extra host->device round trip per request, which through a network
    tunnel costs more than the prefill compute it saves."""
    ctx_k, ctx_v = gather_blocks(state, block_ids, n_blocks)
    return _prefill_suffix_impl(params, ctx_k, ctx_v, tokens,
                                true_suffix_len, cfg)


def _prefill_suffix_impl(params, ctx_k, ctx_v, tokens, true_suffix_len,
                         cfg: ModelConfig):
    """Prefill ONLY the uncached suffix, attending over the cached-prefix KV
    context (reference: vLLM prefix caching skips recomputation of shared
    prompt prefixes). ctx_k/ctx_v: [L, 1, C, KV, HD]; tokens [1, S_pad].
    Returns (k_suffix [L, 1, S_pad, KV, HD], v_suffix, last_logits)."""
    cached_len = ctx_k.shape[2]
    s_pad = tokens.shape[1]
    dtype = cfg.activation_dtype
    pad = ((0, 0), (0, 0), (0, s_pad), (0, 0), (0, 0))
    cache = llama.KVCache(
        k=jnp.pad(ctx_k.astype(dtype), pad), v=jnp.pad(ctx_v.astype(dtype), pad),
        length=jnp.int32(cached_len))
    mask = (jnp.arange(s_pad)[None, :] < true_suffix_len).astype(jnp.float32)
    logits, cache = llama.forward(params, tokens, cfg, cache=cache, token_mask=mask)
    last = logits[0, true_suffix_len - 1].astype(jnp.float32)
    return (cache.k[:, :, cached_len:], cache.v[:, :, cached_len:], last)


prefill_suffix = functools.partial(jax.jit, static_argnames=("cfg",))(
    _prefill_suffix_impl)


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("n_new",))
def install_with_prefix(
    state: PagedState,
    k_suf: jax.Array,  # [L, 1, S_pad, KV, HD] — suffix KV only
    v_suf: jax.Array,
    new_ids: jax.Array,  # [n_new] pool indices for the suffix
    table_row: jax.Array,  # [max_blocks] full table (cached + new ids, padded)
    true_len: jax.Array,
    slot: jax.Array,
    n_new: int,
) -> PagedState:
    """Install suffix KV into fresh blocks; cached-prefix blocks are already in
    the pool and only need table entries."""
    L = state.k.shape[0]
    bs = state.k.shape[2]
    kb = k_suf[:, 0].reshape(L, n_new, bs, *k_suf.shape[3:]).astype(state.k.dtype)
    vb = v_suf[:, 0].reshape(L, n_new, bs, *v_suf.shape[3:]).astype(state.v.dtype)
    nk = state.k.at[:, new_ids].set(kb)
    nv = state.v.at[:, new_ids].set(vb)
    bt = state.block_tables.at[slot].set(table_row)
    lengths = state.lengths.at[slot].set(true_len)
    return PagedState(k=nk, v=nv, block_tables=bt, lengths=lengths)


# ------------------------------------------------------------------------- decode

def _decode_block_paged(x, lp, cfg: ModelConfig, pk, pv, block_tables, lengths,
                        active):
    """One layer's paged decode for all slots: the shared layer math
    (model_runner._decode_core) with a block-table cache adapter.

    x [S,1,D]; pk/pv [NB, bs, KV, HD] (this layer's pool); reads gather each
    slot's blocks into [S, max_len, KV, HD] (activation-only — the POOL is what
    lives in HBM persistently), writes scatter the new token through the table.
    """
    from .model_runner import _decode_core

    s = x.shape[0]
    nb_slot = block_tables.shape[1]
    bs = pk.shape[1]
    max_len = nb_slot * bs
    kvh, hd = cfg.n_kv_heads, cfg.head_dim

    def cache_rw(k_new, v_new):
        # scatter through the block table (distinct active slots own distinct
        # blocks, so writes never collide); INACTIVE slots' tables may point at
        # freed/re-owned blocks, so their writes land in the scratch block (the
        # pool's last physical block, never allocated)
        scratch = pk.shape[0] - 1
        safe_idx = jnp.minimum(lengths // bs, nb_slot - 1)
        write_block = jnp.where(active, block_tables[jnp.arange(s), safe_idx], scratch)
        write_off = lengths % bs
        nk = pk.at[write_block, write_off].set(k_new.astype(pk.dtype))
        nv = pv.at[write_block, write_off].set(v_new.astype(pv.dtype))
        ck = nk[block_tables].reshape(s, max_len, kvh, hd)
        cv = nv[block_tables].reshape(s, max_len, kvh, hd)
        return ck, cv, (nk, nv)

    x, (nk, nv) = _decode_core(x, lp, cfg, lengths, active, cache_rw)
    return x, nk, nv


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_step_paged(
    params,
    state: PagedState,
    tokens: jax.Array,  # [slots] int32
    active: jax.Array,  # [slots] bool
    cfg: ModelConfig,
) -> Tuple[PagedState, jax.Array]:
    """One decode step for every slot against the paged pool."""
    x = params["embed"].astype(cfg.activation_dtype)[tokens[:, None]]

    if cfg.scan_layers:
        def body(carry, xs):
            h = carry
            lp, pk, pv = xs
            h, pk, pv = _decode_block_paged(h, lp, cfg, pk, pv,
                                            state.block_tables, state.lengths, active)
            return h, (pk, pv)

        x, (nk, nv) = jax.lax.scan(body, x, (params["layers"], state.k, state.v))
    else:
        nk, nv = [], []
        for i, lp in enumerate(params["layers"]):
            x, pk, pv = _decode_block_paged(x, lp, cfg, state.k[i], state.v[i],
                                            state.block_tables, state.lengths, active)
            nk.append(pk)
            nv.append(pv)
        nk, nv = jnp.stack(nk), jnp.stack(nv)

    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", x, _qw(head, cfg.activation_dtype))[:, 0]
    lengths = jnp.where(active, state.lengths + 1, state.lengths)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), logits.astype(jnp.float32)


def _verify_block_paged(x, lp, cfg: ModelConfig, pk, pv, block_tables, lengths,
                        active):
    """Paged verify: the shared W-token window math with block-table writes.
    The engine pre-grows every active slot's table by the window width, so all
    window positions map to owned blocks; inactive slots (and any position
    past the table) write to the scratch block."""
    from .model_runner import _verify_core

    s, wlen, _ = x.shape
    nb_slot = block_tables.shape[1]
    bs = pk.shape[1]
    max_len = nb_slot * bs
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    pos = lengths[:, None] + jnp.arange(wlen)[None, :]  # [S,W]

    def cache_rw(k_new, v_new):
        scratch = pk.shape[0] - 1
        blk_idx = pos // bs  # [S,W]
        in_table = blk_idx < nb_slot
        safe_idx = jnp.minimum(blk_idx, nb_slot - 1)
        rows = jnp.arange(s)[:, None]
        write_block = jnp.where(active[:, None] & in_table,
                                block_tables[rows, safe_idx], scratch)
        write_off = pos % bs
        nk = pk.at[write_block, write_off].set(k_new.astype(pk.dtype))
        nv = pv.at[write_block, write_off].set(v_new.astype(pv.dtype))
        ck = nk[block_tables].reshape(s, max_len, kvh, hd)
        cv = nv[block_tables].reshape(s, max_len, kvh, hd)
        return ck, cv, (nk, nv)

    x, (nk, nv) = _verify_core(x, lp, cfg, lengths, cache_rw)
    return x, nk, nv


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def spec_verify_step_paged(
    params,
    state: PagedState,
    window: jax.Array,  # [S,W] int32 — [last_token, draft_1..draft_k]
    draft_len: jax.Array,  # [S] int32
    active: jax.Array,  # [S] bool
    cfg: ModelConfig,
    rng: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    top_k: jax.Array,
):
    """Speculative verify against the paged pool (see
    model_runner.spec_verify_step for the contract)."""
    from .model_runner import spec_driver

    nk, nv, lengths, greedy, n_acc = spec_driver(
        params, state.k, state.v, state.lengths, window, draft_len, active,
        cfg, rng, temperature, top_p, top_k,
        lambda h, lp, pk, pv: _verify_block_paged(
            h, lp, cfg, pk, pv, state.block_tables, state.lengths, active))
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), greedy, n_acc


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_multi_paged(
    params,
    state: PagedState,
    tokens: jax.Array,  # [slots] int32
    active: jax.Array,  # [slots] bool — FIXED for the whole burst
    cfg: ModelConfig,
    rngs: jax.Array,  # [K] stacked PRNG keys
    temperature: jax.Array,  # [slots] f32
    top_p: jax.Array,  # [slots] f32
    top_k: jax.Array,  # [slots] i32
):
    """K fused decode+sample steps against the paged pool (one host sync per
    burst; vLLM multi-step scheduling). Callers pre-grow every active slot's
    block table by K tokens — block_tables are frozen across the burst."""
    def body(carry, rng):
        st, toks = carry
        st, logits = decode_step_paged(params, st, toks, active, cfg)
        nxt = sampling.sample(rng, logits, temperature, top_p, top_k)
        nxt = jnp.where(active, nxt, toks).astype(jnp.int32)
        return (st, nxt), nxt

    (state, _), toks_k = jax.lax.scan(body, (state, tokens.astype(jnp.int32)), rngs)
    return state, toks_k


# ------------------------------------------------------------------ chunked prefill

def chunked_prefill(params, prompt_ids: List[int], cfg: ModelConfig,
                    chunk: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill a long prompt chunk-at-a-time (reference: vLLM chunked prefill).

    Peak activation memory is one chunk's, not the whole prompt's; the temp KV
    grows to the padded prompt length and is installed into blocks afterwards.
    Returns (k [L,1,S_pad,KV,HD], v, last_logits [vocab] f32)."""
    n = len(prompt_ids)
    s_pad = -(-n // chunk) * chunk
    cache = llama.init_kv_cache(cfg, batch=1, max_len=s_pad,
                                dtype=cfg.activation_dtype)
    last = None
    for start in range(0, s_pad, chunk):
        piece = prompt_ids[start:start + chunk]
        tokens = np.zeros((1, chunk), np.int32)
        tokens[0, : len(piece)] = piece
        logits, cache = _prefill_chunk(params, cache, jnp.asarray(tokens),
                                       jnp.int32(len(piece)), cfg)
        if start < n <= start + chunk:
            last = logits[0, (n - 1) - start].astype(jnp.float32)
    return cache.k, cache.v, last


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _prefill_chunk(params, cache, tokens, true_len, cfg: ModelConfig):
    # pad positions in the final chunk must not claim MoE expert capacity
    # (model_runner.prefill passes the same mask for the same reason)
    mask = (jnp.arange(tokens.shape[1])[None, :] < true_len).astype(jnp.float32)
    logits, cache = llama.forward(params, tokens, cfg, cache=cache, token_mask=mask)
    return logits, cache
