"""Paged KV cache: block-granular memory for continuous batching.

Reference capability: vLLM's PagedAttention block tables (the engine the
reference wraps, vllm_models.py:125-139) — the slot cache reserves
max_model_len tokens per slot up front, so HBM caps max_num_seqs at
slots x max_model_len x layers; paging shares one block pool across slots and
allocates per BLOCK_SIZE tokens, so many short sequences (or few long ones) fit
the same memory. All shapes stay static for XLA: the pool is
[L, num_blocks, block, kv_heads, head_dim], each slot owns a fixed-width block
table [max_blocks] of pool indices, and reads gather / writes scatter through
the table.

Host-side: _BlockManager hands out pool indices; when the pool is exhausted the
engine preempts the youngest request and re-prefills it later (vLLM's
recompute preemption).
"""
from __future__ import annotations

import functools
import hashlib
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.ops.quant import as_weight as _qw
from ray_tpu.models.config import ModelConfig

from . import sampling


class PagedState(NamedTuple):
    """Device-resident paged serving state.

    k/v: [L, num_blocks, block_size, kv_heads, head_dim] — the shared pool.
    block_tables: [slots, max_blocks] int32 pool indices (junk entries are
        masked by lengths at read time).
    lengths: [slots] int32 tokens cached per slot.
    """

    k: jax.Array
    v: jax.Array
    block_tables: jax.Array
    lengths: jax.Array


POOL_SPEC = P(None, None, None, "tp", None)
# dp>1: the BLOCK axis shards over dp — each replica owns an independent pool
# partition (plus its own scratch block) and its slots' tables hold replica-
# LOCAL block ids; tables/lengths shard over dp on the slot axis.
POOL_SPEC_DP = P(None, "dp", None, "tp", None)
TABLE_SPEC_DP = P("dp", None)
LENGTHS_SPEC_DP = P("dp")
# pp>1: the LAYER axis shards over pp — each stage holds its layers' slice of
# the block pool (the fitting-a-bigger-model point of inference pp); tables/
# lengths are shared (block ids are layer-independent). With dp too, the block
# axis additionally shards over dp (independent per-replica partitions, as in
# POOL_SPEC_DP) and tables/lengths shard over dp on the slot axis.
POOL_SPEC_PP = P("pp", None, None, "tp", None)
POOL_SPEC_PP_DP = P("pp", "dp", None, "tp", None)


def _dp_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get("dp", 1))


def _pp_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get("pp", 1))


def init_paged_state(cfg: ModelConfig, slots: int, max_len: int, num_blocks: int,
                     block_size: int, mesh: Optional[Mesh] = None) -> PagedState:
    """Each pool (partition) gets ONE extra physical block (its last index):
    inactive slots' decode writes are redirected there — their block-table
    entries may reference blocks already released and re-owned by other
    requests. With dp>1 `num_blocks` is the TOTAL across replicas; each replica
    owns num_blocks/dp blocks + a scratch, and the block axis shards over dp
    (vLLM analogue: one independent KV pool per dp engine replica)."""
    dp = _dp_size(mesh)
    max_blocks = max_len // block_size
    if dp > 1:
        if num_blocks % dp or slots % dp:
            raise ValueError(
                f"num_blocks ({num_blocks}) and slots ({slots}) must divide by "
                f"data_parallel_size ({dp})")
        n_block_axis = num_blocks + dp  # one scratch per replica partition
    else:
        n_block_axis = num_blocks + 1
    shape = (cfg.n_layers, n_block_axis, block_size, cfg.n_kv_heads, cfg.head_dim)
    dtype = cfg.activation_dtype
    k = jnp.zeros(shape, dtype)
    v = jnp.zeros(shape, dtype)
    bt = jnp.zeros((slots, max_blocks), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    if mesh is not None:
        pp = _pp_size(mesh)
        if dp > 1 and pp > 1:
            pool_spec = POOL_SPEC_PP_DP
        elif dp > 1:
            pool_spec = POOL_SPEC_DP
        elif pp > 1:
            pool_spec = POOL_SPEC_PP
        else:
            pool_spec = POOL_SPEC
        k = jax.device_put(k, NamedSharding(mesh, pool_spec))
        v = jax.device_put(v, NamedSharding(mesh, pool_spec))
        bt = jax.device_put(bt, NamedSharding(
            mesh, TABLE_SPEC_DP if dp > 1 else P()))
        lengths = jax.device_put(lengths, NamedSharding(
            mesh, LENGTHS_SPEC_DP if dp > 1 else P()))
    return PagedState(k=k, v=v, block_tables=bt, lengths=lengths)


class _BlockManager:
    """Host-side free list + per-slot allocation bookkeeping, with a
    prefix cache (reference: vLLM automatic prefix caching): full prompt
    blocks are content-addressed by a hash CHAIN (block key = H(parent key,
    block tokens)), shared across slots via refcounts, and kept around at
    refcount 0 until the pool needs the space (LRU eviction)."""

    def __init__(self, num_blocks: int, block_size: int, max_blocks_per_slot: int,
                 slots: int, enable_prefix_caching: bool = True):
        self.block_size = block_size
        self.max_blocks = max_blocks_per_slot
        self.total_blocks = num_blocks
        self.free: List[int] = list(range(num_blocks))
        self.owned: List[List[int]] = [[] for _ in range(slots)]  # includes shared
        self.shared: List[List[int]] = [[] for _ in range(slots)]  # shared subset
        self.enable_prefix_caching = enable_prefix_caching
        self.cached: Dict[bytes, int] = {}  # chain key -> block id
        self.block_key: Dict[int, bytes] = {}
        self.refs: Dict[int, int] = {}  # cached block id -> live references
        self._lru: Dict[int, int] = {}  # ref-0 cached block -> last-use tick
        self._tick = 0
        self.hit_tokens = 0  # metrics: prompt tokens served from the cache

    @staticmethod
    def chain_keys(prompt: List[int], block_size: int) -> List[bytes]:
        """Hash-chain keys for each FULL block of the prompt."""
        keys = []
        parent = b""
        for start in range(0, (len(prompt) // block_size) * block_size, block_size):
            h = hashlib.sha256(parent)
            h.update(np.asarray(prompt[start:start + block_size], np.int64).tobytes())
            parent = h.digest()
            keys.append(parent)
        return keys

    @property
    def num_free(self) -> int:
        # ref-0 cached blocks are reclaimable on demand
        return len(self.free) + len(self._lru)

    def blocks_needed(self, tokens: int) -> int:
        return -(-tokens // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return self.num_free >= n

    def _take_free(self) -> int:
        if self.free:
            return self.free.pop()
        # evict the least-recently-used unreferenced cached block
        victim = min(self._lru, key=self._lru.get)
        self._lru.pop(victim)
        key = self.block_key.pop(victim)
        self.cached.pop(key, None)
        self.refs.pop(victim, None)
        return victim

    def allocate(self, slot: int, n: int) -> List[int]:
        assert self.num_free >= n, "pool exhausted (caller must check/preempt)"
        got = [self._take_free() for _ in range(n)]
        self.owned[slot].extend(got)
        return got

    def match_prefix(self, slot: int, prompt: List[int]) -> List[int]:
        """Attach the longest cached block chain for this prompt to the slot
        (bumping refcounts); returns the matched block ids in order. Always
        leaves >= 1 prompt token uncached so prefill still produces the
        last-token logits."""
        if not self.enable_prefix_caching:
            return []
        usable = len(prompt) - 1  # the final token must be computed
        matched: List[int] = []
        for key in self.chain_keys(prompt[:usable] if usable > 0 else [],
                                   self.block_size):
            bid = self.cached.get(key)
            if bid is None:
                break
            matched.append(bid)
        if matched:
            # round DOWN to a power of two of blocks: every distinct attached
            # count is a fresh XLA specialization of the gather/suffix-prefill
            # programs, so bound them like the prefill buckets do
            matched = matched[: 1 << (len(matched).bit_length() - 1)]
        for bid in matched:
            if self.refs.get(bid, 0) == 0:
                self._lru.pop(bid, None)
            self.refs[bid] = self.refs.get(bid, 0) + 1
        self.owned[slot].extend(matched)
        self.shared[slot].extend(matched)
        return matched

    def register_blocks(self, slot: int, prompt: List[int],
                        block_ids: List[int], skip_blocks: int) -> None:
        """Publish a slot's freshly filled FULL prompt blocks into the cache
        (the slot keeps them as shared from now on)."""
        if not self.enable_prefix_caching:
            return
        keys = self.chain_keys(prompt, self.block_size)
        for i, key in enumerate(keys):
            if i < skip_blocks:
                continue  # already cached (matched prefix)
            if i >= len(block_ids):
                break
            bid = block_ids[i]
            if key in self.cached:
                continue  # raced by an identical prompt; keep ours private
            self.cached[key] = bid
            self.block_key[bid] = key
            self.refs[bid] = self.refs.get(bid, 0) + 1
            if bid in self.owned[slot] and bid not in self.shared[slot]:
                self.shared[slot].append(bid)

    def release(self, slot: int) -> None:
        shared = set(self.shared[slot])
        self._tick += 1
        for bid in self.owned[slot]:
            if bid in shared:
                self.refs[bid] = self.refs.get(bid, 1) - 1
                if self.refs[bid] <= 0:
                    self.refs[bid] = 0
                    self._lru[bid] = self._tick  # reclaimable, still cached
            else:
                self.free.append(bid)
        self.owned[slot] = []
        self.shared[slot] = []

    def slot_capacity(self, slot: int) -> int:
        return len(self.owned[slot]) * self.block_size

    # slot-aware forms (trivial here; _ShardedBlockManager scopes them to the
    # slot's replica pool) — engine call sites use ONLY these where pool
    # locality matters, so dp>1 composes without engine-side branching
    def can_allocate_for(self, slot: int, n: int) -> bool:
        return self.can_allocate(n)

    def num_free_for(self, slot: int) -> int:
        return self.num_free

    def max_fit(self, slot: int) -> int:
        """Largest block count a request in this slot could ever hold."""
        return min(self.total_blocks, self.max_blocks)

    def same_pool(self, slot_a: int, slot_b: int) -> bool:
        return True

    def owned_for(self, slot: int):
        return self.owned[slot]

    def add_hit_tokens(self, slot: int, n: int) -> None:
        self.hit_tokens += n


class _ShardedBlockManager:
    """dp independent per-replica block pools (reference capability: one vLLM
    engine replica per dp rank, each with its own KV pool — here one host-side
    manager per replica partition inside the single engine). Slot s maps to
    replica s // slots_per; handed-out block ids are replica-LOCAL (the device
    tables are read inside the per-replica shard_map body). The prefix cache is
    per-replica too: a cached block can only serve slots whose tables can
    reference its pool partition."""

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_slot: int, slots: int, dp: int,
                 enable_prefix_caching: bool = True):
        assert num_blocks % dp == 0 and slots % dp == 0
        self.dp = dp
        self.block_size = block_size
        self.max_blocks = max_blocks_per_slot
        self.slots_per = slots // dp
        self.per_replica_blocks = num_blocks // dp
        self.subs = [
            _BlockManager(num_blocks // dp, block_size, max_blocks_per_slot,
                          self.slots_per, enable_prefix_caching)
            for _ in range(dp)
        ]

    def _sub(self, slot: int):
        return self.subs[slot // self.slots_per], slot % self.slots_per

    # -- aggregates (metrics / config introspection) --
    @property
    def total_blocks(self) -> int:
        return sum(s.total_blocks for s in self.subs)

    @property
    def num_free(self) -> int:
        return sum(s.num_free for s in self.subs)

    @property
    def hit_tokens(self) -> int:
        return sum(s.hit_tokens for s in self.subs)

    @hit_tokens.setter
    def hit_tokens(self, value: int) -> None:
        # engine increments on prefix hits; attribute the delta to replica 0's
        # counter is wrong — engine uses add_hit_tokens instead. Setter kept
        # only for symmetry with reads; reject silent use.
        raise AttributeError("use add_hit_tokens(slot, n)")

    def add_hit_tokens(self, slot: int, n: int) -> None:
        sub, _ = self._sub(slot)
        sub.hit_tokens += n

    @property
    def cached(self):
        out = {}
        for r, s in enumerate(self.subs):
            for key, bid in s.cached.items():
                out[(r, key)] = bid
        return out

    # -- slot-scoped API --
    def blocks_needed(self, tokens: int) -> int:
        return -(-tokens // self.block_size)

    def can_allocate_for(self, slot: int, n: int) -> bool:
        sub, _ = self._sub(slot)
        return sub.can_allocate(n)

    def num_free_for(self, slot: int) -> int:
        sub, _ = self._sub(slot)
        return sub.num_free

    def max_fit(self, slot: int) -> int:
        return min(self.per_replica_blocks, self.max_blocks)

    def same_pool(self, slot_a: int, slot_b: int) -> bool:
        return slot_a // self.slots_per == slot_b // self.slots_per

    def allocate(self, slot: int, n: int):
        sub, local = self._sub(slot)
        return sub.allocate(local, n)

    def release(self, slot: int) -> None:
        sub, local = self._sub(slot)
        sub.release(local)

    def match_prefix(self, slot: int, prompt):
        sub, local = self._sub(slot)
        return sub.match_prefix(local, prompt)

    def register_blocks(self, slot: int, prompt, block_ids, skip_blocks) -> None:
        sub, local = self._sub(slot)
        sub.register_blocks(local, prompt, block_ids, skip_blocks)

    def slot_capacity(self, slot: int) -> int:
        sub, local = self._sub(slot)
        return sub.slot_capacity(local)

    def owned_for(self, slot: int):
        sub, local = self._sub(slot)
        return sub.owned[local]


def make_block_manager(num_blocks: int, block_size: int,
                       max_blocks_per_slot: int, slots: int, dp: int = 1,
                       enable_prefix_caching: bool = True):
    if dp > 1:
        return _ShardedBlockManager(num_blocks, block_size, max_blocks_per_slot,
                                    slots, dp, enable_prefix_caching)
    return _BlockManager(num_blocks, block_size, max_blocks_per_slot, slots,
                         enable_prefix_caching)


# ----------------------------------------------------------------- prefill install

@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("n_blocks",))
def install_prefill(
    state: PagedState,
    k: jax.Array,  # [L, 1, S_pad, KV, HD] from prefill_detached
    v: jax.Array,
    block_ids: jax.Array,  # [n_blocks] int32 pool indices (S_pad = n_blocks*bs)
    true_len: jax.Array,  # scalar int32
    slot: jax.Array,  # scalar int32
    n_blocks: int,
) -> PagedState:
    """Scatter a prompt's KV into its allocated blocks and fill the block table."""
    L = state.k.shape[0]
    bs = state.k.shape[2]
    kb = k[:, 0].reshape(L, n_blocks, bs, *k.shape[3:]).astype(state.k.dtype)
    vb = v[:, 0].reshape(L, n_blocks, bs, *v.shape[3:]).astype(state.v.dtype)
    nk = state.k.at[:, block_ids].set(kb)
    nv = state.v.at[:, block_ids].set(vb)
    table_row = jnp.zeros((state.block_tables.shape[1],), jnp.int32)
    table_row = jax.lax.dynamic_update_slice(table_row, block_ids, (0,))
    bt = state.block_tables.at[slot].set(table_row)
    lengths = state.lengths.at[slot].set(true_len)
    return PagedState(k=nk, v=nv, block_tables=bt, lengths=lengths)


@functools.partial(jax.jit, donate_argnames=("state",))
def append_block(state: PagedState, slot: jax.Array, index: jax.Array,
                 block_id: jax.Array) -> PagedState:
    """Record a newly allocated decode block in a slot's table."""
    bt = state.block_tables.at[slot, index].set(block_id)
    return state._replace(block_tables=bt)


def trim_kv_for_transfer(k, v, n_tokens: int, block_size: int):
    """Trim bucket-padded prefill KV [L, 1, S_pad, ...] before a P/D handoff
    to the smallest power-of-two block count covering n_tokens + 1.

    The bucket-pad tail is attention-masked garbage the decode side re-pads
    on install anyway, so shipping it only burns handoff bandwidth (a short
    prompt in a coarse bucket can transfer several times its real KV).
    Power-of-two block counts keep the decode side's install_prefill compile
    variants log-bounded, exactly as bucketed prefill shapes do."""
    s_pad = k.shape[2]
    blocks = max(1, -(-(n_tokens + 1) // block_size))
    p2 = 1
    while p2 < blocks:
        p2 <<= 1
    s = p2 * block_size
    if s >= s_pad:
        return k, v
    return k[:, :, :s], v[:, :, :s]


# ----------------------------------------------------------------- prefix cache

@functools.partial(jax.jit, static_argnames=("n_blocks",))
def gather_blocks(state: PagedState, block_ids: jax.Array, n_blocks: int):
    """Cached prefix blocks -> contiguous KV context [L, 1, n*bs, KV, HD]."""
    kb = state.k[:, block_ids]  # [L, n, bs, KV, HD]
    vb = state.v[:, block_ids]
    L, _, bs = kb.shape[0], kb.shape[1], kb.shape[2]
    shape = (L, 1, n_blocks * bs) + kb.shape[3:]
    return kb.reshape(shape), vb.reshape(shape)


@functools.partial(jax.jit, static_argnames=("cfg", "n_blocks"))
def prefill_suffix_from_state(params, state: PagedState, block_ids: jax.Array,
                              tokens, true_suffix_len, cfg: ModelConfig,
                              n_blocks: int):
    """gather_blocks + prefill_suffix fused into ONE program: the warm
    (prefix-hit) path previously dispatched gather and suffix separately —
    an extra host->device round trip per request, which through a network
    tunnel costs more than the prefill compute it saves."""
    ctx_k, ctx_v = gather_blocks(state, block_ids, n_blocks)
    return _prefill_suffix_impl(params, ctx_k, ctx_v, tokens,
                                true_suffix_len, cfg)


def _prefill_suffix_impl(params, ctx_k, ctx_v, tokens, true_suffix_len,
                         cfg: ModelConfig):
    """Prefill ONLY the uncached suffix, attending over the cached-prefix KV
    context (reference: vLLM prefix caching skips recomputation of shared
    prompt prefixes). ctx_k/ctx_v: [L, 1, C, KV, HD]; tokens [1, S_pad].
    Returns (k_suffix [L, 1, S_pad, KV, HD], v_suffix, last_logits)."""
    cached_len = ctx_k.shape[2]
    s_pad = tokens.shape[1]
    dtype = cfg.activation_dtype
    pad = ((0, 0), (0, 0), (0, s_pad), (0, 0), (0, 0))
    cache = llama.KVCache(
        k=jnp.pad(ctx_k.astype(dtype), pad), v=jnp.pad(ctx_v.astype(dtype), pad),
        length=jnp.int32(cached_len))
    mask = (jnp.arange(s_pad)[None, :] < true_suffix_len).astype(jnp.float32)
    logits, cache = llama.forward(params, tokens, cfg, cache=cache, token_mask=mask)
    last = logits[0, true_suffix_len - 1].astype(jnp.float32)
    return (cache.k[:, :, cached_len:], cache.v[:, :, cached_len:], last)


prefill_suffix = functools.partial(jax.jit, static_argnames=("cfg",))(
    _prefill_suffix_impl)


@functools.partial(jax.jit, donate_argnames=("state",), static_argnames=("n_new",))
def install_with_prefix(
    state: PagedState,
    k_suf: jax.Array,  # [L, 1, S_pad, KV, HD] — suffix KV only
    v_suf: jax.Array,
    new_ids: jax.Array,  # [n_new] pool indices for the suffix
    table_row: jax.Array,  # [max_blocks] full table (cached + new ids, padded)
    true_len: jax.Array,
    slot: jax.Array,
    n_new: int,
) -> PagedState:
    """Install suffix KV into fresh blocks; cached-prefix blocks are already in
    the pool and only need table entries."""
    L = state.k.shape[0]
    bs = state.k.shape[2]
    kb = k_suf[:, 0].reshape(L, n_new, bs, *k_suf.shape[3:]).astype(state.k.dtype)
    vb = v_suf[:, 0].reshape(L, n_new, bs, *v_suf.shape[3:]).astype(state.v.dtype)
    nk = state.k.at[:, new_ids].set(kb)
    nv = state.v.at[:, new_ids].set(vb)
    bt = state.block_tables.at[slot].set(table_row)
    lengths = state.lengths.at[slot].set(true_len)
    return PagedState(k=nk, v=nv, block_tables=bt, lengths=lengths)


# ------------------------------------------------------------------------- decode

def _decode_block_paged(x, lp, cfg: ModelConfig, pk, pv, block_tables, lengths,
                        active):
    """One layer's paged decode for all slots: the shared layer math
    (model_runner._decode_core) with a block-table cache adapter.

    x [S,1,D]; pk/pv [NB, bs, KV, HD] (this layer's pool); reads gather each
    slot's blocks into [S, max_len, KV, HD] (activation-only — the POOL is what
    lives in HBM persistently), writes scatter the new token through the table.
    """
    from .model_runner import _decode_core

    s = x.shape[0]
    nb_slot = block_tables.shape[1]
    bs = pk.shape[1]
    max_len = nb_slot * bs
    kvh, hd = cfg.n_kv_heads, cfg.head_dim

    def cache_rw(k_new, v_new):
        # scatter through the block table (distinct active slots own distinct
        # blocks, so writes never collide); INACTIVE slots' tables may point at
        # freed/re-owned blocks, so their writes land in the scratch block (the
        # pool's last physical block, never allocated)
        scratch = pk.shape[0] - 1
        safe_idx = jnp.minimum(lengths // bs, nb_slot - 1)
        write_block = jnp.where(active, block_tables[jnp.arange(s), safe_idx], scratch)
        write_off = lengths % bs
        nk = pk.at[write_block, write_off].set(k_new.astype(pk.dtype))
        nv = pv.at[write_block, write_off].set(v_new.astype(pv.dtype))
        ck = nk[block_tables].reshape(s, max_len, kvh, hd)
        cv = nv[block_tables].reshape(s, max_len, kvh, hd)
        return ck, cv, (nk, nv)

    x, (nk, nv) = _decode_core(x, lp, cfg, lengths, active, cache_rw)
    return x, nk, nv


def _decode_step_impl(params, k, v, block_tables, lengths, tokens, active,
                      cfg: ModelConfig):
    """One decode step against ONE pool (the whole pool, or — inside the dp
    shard_map — one replica's local shard). Raw arrays in/out so the same math
    serves the single-pool jit and the per-replica body."""
    x = params["embed"].astype(cfg.activation_dtype)[tokens[:, None]]

    if cfg.scan_layers:
        def body(carry, xs):
            h = carry
            lp, pk, pv = xs
            h, pk, pv = _decode_block_paged(h, lp, cfg, pk, pv,
                                            block_tables, lengths, active)
            return h, (pk, pv)

        x, (nk, nv) = jax.lax.scan(body, x, (params["layers"], k, v))
    else:
        nk, nv = [], []
        for i, lp in enumerate(params["layers"]):
            x, pk, pv = _decode_block_paged(x, lp, cfg, k[i], v[i],
                                            block_tables, lengths, active)
            nk.append(pk)
            nv.append(pv)
        nk, nv = jnp.stack(nk), jnp.stack(nv)

    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", x, _qw(head, cfg.activation_dtype))[:, 0]
    new_lengths = jnp.where(active, lengths + 1, lengths)
    return nk, nv, new_lengths, logits.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_step_paged(
    params,
    state: PagedState,
    tokens: jax.Array,  # [slots] int32
    active: jax.Array,  # [slots] bool
    cfg: ModelConfig,
) -> Tuple[PagedState, jax.Array]:
    """One decode step for every slot against the paged pool."""
    nk, nv, lengths, logits = _decode_step_impl(
        params, state.k, state.v, state.block_tables, state.lengths,
        tokens, active, cfg)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), logits


def _pp_paged_layers(params, state: PagedState, x, active, mesh: Mesh, *,
                     width: int, block_fn):
    """Paged layer pass through the pp schedule, shared by decode (width=1)
    and spec verify (width=W). Unlike the slot variant the whole (stage-local)
    pool rides the scan carry; block_fn(h, lp, pk, pv, bt_mb, ln_mb, act_eff)
    -> (h, pk, pv), where act_eff is False on bubble ticks so those writes
    land in the scratch block."""
    from ray_tpu.llm.model_runner import _pp_schedule, _pp_shard_map

    m = mesh.shape["pp"]
    nb_slot = state.block_tables.shape[1]

    def inner(layers_local, k_local, v_local, x_local, bt, lengths, active_i):
        s_l = x_local.shape[0]  # this dp replica's slot count
        smb = s_l // m
        x_mb = x_local.reshape(m, smb, width, x_local.shape[-1])

        def step_mb(x_in, kv, jc, valid):
            k, v = kv
            bt_mb = jax.lax.dynamic_slice(bt, (jc * smb, 0), (smb, nb_slot))
            ln_mb = jax.lax.dynamic_slice(lengths, (jc * smb,), (smb,))
            act_mb = (jax.lax.dynamic_slice(active_i, (jc * smb,), (smb,)) > 0)
            act_eff = act_mb & valid  # bubble ticks write only the scratch block

            def lbody(c, xs):
                lp, pk, pv = xs
                h, pk, pv = block_fn(c, lp, pk, pv, bt_mb, ln_mb, act_eff)
                return h, (pk, pv)

            h, (nk, nv) = jax.lax.scan(lbody, x_in, (layers_local, k, v))
            return h, (nk, nv)

        outs, (k, v) = _pp_schedule(x_mb, (k_local, v_local), step_mb)
        return outs.reshape(s_l, width, outs.shape[-1]), k, v

    return _pp_shard_map(inner, params["layers"], mesh,
                         (state.k, state.v, x, state.block_tables,
                          state.lengths, active.astype(jnp.int32)))


def decode_step_paged_pp(params, state: PagedState, tokens, active,
                         cfg: ModelConfig, mesh: Mesh):
    """Paged decode with the layer stack + pool split across "pp" stages.

    Mirror of model_runner.decode_step_pp on the paged layout: each stage holds
    its L/pp layers and THEIR slice of the block pool (POOL_SPEC_PP); slots
    split into pp microbatches and activations hop stage->stage via ppermute.
    Block tables/lengths are layer-independent, so every stage reads the same
    tables. Bubble ticks run a clipped microbatch with active=False, so their
    scatter lands in the scratch block — no whole-pool select per tick is
    needed to discard them. tp/ep stay GSPMD auto axes inside the stage. With
    dp>1, slots and the block axis additionally shard over dp replicas
    (POOL_SPEC_PP_DP): each replica owns an independent pool partition with
    replica-local block ids and its own scratch (the partition's last block),
    so the manual-region body is unchanged — it just sees local arrays.
    """
    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    s = tokens.shape[0]
    if s % (pp * dp):
        raise ValueError(f"max_num_seqs {s} must be divisible by pp*dp {pp * dp}")

    x = params["embed"].astype(cfg.activation_dtype)[tokens[:, None]]  # [S,1,D]
    h, nk, nv = _pp_paged_layers(
        params, state, x, active, mesh, width=1,
        block_fn=lambda c, lp, pk, pv, bt, ln, ac:
            _decode_block_paged(c, lp, cfg, pk, pv, bt, ln, ac))

    h = llama.rms_norm(h, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", h, _qw(head, cfg.activation_dtype))[:, 0]
    lengths = jnp.where(active, state.lengths + 1, state.lengths)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), logits.astype(jnp.float32)


def _verify_block_paged(x, lp, cfg: ModelConfig, pk, pv, block_tables, lengths,
                        active):
    """Paged verify: the shared W-token window math with block-table writes.
    The engine pre-grows every active slot's table by the window width, so all
    window positions map to owned blocks; inactive slots (and any position
    past the table) write to the scratch block."""
    from .model_runner import _verify_core

    s, wlen, _ = x.shape
    nb_slot = block_tables.shape[1]
    bs = pk.shape[1]
    max_len = nb_slot * bs
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    pos = lengths[:, None] + jnp.arange(wlen)[None, :]  # [S,W]

    def cache_rw(k_new, v_new):
        scratch = pk.shape[0] - 1
        blk_idx = pos // bs  # [S,W]
        in_table = blk_idx < nb_slot
        safe_idx = jnp.minimum(blk_idx, nb_slot - 1)
        rows = jnp.arange(s)[:, None]
        write_block = jnp.where(active[:, None] & in_table,
                                block_tables[rows, safe_idx], scratch)
        write_off = pos % bs
        nk = pk.at[write_block, write_off].set(k_new.astype(pk.dtype))
        nv = pv.at[write_block, write_off].set(v_new.astype(pv.dtype))
        ck = nk[block_tables].reshape(s, max_len, kvh, hd)
        cv = nv[block_tables].reshape(s, max_len, kvh, hd)
        return ck, cv, (nk, nv)

    x, (nk, nv) = _verify_core(x, lp, cfg, lengths, cache_rw, active=active)
    return x, nk, nv


def spec_verify_step_paged_pp(params, state: PagedState, window, draft_len,
                              active, rng, temperature, top_p, top_k, *,
                              cfg: ModelConfig, mesh: Mesh):
    """Paged speculative verify through the pipeline schedule: the verify
    window is the microbatch payload, each stage holds its layers' pool slice,
    and bubble-tick writes redirect to the scratch block via the same
    active-mask plumbing _verify_block_paged already has. Composes with dp
    (replica pool partitions) exactly like decode_step_paged_pp."""
    from .model_runner import spec_driver

    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    s, w = window.shape
    if s % (pp * dp):
        raise ValueError(f"max_num_seqs {s} must be divisible by pp*dp {pp * dp}")

    def layers_pass(x):  # [S, W, D]
        return _pp_paged_layers(
            params, state, x, active, mesh, width=w,
            block_fn=lambda c, lp, pk, pv, bt, ln, ac:
                _verify_block_paged(c, lp, cfg, pk, pv, bt, ln, ac))

    nk, nv, lengths, greedy, n_acc = spec_driver(
        params, state.k, state.v, state.lengths, window, draft_len, active,
        cfg, rng, temperature, top_p, top_k, layers_pass=layers_pass)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), greedy, n_acc


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def spec_verify_step_paged(
    params,
    state: PagedState,
    window: jax.Array,  # [S,W] int32 — [last_token, draft_1..draft_k]
    draft_len: jax.Array,  # [S] int32
    active: jax.Array,  # [S] bool
    cfg: ModelConfig,
    rng: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    top_k: jax.Array,
):
    """Speculative verify against the paged pool (see
    model_runner.spec_verify_step for the contract)."""
    from .model_runner import spec_driver

    nk, nv, lengths, greedy, n_acc = spec_driver(
        params, state.k, state.v, state.lengths, window, draft_len, active,
        cfg, rng, temperature, top_p, top_k,
        lambda h, lp, pk, pv: _verify_block_paged(
            h, lp, cfg, pk, pv, state.block_tables, state.lengths, active))
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), greedy, n_acc


@functools.partial(
    jax.jit, static_argnames=("cfg", "m", "k", "nmax", "propose_fn"),
    donate_argnames=("state",))
def spec_multi_paged(
    params,
    state: PagedState,
    hist: jax.Array,  # [S, width] int32 — prompt + emitted tokens per slot
    hlen: jax.Array,  # [S] int32
    active: jax.Array,  # [S] bool — FIXED for the whole burst
    cfg: ModelConfig,
    rngs: jax.Array,  # [m] stacked PRNG keys
    temperature: jax.Array,
    top_p: jax.Array,
    top_k: jax.Array,
    m: int,
    k: int,
    nmax: int,
    propose_fn=None,
):
    """m fused speculative windows against the PAGED pool (spec x multi-step x
    paged composed): same propose->verify->accept scan as model_runner.spec_multi
    with block-table writes. Callers pre-grow every active slot's table by
    m*(k+1) tokens — block_tables are frozen across the burst; window positions
    past a slot's table land in the scratch block (never read back, because
    lengths only advance over accepted tokens that DO have table entries)."""
    from .model_runner import propose_ngram_device, spec_multi_impl

    return spec_multi_impl(
        params, state, hist, hlen, active, cfg, rngs, temperature, top_p,
        top_k, m, k, nmax, propose_fn or propose_ngram_device,
        lambda st: lambda x, lp, pk, pv: _verify_block_paged(
            x, lp, cfg, pk, pv, st.block_tables, st.lengths, active),
        lambda st, nk, nv, lengths: PagedState(
            k=nk, v=nv, block_tables=st.block_tables, lengths=lengths))


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_multi_paged(
    params,
    state: PagedState,
    tokens: jax.Array,  # [slots] int32
    active: jax.Array,  # [slots] bool — FIXED for the whole burst
    cfg: ModelConfig,
    rngs: jax.Array,  # [K] stacked PRNG keys
    temperature: jax.Array,  # [slots] f32
    top_p: jax.Array,  # [slots] f32
    top_k: jax.Array,  # [slots] i32
    steps_left: jax.Array,  # [slots] int32 — per-slot step budget within K
):
    """K fused decode+sample steps against the paged pool (one host sync per
    burst; vLLM multi-step scheduling). Callers pre-grow every active slot's
    block table by min(K, steps_left[s]) tokens — block_tables are frozen
    across the burst. steps_left makes the burst barrier-free: a slot past its
    own budget goes inactive for the remaining steps (its writes land in the
    scratch block) instead of capping K for the whole batch."""
    def body(carry, xs):
        rng, t = xs
        st, toks = carry
        act_t = active & (t < steps_left)
        st, logits = decode_step_paged(params, st, toks, act_t, cfg)
        nxt = sampling.sample(rng, logits, temperature, top_p, top_k)
        nxt = jnp.where(act_t, nxt, toks).astype(jnp.int32)
        return (st, nxt), nxt

    (state, _), toks_k = jax.lax.scan(
        body, (state, tokens.astype(jnp.int32)),
        (rngs, jnp.arange(rngs.shape[0], dtype=jnp.int32)))
    return state, toks_k


# ------------------------------------------------- data-parallel (dp) composition
#
# kv_layout="paged" with data_parallel_size > 1 (the vLLM capability of one KV
# pool per dp engine replica, here inside ONE SPMD program): every paged device
# op runs under a shard_map whose manual axis is "dp" — each replica owns an
# independent pool partition + scratch block, its slots' tables hold replica-
# LOCAL block ids, and decode/verify touch no cross-replica data at all (tp
# stays a GSPMD auto axis inside the body). Slot-targeted ops (installs, table
# appends) are replica-masked: non-owners redirect their writes to their own
# scratch block, so nothing is ever selected over the full pool.

POOL_DP = P(None, "dp", None, None, None)  # manual-axis view of POOL_SPEC_DP
TABLE_DP = P("dp", None)
VEC_DP = P("dp")


def _rep_specs(tree):
    """Replicated-in-dp specs for a params pytree (tp shardings stay auto)."""
    return jax.tree.map(lambda _: P(), tree)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"),
                   donate_argnames=("state",))
def decode_step_paged_dp(params, state: PagedState, tokens, active,
                         cfg: ModelConfig, mesh: Mesh):
    from ray_tpu.parallel.sharding import manual_axes

    def body(p, k, v, bt, ln, toks, act):
        return _decode_step_impl(p, k, v, bt, ln, toks, act, cfg)

    with manual_axes("dp"):
        nk, nv, lengths, logits = jax.shard_map(
            body, mesh=mesh,
            in_specs=(_rep_specs(params), POOL_DP, POOL_DP, TABLE_DP, VEC_DP,
                      VEC_DP, VEC_DP),
            out_specs=(POOL_DP, POOL_DP, VEC_DP, P("dp", None)),
            axis_names={"dp"},
        )(params, state.k, state.v, state.block_tables, state.lengths,
          tokens, active)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), logits


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"),
                   donate_argnames=("state",))
def decode_multi_paged_dp(params, state: PagedState, tokens, active,
                          cfg: ModelConfig, rngs, temperature, top_p, top_k,
                          steps_left, mesh: Mesh):
    from ray_tpu.parallel.sharding import manual_axes

    def body(p, k, v, bt, ln, toks, act, rr, tt, tp_, tk, sl):
        # distinct sampling streams per replica
        rr = jax.vmap(lambda r: jax.random.fold_in(r, jax.lax.axis_index("dp")))(rr)

        def step(carry, xs):
            rng, t_i = xs
            kk, vv, lln, t = carry
            act_t = act & (t_i < sl)
            kk, vv, lln, logits = _decode_step_impl(p, kk, vv, bt, lln, t,
                                                    act_t, cfg)
            nxt = sampling.sample(rng, logits, tt, tp_, tk)
            nxt = jnp.where(act_t, nxt, t).astype(jnp.int32)
            return (kk, vv, lln, nxt), nxt

        (kk, vv, lln, _), toks_k = jax.lax.scan(
            step, (k, v, ln, toks.astype(jnp.int32)),
            (rr, jnp.arange(rr.shape[0], dtype=jnp.int32)))
        return kk, vv, lln, toks_k

    with manual_axes("dp"):
        nk, nv, lengths, toks_k = jax.shard_map(
            body, mesh=mesh,
            in_specs=(_rep_specs(params), POOL_DP, POOL_DP, TABLE_DP, VEC_DP,
                      VEC_DP, VEC_DP, P(), VEC_DP, VEC_DP, VEC_DP, VEC_DP),
            out_specs=(POOL_DP, POOL_DP, VEC_DP, P(None, "dp")),
            axis_names={"dp"},
        )(params, state.k, state.v, state.block_tables, state.lengths,
          tokens, active, rngs, temperature, top_p, top_k, steps_left)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), toks_k


def _install_dp(state: PagedState, k, v, new_ids, table_row, true_len, slot,
                n_new: int, mesh: Mesh, slots_per: int):
    """Shared dp-sharded install: scatter n_new fresh KV blocks + set the
    slot's table row and length — only on the OWNING replica's shard;
    non-owners redirect the scatter into their own scratch block (cheap, never
    read) so nothing is ever selected over the full pool."""
    from ray_tpu.parallel.sharding import manual_axes

    replica = slot // slots_per
    local_slot = slot % slots_per

    def body(pk, pv, bt, ln, kk, vv, ids, row):
        mine = jax.lax.axis_index("dp") == replica
        scratch = pk.shape[1] - 1
        ids_eff = jnp.where(mine, ids, scratch)
        L = pk.shape[0]
        bs = pk.shape[2]
        kb = kk[:, 0].reshape(L, n_new, bs, *kk.shape[3:]).astype(pk.dtype)
        vb = vv[:, 0].reshape(L, n_new, bs, *vv.shape[3:]).astype(pv.dtype)
        nk = pk.at[:, ids_eff].set(kb)
        nv = pv.at[:, ids_eff].set(vb)
        nbt = bt.at[local_slot].set(jnp.where(mine, row, bt[local_slot]))
        nln = ln.at[local_slot].set(jnp.where(mine, true_len, ln[local_slot]))
        return nk, nv, nbt, nln

    with manual_axes("dp"):
        nk, nv, bt, ln = jax.shard_map(
            body, mesh=mesh,
            in_specs=(POOL_DP, POOL_DP, TABLE_DP, VEC_DP, P(), P(), P(), P()),
            out_specs=(POOL_DP, POOL_DP, TABLE_DP, VEC_DP),
            axis_names={"dp"},
        )(state.k, state.v, state.block_tables, state.lengths,
          k, v, new_ids, table_row)
    return PagedState(k=nk, v=nv, block_tables=bt, lengths=ln)


@functools.partial(jax.jit, static_argnames=("n_blocks", "mesh", "slots_per"),
                   donate_argnames=("state",))
def install_prefill_dp(state: PagedState, k, v, block_ids, true_len, slot,
                       n_blocks: int, mesh: Mesh, slots_per: int):
    """install_prefill with the pool dp-sharded: the table row is just the
    fresh block ids (whole-prompt install)."""
    row = jnp.zeros((state.block_tables.shape[1],), jnp.int32)
    row = jax.lax.dynamic_update_slice(row, block_ids, (0,))
    return _install_dp(state, k, v, block_ids, row, true_len, slot,
                       n_new=n_blocks, mesh=mesh, slots_per=slots_per)


@functools.partial(jax.jit, static_argnames=("n_new", "mesh", "slots_per"),
                   donate_argnames=("state",))
def install_with_prefix_dp(state: PagedState, k_suf, v_suf, new_ids, table_row,
                           true_len, slot, n_new: int, mesh: Mesh,
                           slots_per: int):
    """install_with_prefix with the pool dp-sharded: only the suffix KV
    scatters (the cached-prefix blocks are already in the replica's pool); the
    caller-built table row carries cached + new ids."""
    return _install_dp(state, k_suf, v_suf, new_ids, table_row, true_len, slot,
                       n_new=n_new, mesh=mesh, slots_per=slots_per)


@functools.partial(jax.jit, static_argnames=("mesh", "slots_per"),
                   donate_argnames=("state",))
def append_block_dp(state: PagedState, slot, index, block_id, mesh: Mesh,
                    slots_per: int):
    from ray_tpu.parallel.sharding import manual_axes

    replica = slot // slots_per
    local_slot = slot % slots_per

    def body(bt):
        mine = jax.lax.axis_index("dp") == replica
        new = bt.at[local_slot, index].set(block_id)
        return jnp.where(mine, new, bt)

    with manual_axes("dp"):
        bt = jax.shard_map(body, mesh=mesh, in_specs=(TABLE_DP,),
                           out_specs=TABLE_DP, axis_names={"dp"},
                           )(state.block_tables)
    return state._replace(block_tables=bt)


@functools.partial(jax.jit, static_argnames=("cfg", "n_blocks", "mesh",
                                             "slots_per"))
def prefill_suffix_from_state_dp(params, state: PagedState, block_ids, tokens,
                                 true_suffix_len, cfg: ModelConfig,
                                 n_blocks: int, mesh: Mesh, slots_per: int,
                                 slot=None):
    """Prefix-cache warm path under dp: the owning replica gathers its cached
    blocks (others contribute zeros), a psum replicates the context, and the
    suffix prefill runs in auto mode — still ONE device dispatch."""
    from ray_tpu.parallel.sharding import manual_axes

    replica = slot // slots_per

    def gather(pk, pv, ids):
        mine = jax.lax.axis_index("dp") == replica
        ids_eff = jnp.where(mine, ids, pk.shape[1] - 1)
        kb = jnp.where(mine, pk[:, ids_eff], 0)
        vb = jnp.where(mine, pv[:, ids_eff], 0)
        return jax.lax.psum(kb, "dp"), jax.lax.psum(vb, "dp")

    with manual_axes("dp"):
        kb, vb = jax.shard_map(
            gather, mesh=mesh, in_specs=(POOL_DP, POOL_DP, P()),
            out_specs=(P(), P()), axis_names={"dp"},
        )(state.k, state.v, block_ids)
    L, _, bs = kb.shape[0], kb.shape[1], kb.shape[2]
    shape = (L, 1, n_blocks * bs) + kb.shape[3:]
    return _prefill_suffix_impl(params, kb.reshape(shape), vb.reshape(shape),
                                tokens, true_suffix_len, cfg)


@functools.partial(jax.jit, static_argnames=("cfg", "mesh"),
                   donate_argnames=("state",))
def spec_verify_step_paged_dp(params, state: PagedState, window, draft_len,
                              active, cfg: ModelConfig, rng, temperature,
                              top_p, top_k, mesh: Mesh):
    from ray_tpu.parallel.sharding import manual_axes

    from .model_runner import spec_driver

    def body(p, k, v, bt, ln, win, dl, act, rr, tt, tp_, tk):
        rr = jax.random.fold_in(rr, jax.lax.axis_index("dp"))
        nk, nv, lengths, greedy, n_acc = spec_driver(
            p, k, v, ln, win, dl, act, cfg, rr, tt, tp_, tk,
            lambda h, lp, pk, pv: _verify_block_paged(h, lp, cfg, pk, pv,
                                                      bt, ln, act))
        return nk, nv, lengths, greedy, n_acc

    with manual_axes("dp"):
        nk, nv, lengths, greedy, n_acc = jax.shard_map(
            body, mesh=mesh,
            in_specs=(_rep_specs(params), POOL_DP, POOL_DP, TABLE_DP, VEC_DP,
                      TABLE_DP, VEC_DP, VEC_DP, P(), VEC_DP, VEC_DP, VEC_DP),
            out_specs=(POOL_DP, POOL_DP, VEC_DP, TABLE_DP, VEC_DP),
            axis_names={"dp"},
        )(params, state.k, state.v, state.block_tables, state.lengths,
          window, draft_len, active, rng, temperature, top_p, top_k)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), greedy, n_acc


@functools.partial(
    jax.jit, static_argnames=("cfg", "m", "k", "nmax", "mesh"),
    donate_argnames=("state",))
def spec_multi_paged_dp(params, state: PagedState, hist, hlen, active,
                        cfg: ModelConfig, rngs, temperature, top_p, top_k,
                        m: int, k: int, nmax: int, mesh: Mesh):
    from ray_tpu.parallel.sharding import manual_axes

    from .model_runner import propose_ngram_device, spec_multi_impl

    def body(p, pk, pv, bt, ln, hh, hl, act, rr, tt, tp_, tk):
        rr = jax.vmap(lambda r: jax.random.fold_in(r, jax.lax.axis_index("dp")))(rr)
        st = PagedState(k=pk, v=pv, block_tables=bt, lengths=ln)
        st, toks_m, acc_m, drafted_m = spec_multi_impl(
            p, st, hh, hl, act, cfg, rr, tt, tp_, tk, m, k, nmax,
            propose_ngram_device,
            lambda s: lambda x, lp, kk, vv: _verify_block_paged(
                x, lp, cfg, kk, vv, s.block_tables, s.lengths, act),
            lambda s, nk, nv, lengths: PagedState(
                k=nk, v=nv, block_tables=s.block_tables, lengths=lengths))
        return st.k, st.v, st.lengths, toks_m, acc_m, drafted_m

    with manual_axes("dp"):
        nk, nv, lengths, toks_m, acc_m, drafted_m = jax.shard_map(
            body, mesh=mesh,
            in_specs=(_rep_specs(params), POOL_DP, POOL_DP, TABLE_DP, VEC_DP,
                      TABLE_DP, VEC_DP, VEC_DP, P(), VEC_DP, VEC_DP, VEC_DP),
            out_specs=(POOL_DP, POOL_DP, VEC_DP, P(None, "dp", None),
                       P(None, "dp"), P(None, "dp")),
            axis_names={"dp"},
        )(params, state.k, state.v, state.block_tables, state.lengths,
          hist, hlen, active, rngs, temperature, top_p, top_k)
    return PagedState(k=nk, v=nv, block_tables=state.block_tables,
                      lengths=lengths), toks_m, acc_m, drafted_m


class PagedOps:
    """Engine-facing dispatch over the paged device ops: dp=1 delegates to the
    single-pool jits; dp>1 routes through the shard_map variants (the engine's
    call sites stay layout- and mesh-agnostic)."""

    def __init__(self, cfg: ModelConfig, mesh: Optional[Mesh], slots: int):
        self.cfg = cfg
        self.mesh = mesh
        self.dp = _dp_size(mesh)
        self.pp = _pp_size(mesh)
        self.slots_per = slots // max(self.dp, 1)
        if self.pp > 1:
            # jit + pool donation for the hot decode loop (parity with the
            # decode_step_paged jit and the engine's slot-pp _decode_pp_jit)
            self._decode_pp = jax.jit(
                functools.partial(decode_step_paged_pp, cfg=cfg, mesh=mesh),
                donate_argnames=("state",))
            self._spec_pp = jax.jit(
                functools.partial(spec_verify_step_paged_pp, cfg=cfg, mesh=mesh),
                donate_argnames=("state",))

    def install_prefill(self, state, k, v, block_ids, true_len, slot, n_blocks):
        if self.dp > 1:
            return install_prefill_dp(state, k, v, block_ids, true_len, slot,
                                      n_blocks=n_blocks, mesh=self.mesh,
                                      slots_per=self.slots_per)
        return install_prefill(state, k, v, block_ids, true_len, slot,
                               n_blocks=n_blocks)

    def install_with_prefix(self, state, k_suf, v_suf, new_ids, table_row,
                            true_len, slot, n_new):
        if self.dp > 1:
            return install_with_prefix_dp(state, k_suf, v_suf, new_ids,
                                          table_row, true_len, slot,
                                          n_new=n_new, mesh=self.mesh,
                                          slots_per=self.slots_per)
        return install_with_prefix(state, k_suf, v_suf, new_ids, table_row,
                                   true_len, slot, n_new=n_new)

    def append_block(self, state, slot, index, block_id):
        if self.dp > 1:
            return append_block_dp(state, slot, index, block_id,
                                   mesh=self.mesh, slots_per=self.slots_per)
        return append_block(state, slot, index, block_id)

    def prefill_suffix_from_state(self, params, state, block_ids, tokens,
                                  true_suffix_len, n_blocks, slot):
        if self.dp > 1:
            return prefill_suffix_from_state_dp(
                params, state, block_ids, tokens, true_suffix_len, self.cfg,
                n_blocks=n_blocks, mesh=self.mesh, slots_per=self.slots_per,
                slot=slot)
        return prefill_suffix_from_state(params, state, block_ids, tokens,
                                         true_suffix_len, self.cfg,
                                         n_blocks=n_blocks)

    def decode_step(self, params, state, tokens, active):
        if self.pp > 1:
            # handles dp>1 too (slots + pool partition per replica inside the
            # same manual region)
            return self._decode_pp(params, state, tokens, active)
        if self.dp > 1:
            return decode_step_paged_dp(params, state, tokens, active,
                                        self.cfg, self.mesh)
        return decode_step_paged(params, state, tokens, active, self.cfg)

    def decode_multi(self, params, state, tokens, active, rngs, temperature,
                     top_p, top_k, steps_left):
        if self.dp > 1:
            return decode_multi_paged_dp(params, state, tokens, active,
                                         self.cfg, rngs, temperature, top_p,
                                         top_k, steps_left, mesh=self.mesh)
        return decode_multi_paged(params, state, tokens, active, self.cfg,
                                  rngs, temperature, top_p, top_k, steps_left)

    def spec_verify(self, params, state, window, draft_len, active, rng,
                    temperature, top_p, top_k):
        if self.pp > 1:
            # handles dp>1 too (same manual region as the pp decode)
            return self._spec_pp(params, state, window, draft_len, active,
                                 rng, temperature, top_p, top_k)
        if self.dp > 1:
            return spec_verify_step_paged_dp(params, state, window, draft_len,
                                             active, self.cfg, rng,
                                             temperature, top_p, top_k,
                                             mesh=self.mesh)
        return spec_verify_step_paged(params, state, window, draft_len, active,
                                      self.cfg, rng, temperature, top_p, top_k)

    def spec_multi(self, params, state, hist, hlen, active, rngs, temperature,
                   top_p, top_k, m, k, nmax):
        if self.dp > 1:
            return spec_multi_paged_dp(params, state, hist, hlen, active,
                                       self.cfg, rngs, temperature, top_p,
                                       top_k, m=m, k=k, nmax=nmax,
                                       mesh=self.mesh)
        return spec_multi_paged(params, state, hist, hlen, active, self.cfg,
                                rngs, temperature, top_p, top_k, m=m, k=k,
                                nmax=nmax)


# ------------------------------------------------------------------ chunked prefill

def chunked_prefill(params, prompt_ids: List[int], cfg: ModelConfig,
                    chunk: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill a long prompt chunk-at-a-time (reference: vLLM chunked prefill).

    Peak activation memory is one chunk's, not the whole prompt's; the temp KV
    grows to the padded prompt length and is installed into blocks afterwards.
    Returns (k [L,1,S_pad,KV,HD], v, last_logits [vocab] f32)."""
    n = len(prompt_ids)
    s_pad = -(-n // chunk) * chunk
    cache = llama.init_kv_cache(cfg, batch=1, max_len=s_pad,
                                dtype=cfg.activation_dtype)
    last = None
    for start in range(0, s_pad, chunk):
        piece = prompt_ids[start:start + chunk]
        tokens = np.zeros((1, chunk), np.int32)
        tokens[0, : len(piece)] = piece
        logits, cache = _prefill_chunk(params, cache, jnp.asarray(tokens),
                                       jnp.int32(len(piece)), cfg)
        if start < n <= start + chunk:
            last = logits[0, (n - 1) - start].astype(jnp.float32)
    return cache.k, cache.v, last


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _prefill_chunk(params, cache, tokens, true_len, cfg: ModelConfig):
    # pad positions in the final chunk must not claim MoE expert capacity
    # (model_runner.prefill passes the same mask for the same reason)
    mask = (jnp.arange(tokens.shape[1])[None, :] < true_len).astype(jnp.float32)
    logits, cache = llama.forward(params, tokens, cfg, cache=cache, token_mask=mask)
    return logits, cache
