"""LLMEngine ABC + JaxLLMEngine: continuous batching on a device mesh.

Capability parity: reference python/ray/llm/_internal/serve/deployments/llm/
llm_engine.py:15 (``LLMEngine`` — start, generate stream) and vllm_engine.py:180
(``VLLMEngine`` — the continuous-batching loop lives in vLLM's AsyncLLMEngine).
Here the loop is explicit and TPU-shaped: a scheduler thread that (1) admits
waiting requests into free cache slots via a bucketed prefill jit, (2) advances
all active slots with one FUSED K-step decode+sample burst (the default mode —
K auto-tuned from the measured host round trip vs device step time, so one
host sync amortizes over K tokens), (3) streams token bursts out through
per-request queues. Scheduling is barrier-free continuous batching: requests
admit, retire, and abort at burst boundaries without draining the active
batch, and a per-slot step budget on device keeps one near-finished request
from collapsing the burst width for everyone. Every device computation has
static shapes, so after warmup the loop replays cached XLA executables only.
"""
from __future__ import annotations

import abc
import dataclasses
import itertools
import logging
import queue
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.util import telemetry
from ray_tpu.util.hot_path import hot_path

from .config import LLMConfig, SamplingParams
from . import model_runner
from .tokenizer import get_tokenizer

LOGGER = logging.getLogger(__name__)

_METRICS_WARN = None


def _metrics_guard_warn(where: str, e: BaseException) -> None:
    """Metrics must never take the engine down — but a broken exporter must
    not be INVISIBLE either (the PR 8 stale-registry bug hid behind exactly
    this pattern). One warning per 30s per call site, so one failing
    exporter does not mute the others' first report."""
    global _METRICS_WARN
    if _METRICS_WARN is None:
        from ray_tpu.util.logutil import LogThrottle

        _METRICS_WARN = LogThrottle(30.0)
    if _METRICS_WARN.ready(where):
        LOGGER.warning("engine telemetry export failed in %s (suppressed for "
                       "30s): %r", where, e)


@dataclasses.dataclass
class RequestOutput:
    """One streamed chunk: the tokens emitted since the previous chunk."""

    request_id: str
    token_ids: List[int]
    text: str = ""
    finished: bool = False
    finish_reason: Optional[str] = None  # "stop" | "length"
    num_prompt_tokens: int = 0
    num_generated_tokens: int = 0


class LLMEngine(abc.ABC):
    """Engine interface (reference llm_engine.py:15)."""

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def generate(self, prompt: Any, params: SamplingParams, request_id: Optional[str] = None
                 ) -> Iterator[RequestOutput]: ...

    @abc.abstractmethod
    def shutdown(self) -> None: ...


def _pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1). Burst widths are quantized to
    powers of two: every distinct K is its own XLA trace, so this bounds the
    engine to log2(K_max)+1 compiled decode programs."""
    return 1 << (max(1, int(n)).bit_length() - 1)


class _Request:
    def __init__(self, req_id: str, prompt_ids: List[int], params: SamplingParams,
                 prefill_kv=None):
        self.id = req_id
        self.prompt_ids = prompt_ids
        self.params = params
        self.out_queue: "queue.Queue[RequestOutput]" = queue.Queue()
        self.generated = 0
        self.slot = -1
        self.prefill_kv = prefill_kv  # (k, v, first_token): P/D-disagg transfer-in
        # paged streaming handoff: in-flight PagedKVFetch whose pages stream
        # concurrently with other requests' decode bursts; admission defers
        # until it is ready and resolves it into prefill_kv
        self.kv_fetch = None
        self.kv_fetch_error = None  # DevicePlaneError a failed fetch resolved to
        # completed fetch whose staging buffer prefill_kv still aliases;
        # recycled once the KV is installed (or the request fails)
        self.kv_staging = None
        self.kv_first_token = 0
        self.first_emitted = False  # first token streamed at arrival (TTFT
        # rides the handle); admission must not emit it again
        self.pending_text: List[int] = []  # undecoded ids (byte tokenizer is stateless)
        # prompt + every sampled token: recompute-preemption (paged pool
        # exhausted) re-prefills from this history so decoding continues exactly
        self.token_history: List[int] = list(prompt_ids)
        self.admitted_at = 0  # admission sequence number (preemption picks youngest)
        # request-lifecycle telemetry (queue -> prefill -> decode spans, TTFT,
        # tokens/s) + the prefix-cache evidence the Serve decode work needs:
        # how many prompt tokens the cache served vs how many prefill computed
        self.created_wall_ns = time.time_ns()
        self.created_perf_ns = time.perf_counter_ns()
        self.first_token_perf_ns = 0
        self.queue_recorded = False
        self.finish_recorded = False
        self.prefix_hit_tokens = -1  # -1 = no paged prefill ran (yet)
        self.prefill_tokens = 0  # tokens the model actually prefilled
        # request-scoped trace: captured at creation (the caller's thread —
        # serve replica / router with the propagated context); the scheduler
        # loop that records the queue/prefill/decode spans has no context
        try:
            from ray_tpu.util.tracing import current_trace_id

            self.trace_id = current_trace_id()
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (self.trace_id = None) by design
        except Exception:
            self.trace_id = None


class JaxLLMEngine(LLMEngine):
    """Slot-based continuous batching over jitted prefill/decode (model_runner.py)."""

    def __init__(self, config: LLMConfig, params=None, mesh=None):
        self.config = config
        self.model_config = config.resolve_model_config()
        self.tokenizer = get_tokenizer(config.resolve_tokenizer_name())
        self._mesh = mesh
        self._params_in = params
        self._started = False
        self._shutdown = False
        self._waiting: "queue.Queue[_Request]" = queue.Queue()
        self._active: Dict[int, Optional[_Request]] = {}
        self._lock = threading.Lock()
        self._start_lock = threading.Lock()
        self._rng_lock = threading.Lock()
        self._loop_thread: Optional[threading.Thread] = None
        self._wakeup = threading.Event()
        self._admitting: Optional[_Request] = None  # mid-admission request
        # live requests by id (waiting or active); abort() only marks ids found
        # here, so a stale abort can never poison a later request reusing the id
        self._requests: Dict[str, "_Request"] = {}
        # request ids cancelled via abort(); acted on at admission (waiting) or
        # the next loop tick (active), cleared on request release
        self._aborted: set = set()
        self.state = None  # decode KV state, allocated on first decode admission
        # fused-decode fast path (resolved in start(); harmless defaults so an
        # unstarted engine's helpers — e.g. _propose_ngram in tests — work)
        self._fused_auto = False
        self._fused_fixed = 1
        self._fused_max = 1
        self._sync_target = 0.15
        self._host_rt_s = 0.0  # measured tiny dispatch+fetch round trip
        self._step_s = 0.0  # EWMA of per-decode-step device time
        self._k_seen: set = set()  # burst widths already compiled (first
        # burst at a new K carries compile time; skip it in the EWMA)
        self._prefill_per_tok_s = 0.0  # EWMA: prefill seconds per computed token
        self._last_tick_monotonic = time.monotonic()  # loop liveness (health)
        # metrics (scraped by LLMServer / autoscaling)
        self.num_pending = 0
        self.num_active = 0
        self.total_generated = 0
        self.num_preemptions = 0
        self.num_aborted = 0
        self.num_spec_drafted = 0
        self.num_spec_accepted = 0
        self.num_prefix_skipped = 0  # pay-or-skip gate declined the cache
        # P/D export bookkeeping (prefill side): (monotonic, key) per un-acked
        # KV export, LRU/TTL-pruned by _track_pd_export and the lazy prune
        # daemon; kept in sync with the device plane's own releases (consumer
        # acks, TTL sweeps) through a plane release listener
        self._pd_exports: List[Tuple[float, bytes]] = []
        self._pd_prune_thread: Optional[threading.Thread] = None
        self._pd_listener_registered = False

    # -- lifecycle ---------------------------------------------------------------
    def start(self) -> None:
        """Load + shard params (thread-safe, idempotent). The decode KV state and
        scheduler loop are allocated lazily on first decode use, so a dedicated
        prefill replica (P/D disaggregation) never pays for them."""
        with self._start_lock:
            if self._started:
                return
            from ray_tpu.usage import record_library_usage

            record_library_usage("llm")
            cfg = self.model_config
            c = self.config
            if self._mesh is None:
                # pp*dp*ep*tp devices out of the local set (an engine may
                # intentionally use a subset, e.g. one replica per chip).
                from jax.sharding import Mesh

                pp = c.pipeline_parallel_size
                n = (pp * c.data_parallel_size * c.expert_parallel_size
                     * c.tensor_parallel_size)
                devs = jax.devices()
                if len(devs) < n:
                    raise ValueError(f"need {n} devices for pp×dp×ep×tp, have {len(devs)}")
                if pp > 1:
                    self._mesh = Mesh(
                        np.asarray(devs[:n]).reshape(
                            pp, c.data_parallel_size, c.expert_parallel_size,
                            c.tensor_parallel_size),
                        ("pp", "dp", "ep", "tp"),
                    )
                else:
                    self._mesh = Mesh(
                        np.asarray(devs[:n]).reshape(
                            c.data_parallel_size, c.expert_parallel_size,
                            c.tensor_parallel_size
                        ),
                        ("dp", "ep", "tp"),
                    )
            # fused decode is the default engine mode: explicit
            # num_decode_steps, else RAY_TPU_LLM_FUSED_STEPS (0 = auto-tune K
            # from measured host round trip vs device step time)
            from ray_tpu.config import CONFIG as _CFG

            k_cfg = c.resolve_decode_steps()
            self._fused_auto = k_cfg == 0
            # the cap bounds only the AUTO-tuned K (as documented); an
            # explicitly configured burst width is honored (pow2-quantized)
            self._fused_max = _pow2_floor(max(1, _CFG.llm_fused_steps_max))
            self._fused_fixed = 1 if self._fused_auto else _pow2_floor(k_cfg)
            self._sync_target = min(max(_CFG.llm_fused_sync_target, 0.01), 0.9)
            if c.pipeline_parallel_size > 1:
                if c.max_num_seqs % (c.pipeline_parallel_size
                                     * c.data_parallel_size):
                    raise ValueError(
                        "max_num_seqs must divide by pp*dp (slots shard over "
                        "dp replicas, then microbatch over pp stages)")
                if cfg.n_layers % c.pipeline_parallel_size:
                    raise ValueError("n_layers must divide by pipeline_parallel_size")
                if not cfg.scan_layers:
                    raise ValueError("pipeline_parallel_size > 1 requires scan_layers")
                if self._fused_auto or self._fused_fixed > 1:
                    # pp decode keeps per-step scheduling (microbatch ticks):
                    # downgrade cleanly instead of warning about a user knob
                    LOGGER.info(
                        "llm.engine model=%s: pipeline_parallel_size=%d keeps "
                        "per-step decode scheduling; fused multi-step decode "
                        "(num_decode_steps=%s) downgraded to 1",
                        c.model_id, c.pipeline_parallel_size,
                        "auto" if self._fused_auto else self._fused_fixed)
                    self._fused_auto = False
                    self._fused_fixed = 1
                    self._fused_max = 1
            if c.max_num_seqs % c.data_parallel_size:
                raise ValueError("max_num_seqs must be divisible by data_parallel_size")
            if c.kv_layout == "paged":
                if c.data_parallel_size > 1:
                    # paged ⊗ dp: per-replica pool partitions (paged.py dp
                    # section); the pool must split evenly across replicas
                    num_blocks = c.num_kv_blocks or (
                        c.max_num_seqs * c.max_model_len // c.kv_block_size)
                    if num_blocks % c.data_parallel_size:
                        raise ValueError(
                            f"num_kv_blocks ({num_blocks}) must divide by "
                            f"data_parallel_size ({c.data_parallel_size})")
                if c.max_model_len % c.kv_block_size:
                    raise ValueError("max_model_len must be a multiple of kv_block_size")
                if any(b % c.kv_block_size for b in c.buckets()):
                    raise ValueError(
                        "every prefill bucket must be a multiple of kv_block_size")
                if c.prefill_chunk and c.prefill_chunk % c.kv_block_size:
                    raise ValueError(
                        "prefill_chunk must be a multiple of kv_block_size "
                        "(chunked KV installs block-by-block)")
            elif c.kv_layout != "slot":
                raise ValueError(f"unknown kv_layout {c.kv_layout!r}")
            if c.num_speculative_tokens:
                if c.speculative_method != "ngram":
                    raise NotImplementedError(
                        f"speculative_method {c.speculative_method!r}: only "
                        "'ngram' (prompt lookup) is implemented")
            if c.prefill_chunk and c.max_model_len % c.prefill_chunk:
                # guarantees a chunk-padded prompt never exceeds max_model_len
                # (the block table / slot cache width)
                raise ValueError("max_model_len must be a multiple of prefill_chunk")
            if c.quantization:
                # validate BEFORE any checkpoint load: streaming a full model
                # onto devices just to reject the config string is hostile
                if c.quantization != "int8":
                    raise ValueError(
                        f"unknown quantization {c.quantization!r} (supported: int8)")
            if self._params_in is not None:
                self.params = model_runner.shard_params(self._params_in, cfg, self._mesh)
            else:
                from ray_tpu.models import checkpoint as ckpt_io

                if ckpt_io.looks_like_checkpoint_dir(c.model_source):
                    # real weights: stream safetensors straight into the sharded
                    # pytree (reference vllm_engine.py:180 — an engine that can't
                    # load a model is a demo)
                    self.params = ckpt_io.load_llama_params(
                        c.model_source, cfg, self._mesh,
                        rules=model_runner.infer_rules_for_mesh(self._mesh),
                        param_dtype=jnp.dtype(c.dtype))
                else:
                    self.params = model_runner.shard_params(
                        llama_init_cached(cfg), cfg, self._mesh)
            self._params_in = None
            if c.quantization:
                from ray_tpu.ops.quant import quantize_llama_params

                # quantize on device AFTER sharding: per-output-channel int8
                # weights + scales; dequant fuses into each matmul's operand
                # read (ops/quant.py)
                self.params = jax.jit(quantize_llama_params)(self.params)
            self._active = {s: None for s in range(c.max_num_seqs)}
            self._admission_counter = itertools.count(1)
            if c.pipeline_parallel_size > 1:
                import functools

                self._decode_pp_jit = jax.jit(
                    functools.partial(model_runner.decode_step_pp,
                                      cfg=cfg, mesh=self._mesh),
                    donate_argnames=("state",))
                if c.num_speculative_tokens:
                    self._spec_pp_jit = jax.jit(
                        functools.partial(model_runner.spec_verify_step_pp,
                                          cfg=cfg, mesh=self._mesh),
                        donate_argnames=("state",))
            # graftlint: allow[lock-hygiene] one-time init under _start_lock, before any _next_rng caller exists; steady-state splits hold _rng_lock
            self._rng = jax.random.PRNGKey(0)
            # host mirrors of per-slot sampling params
            n = c.max_num_seqs
            self._temp = np.zeros((n,), np.float32)
            self._top_p = np.ones((n,), np.float32)
            self._top_k = np.zeros((n,), np.int32)
            self._last_tokens = np.zeros((n,), np.int32)
            self._started = True

    def _ensure_decode_started(self) -> None:
        """Allocate the decode KV state + scheduler loop on first decode use."""
        with self._start_lock:
            if self._loop_thread is not None:
                return
            c = self.config
            if self.state is None:
                if c.kv_layout == "paged":
                    from . import paged

                    num_blocks = c.num_kv_blocks or (
                        c.max_num_seqs * c.max_model_len // c.kv_block_size)
                    self._blocks = paged.make_block_manager(
                        num_blocks, c.kv_block_size,
                        c.max_model_len // c.kv_block_size, c.max_num_seqs,
                        dp=c.data_parallel_size,
                        enable_prefix_caching=c.enable_prefix_caching)
                    self._pops = paged.PagedOps(
                        self.model_config, self._mesh, c.max_num_seqs)
                    self.state = paged.init_paged_state(
                        self.model_config, c.max_num_seqs, c.max_model_len,
                        num_blocks, c.kv_block_size, self._mesh)
                else:
                    self.state = model_runner.init_state(
                        self.model_config, c.max_num_seqs, c.max_model_len, self._mesh)
            self._measure_host_rt()
            self._loop_thread = threading.Thread(target=self._loop, daemon=True,
                                                 name="llm-engine")
            self._loop_thread.start()

    # -- fused-burst auto-tune ----------------------------------------------------
    def _measure_host_rt(self, samples: int = 3) -> None:
        """Measure the fixed per-dispatch host round trip (dispatch + fetch of
        a scalar): ~100 µs on local chips, ~110 ms through a network tunnel.
        This is the cost fused bursts amortize, and the dispatch cost the
        prefix-cache pay-or-skip gate compares savings against."""
        try:
            x = jnp.zeros((), jnp.int32)
            np.asarray(x + 1)  # compile outside the timed region
            best = float("inf")
            for _ in range(samples):
                t0 = time.perf_counter()
                np.asarray(x + 1)
                best = min(best, time.perf_counter() - t0)
            self._host_rt_s = max(best, 1e-7)
        except Exception as e:
            self._host_rt_s = 0.0  # unmeasured: auto-K stays at 1, gate open
            LOGGER.warning(
                "host round-trip measurement failed (%r): fused-decode "
                "auto-K is disabled, the engine runs per-step synced — "
                "expect tunnel-era decode throughput", e)

    def decode_steps_target(self) -> int:
        """Current fused burst width target (power of two). Fixed K when
        configured; in auto mode, the smallest K that brings the host-sync
        share of a burst — rt/(rt + K*step) — down to the configured target
        fraction, from the measured round trip and device-step EWMA."""
        if not self._fused_auto:
            return self._fused_fixed
        rt, step = self._host_rt_s, self._step_s
        if rt <= 0 or step <= 0:
            return 1  # unmeasured yet: first bursts run per-step and probe
        f = self._sync_target
        need = rt * (1.0 - f) / (f * step)
        if need <= 1.0:
            return 1  # local chips: syncing every step is already cheap
        k = 1 << int(np.ceil(np.log2(need)))
        return max(1, min(k, self._fused_max))

    def _note_burst_device_wall(self, k: int, wall_s: float) -> None:
        """Fold one burst's dispatch->fetch wall time into the device-step
        EWMA (wall = rt + K*step). The first burst at each K carries its XLA
        compile and is skipped."""
        if k not in self._k_seen:
            self._k_seen.add(k)
            return
        est = max((wall_s - self._host_rt_s) / k, 1e-6)
        self._step_s = est if self._step_s <= 0 else (
            0.5 * est + 0.5 * self._step_s)

    def decode_host_sync_fraction(self) -> float:
        """Estimated share of decode wall time spent on the host round trip
        at the current burst width (the quantity auto-K minimizes)."""
        rt, step = self._host_rt_s, self._step_s
        if rt <= 0 or step <= 0:
            return 0.0
        k = self.decode_steps_target()
        return rt / (rt + k * step)

    def _slot_steps_left(self, req: "_Request") -> int:
        """Decode steps this request can still take: its remaining max_tokens
        budget capped by remaining KV room (both >= 1 for a live request —
        exhaustion finishes it in the previous burst's emit)."""
        next_write = len(req.prompt_ids) + req.generated - 1
        kv_room = (self.config.max_model_len - 1) - next_write
        budget = req.params.max_tokens - req.generated
        return max(1, min(kv_room, budget))

    def _burst_plan(self):
        """(k_steps, steps_left[slots]) for the next fused burst. K is the
        auto/fixed target capped by the LONGEST-running slot's budget (power
        of two); each slot's own budget rides to the device as steps_left, so
        short requests stop at their limit without capping the batch — the
        barrier the old min-over-slots burst width imposed."""
        n = self.config.max_num_seqs
        steps = np.ones((n,), np.int32)
        max_sl = 0
        for slot, req in self._active.items():
            if req is None:
                continue
            sl = self._slot_steps_left(req)
            steps[slot] = sl
            max_sl = max(max_sl, sl)
        k = _pow2_floor(min(self.decode_steps_target(), max(1, max_sl)))
        np.minimum(steps, k, out=steps)
        return k, steps

    def _next_rng(self):
        with self._rng_lock:
            self._rng, sub = jax.random.split(self._rng)
            return sub

    def _encode_prompt(self, prompt, params: SamplingParams) -> List[int]:
        """Tokenize + truncate so the generation fits max_model_len."""
        ids = self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        limit = max(1, self.config.max_model_len - params.max_tokens)
        return ids[-limit:] if len(ids) > limit else ids

    def _pad_to_bucket(self, prompt_ids: List[int]):
        s_pad = next(b for b in self.config.buckets() if b >= len(prompt_ids))
        tokens = np.zeros((1, s_pad), np.int32)
        tokens[0, : len(prompt_ids)] = prompt_ids
        return tokens

    def shutdown(self) -> None:
        self._shutdown = True
        self._wakeup.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)

    # -- API ---------------------------------------------------------------------
    def generate(self, prompt, params: SamplingParams, request_id: Optional[str] = None
                 ) -> Iterator[RequestOutput]:
        self.start()
        self._ensure_decode_started()
        prompt_ids = self._encode_prompt(prompt, params)
        req = _Request(request_id or uuid.uuid4().hex, prompt_ids, params)
        with self._lock:
            self.num_pending += 1
            self._requests[req.id] = req
        self._waiting.put(req)
        self._wakeup.set()

        while True:
            out = req.out_queue.get()
            yield out
            if out.finished:
                return

    def abort(self, request_id: str) -> None:
        """Cancel a request (e.g. its SSE client disconnected): a waiting
        request is failed at admission; an active one frees its slot/KV blocks
        at the next scheduler tick instead of decoding to max_tokens.
        Reference: vllm engine abort_request semantics."""
        with self._lock:
            if request_id not in self._requests:
                return  # already finished (or unknown): nothing to cancel
            self._aborted.add(request_id)
        self._wakeup.set()

    def _finish_abort(self, req: "_Request") -> bool:
        """If `req` was cancelled, finish it now — abort chunk to the client,
        slot and paged blocks freed immediately — and return True. Called from
        every burst-boundary emit path, so a request cancelled while a fused
        burst is in flight on device stops emitting at the boundary (its
        burst tail is discarded) instead of streaming to max_tokens."""
        with self._lock:
            if req.id not in self._aborted:
                return False
        req.out_queue.put(RequestOutput(
            request_id=req.id, token_ids=[], finished=True,
            finish_reason="abort", num_prompt_tokens=len(req.prompt_ids),
            num_generated_tokens=req.generated))
        self.num_aborted += 1
        self._release(req)
        with self._lock:
            self._aborted.discard(req.id)
        return True

    def _process_aborts(self) -> None:
        """Release active slots whose request was aborted (called every tick)."""
        with self._lock:
            if not self._aborted:
                return
        for slot, req in list(self._active.items()):
            if req is not None:
                self._finish_abort(req)

    # -- P/D disaggregation (reference: prefill_decode_disagg deployments) ---------
    def prefill_only(self, prompt, params: SamplingParams,
                     force_host: bool = False) -> Dict[str, Any]:
        """Run prefill and return transferable KV + the sampled first token.
        Used by prefill replicas; the result feeds generate_from_prefill on a
        decode replica. With the device plane up, the KV stays device-resident
        here and the decode replica pulls it device-to-device (DCN on pods —
        reference: NCCL KV handoff in prefill_decode_disagg); only a ~1 KB handle
        rides the control plane. Otherwise the KV travels as host arrays through
        the object store. Does NOT allocate the decode state — prefill replicas
        stay KV-cache-free."""
        self.start()
        prompt_ids = self._encode_prompt(prompt, params)
        # chunk-aware: a P/D prefill replica is exactly where long-prompt
        # activation memory must stay bounded
        k, v, last_logits = self._prefill_kv_tensors(prompt_ids)
        tok = int(model_runner.sample_tokens(
            self._next_rng(), last_logits[None, :],
            jnp.asarray([params.temperature], jnp.float32),
            jnp.asarray([params.top_p], jnp.float32),
            jnp.asarray([params.top_k], jnp.int32),
        )[0])
        out = {"prompt_ids": prompt_ids, "first_token": tok}
        # pre-rendered first-token text: lets the P/D router mint the first
        # SSE content frame the moment this result lands, without waiting for
        # the decode replica's stream to start (TTFT rides prefill alone).
        # Stop tokens emit no content and a token that decodes to a partial
        # UTF-8 codepoint can't be rendered alone — both leave first_text
        # unset and the router falls back to relaying the decode stream.
        stops = params.stop_token_ids or [self.tokenizer.eos_token_id]
        if tok not in stops:
            txt = self.tokenizer.decode([tok])
            if txt and not txt.endswith("�"):
                out["first_text"] = txt
        from ray_tpu.config import CONFIG as _CFG
        from ray_tpu.core import device_plane as _dp

        if self.config.kv_layout == "paged":
            # ship only the block-aligned prefix the decode side installs —
            # the bucket-pad tail is attention-masked garbage it re-pads anyway
            from .paged import trim_kv_for_transfer

            k, v = trim_kv_for_transfer(k, v, len(prompt_ids),
                                        self.config.kv_block_size)
        dp = _dp.plane()
        use_paged = bool(_CFG.pd_paged) and dp.paged_available
        if not force_host and (use_paged or dp.available):
            # plane-level ttl: backstop for a decode replica that crashes
            # before acking (the engine's own tracker prunes sooner)
            if use_paged:
                # block-addressable region on the striped data plane: the
                # decode side pulls it page-by-page over multiple streams,
                # overlapped with its decode bursts
                handle = dp.export_paged({"k": k, "v": v},
                                         ttl_s=_CFG.pd_export_ttl_s,
                                         page_bytes=_CFG.pd_page_bytes)
            else:
                handle = dp.export({"k": k, "v": v}, ttl_s=_CFG.pd_export_ttl_s)
            self._track_pd_export(handle.key)
            out["kv_handle"] = handle
            out["kv_key"] = handle.key.hex()
        else:
            out["k"] = np.asarray(k)
            out["v"] = np.asarray(v)
        return out

    def _track_pd_export(self, key: bytes, max_live: int = None,
                         ttl_s: float = None) -> None:
        """Exports pin device KV until the decode side's pull acks (fetch
        release=True); this LRU/TTL prune is the backstop for crashed consumers.
        Guarded by the engine lock: prefill and decode-ack run on different
        request threads. Defaults from CONFIG: pd_export_max_live, and half of
        pd_export_ttl_s so the engine prunes before the plane-level backstop."""
        import time as _time

        from ray_tpu.config import CONFIG as _CFG
        from ray_tpu.core import device_plane as _dp

        if max_live is None:
            max_live = _CFG.pd_export_max_live
        if ttl_s is None:
            ttl_s = _CFG.pd_export_ttl_s / 2
        self._ensure_pd_release_listener()
        now = _time.monotonic()
        stale = []
        with self._lock:
            pending = self._pd_exports
            pending.append((now, key))
            while pending and (len(pending) > max_live or now - pending[0][0] > ttl_s):
                stale.append(pending.pop(0)[1])
            if self._pd_prune_thread is None:
                # TTL enforcement can't depend on the NEXT prefill arriving —
                # a crashed consumer with no follow-on traffic would pin KV
                # forever. A lazy daemon sweeps on a timer.
                self._pd_prune_thread = threading.Thread(
                    target=self._pd_prune_loop, daemon=True,
                    name="rt-pd-export-prune")
                self._pd_prune_thread.start()
        for old in stale:
            _dp.plane().release(old)

    def _ensure_pd_release_listener(self) -> None:
        """Keep _pd_exports in lockstep with the device plane: consumer acks
        ride the arm channel straight to the plane (pool routing cannot
        address 'the replica that prefilled'), so the engine learns about
        them through the plane's release listener rather than polling. A
        WeakMethod keeps retired engines collectable — the plane is a
        process singleton."""
        if self._pd_listener_registered:
            return
        import weakref

        from ray_tpu.core import device_plane as _dp

        with self._lock:
            if self._pd_listener_registered:
                return
            self._pd_listener_registered = True
        ref = weakref.WeakMethod(self._on_pd_export_released)

        def _cb(key, _ref=ref):
            m = _ref()
            if m is not None:
                m(key)

        _dp.plane().add_release_listener(_cb)

    def _on_pd_export_released(self, key: bytes) -> None:
        with self._lock:
            if self._pd_exports:
                self._pd_exports[:] = [e for e in self._pd_exports
                                       if e[1] != key]

    def _pd_prune_loop(self, interval_s: float = 30.0,
                       ttl_s: float = None) -> None:
        import time as _time

        from ray_tpu.config import CONFIG as _CFG
        from ray_tpu.core import device_plane as _dp

        if ttl_s is None:
            ttl_s = _CFG.pd_export_ttl_s / 2

        while not self._shutdown:
            _time.sleep(interval_s)
            now = _time.monotonic()
            stale = []
            with self._lock:
                pending = self._pd_exports
                while pending and now - pending[0][0] > ttl_s:
                    stale.append(pending.pop(0)[1])
            for old in stale:
                _dp.plane().release(old)

    def release_prefill_export(self, key_hex: str) -> None:
        """Decode-side ack: the KV for this prefill was pulled (or abandoned)."""
        from ray_tpu.core import device_plane as _dp

        key = bytes.fromhex(key_hex)
        _dp.plane().release(key)
        with self._lock:
            if self._pd_exports:
                self._pd_exports[:] = [e for e in self._pd_exports
                                       if e[1] != key]

    def generate_from_prefill(self, prefill_result: Dict[str, Any],
                              params: SamplingParams,
                              request_id: Optional[str] = None
                              ) -> Iterator[RequestOutput]:
        """Continue decoding from a transferred prefill (decode replica side).

        The device-plane handle is validated EAGERLY (not at first next()) so
        a dead export raises here — where the P/D router can still fall back
        to the host path — rather than mid-stream.

        Paged handles stream: the first token (sampled prefill-side, riding
        the ~1 KB handle) is emitted immediately, the KV pages pull over
        multiple streams concurrently with the active batch's decode bursts,
        and the request admits at a burst boundary once its pages have
        landed. A mid-transfer failure surfaces as a typed DevicePlaneError
        from the stream, which the PDRouter converts into its host-fallback
        replay."""
        self.start()
        self._ensure_decode_started()
        fetch = None
        if "kv_handle" in prefill_result:
            from ray_tpu.core import device_plane as _dp

            handle = prefill_result["kv_handle"]
            if isinstance(handle, _dp.PagedKVHandle):
                # raises DevicePlaneError here if the export is already gone
                fetch = _dp.plane().fetch_paged(handle, release=True,
                                                on_done=self._wakeup.set)
                req = _Request(request_id or uuid.uuid4().hex,
                               list(prefill_result["prompt_ids"]), params)
                req.kv_fetch = fetch
                req.kv_first_token = int(prefill_result["first_token"])
            else:
                t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
                kv = _dp.plane().fetch(handle, release=True)
                self._record_kv_handoff_raw(
                    handle.nbytes, (time.perf_counter_ns() - t0_perf) / 1e9,
                    t0_wall, mode="monolithic")
                req = _Request(
                    request_id or uuid.uuid4().hex,
                    list(prefill_result["prompt_ids"]), params,
                    prefill_kv=(kv["k"], kv["v"],
                                int(prefill_result["first_token"])),
                )
        else:
            req = _Request(
                request_id or uuid.uuid4().hex,
                list(prefill_result["prompt_ids"]), params,
                prefill_kv=(prefill_result["k"], prefill_result["v"],
                            int(prefill_result["first_token"])),
            )
        with self._lock:
            self.num_pending += 1
            self._requests[req.id] = req
        if fetch is not None and self._emit_prefill_first_token(req):
            pass  # finished on its first token: never queued, fetch abandoned
        else:
            self._waiting.put(req)
            self._wakeup.set()

        def _stream() -> Iterator[RequestOutput]:
            while True:
                out = req.out_queue.get()
                if out.finish_reason == "kv_transfer":
                    from ray_tpu.core.device_plane import DevicePlaneError

                    err = req.kv_fetch_error if req.kv_fetch_error is not None \
                        else DevicePlaneError("paged KV transfer failed")
                    raise err
                yield out
                if out.finished:
                    return

        return _stream()

    def _emit_prefill_first_token(self, req: _Request) -> bool:
        """Paged P/D handoff: stream the prefill-sampled first token NOW —
        TTFT rides the handle, not the KV payload. Returns True when that
        token already finishes the request (stop token or max_tokens == 1);
        it then never enters the waiting queue and the in-flight fetch is
        abandoned (with a release ack, so the producer unpins)."""
        tok = req.kv_first_token
        req.generated = 1
        req.token_history.append(tok)
        req.first_emitted = True
        self._record_first_token(req)
        with self._lock:
            self.total_generated += 1
        stops = req.params.stop_token_ids or [self.tokenizer.eos_token_id]
        finished, reason = False, None
        if tok in stops:
            finished, reason = True, "stop"
        elif req.generated >= req.params.max_tokens:
            finished, reason = True, "length"
        emit_ids = [] if reason == "stop" else [tok]
        req.out_queue.put(RequestOutput(
            request_id=req.id, token_ids=emit_ids,
            text=self.tokenizer.decode(emit_ids) if emit_ids else "",
            finished=finished, finish_reason=reason,
            num_prompt_tokens=len(req.prompt_ids), num_generated_tokens=1,
        ))
        if finished:
            req.kv_fetch.cancel()
            req.kv_fetch = None
            self._record_finish(req)
            with self._lock:
                self.num_pending -= 1
                self._requests.pop(req.id, None)
                self._aborted.discard(req.id)
        return finished

    def generate_sync(self, prompt, params: SamplingParams) -> RequestOutput:
        """Collect the full generation into one RequestOutput."""
        ids: List[int] = []
        last = None
        for chunk in self.generate(prompt, params):
            ids.extend(chunk.token_ids)
            last = chunk
        return RequestOutput(
            request_id=last.request_id,
            token_ids=ids,
            text=self.tokenizer.decode(ids),
            finished=True,
            finish_reason=last.finish_reason,
            num_prompt_tokens=last.num_prompt_tokens,
            num_generated_tokens=len(ids),
        )

    def metrics(self) -> Dict[str, Any]:
        """Engine health + paged-KV performance counters (reference: vllm
        engine stats — pool occupancy, prefix-cache hits, preemptions — the
        numbers that validate the paged design under load)."""
        out = {
            "num_pending": self.num_pending,
            "num_active": self.num_active,
            "total_generated": self.total_generated,
            "num_preemptions": self.num_preemptions,
            "num_aborted": self.num_aborted,
            "num_spec_drafted": self.num_spec_drafted,
            "num_spec_accepted": self.num_spec_accepted,
            "num_prefix_skipped": self.num_prefix_skipped,
            # P/D: device-plane KV exports this engine still pins (leak probe
            # for the chaos gate — consumer acks must drain it, not the TTL)
            "pd_exports_live": len(self._pd_exports),
            # fused fast path: current burst width and where the decode wall
            # time goes (the quantity auto-K minimizes; the bench gates on it)
            "decode_fused_steps": self.decode_steps_target(),
            "decode_host_sync_fraction": round(
                self.decode_host_sync_fraction(), 4),
            "decode_host_rt_ms": round(self._host_rt_s * 1e3, 4),
            "decode_device_step_ms": round(self._step_s * 1e3, 4),
        }
        blocks = getattr(self, "_blocks", None)
        if blocks is not None:
            total = blocks.total_blocks
            free = blocks.num_free
            out.update({
                "kv_blocks_total": total,
                "kv_blocks_free": free,
                "kv_pool_occupancy": (total - free) / total if total else 0.0,
                "prefix_cache_hit_tokens": blocks.hit_tokens,
                "prefix_cached_blocks": len(blocks.cached),
            })
        self._export_metrics(out)
        return out

    def _export_metrics(self, snap: Dict[str, Any]) -> None:
        """Mirror the engine counters into the cluster metric registry so they
        ride /metrics -> Prometheus/Grafana (reference: vllm stat loggers
        feeding Ray metrics)."""
        try:
            from ray_tpu.util.metrics import Gauge

            tags = {"model": str(self.config.model_id)}
            for name, value in snap.items():
                if not isinstance(value, (int, float)):
                    continue
                # module-level cache: engines share one gauge per metric name
                # (the model tag separates them); per-engine gauges would
                # evict each other from the process registry
                g = _PROM_GAUGES.get(name)
                if g is None:
                    g = Gauge(f"llm_{name}", f"engine {name}", tag_keys=("model",))
                    _PROM_GAUGES[name] = g
                g.set(float(value), tags=tags)
        except Exception as e:
            _metrics_guard_warn("_export_metrics", e)

    # -- request-lifecycle telemetry ----------------------------------------------
    def _model_tag(self) -> Dict[str, str]:
        return {"model": str(self.config.model_id)}

    @staticmethod
    def _prefill_tokens_of(req: _Request) -> int:
        """Tokens the model actually prefilled. Only the paged path tracks a
        cached/computed split (prefix_hit_tokens >= 0); every other layout
        prefills the whole prompt."""
        if req.prefix_hit_tokens >= 0:
            return req.prefill_tokens
        return len(req.prompt_ids)

    def _record_prefill(self, req: _Request, t_admit_perf: int) -> None:
        """Prefill-phase signals, recorded once per successful admission:
        latency, computed-vs-cached token counts, and the per-request
        hit/miss evidence behind prefix_cache_ttft_speedup (why does the
        cache win or lose? the spans now say).

        Guarded like _export_metrics: these run inside the scheduler loop,
        and metrics must never take the engine down."""
        try:
            self._record_prefill_inner(req, t_admit_perf)
        except Exception as e:
            _metrics_guard_warn("_record_prefill", e)

    def _record_prefill_inner(self, req: _Request, t_admit_perf: int) -> None:
        dur = time.perf_counter_ns() - t_admit_perf
        # per-token prefill cost EWMA (dispatch round trip subtracted): the
        # prefix-cache pay-or-skip gate's estimate of what a cached token
        # saves. A first-compile sample inflates it, which only biases the
        # gate toward USING the cache — the safe direction — and washes out.
        computed = max(1, self._prefill_tokens_of(req))
        per_tok = max((dur / 1e9 - self._host_rt_s) / computed, 1e-9)
        self._prefill_per_tok_s = per_tok if self._prefill_per_tok_s <= 0 else (
            0.3 * per_tok + 0.7 * self._prefill_per_tok_s)
        tags = self._model_tag()
        telemetry.get_histogram(
            "llm_prefill_seconds", "engine prefill latency per admission",
            tag_keys=("model",)).observe(dur / 1e9, tags=tags)
        if req.prefix_hit_tokens >= 0:  # a paged prefill ran for this admission
            name = ("llm_prefix_cache_hits_total" if req.prefix_hit_tokens > 0
                    else "llm_prefix_cache_misses_total")
            telemetry.get_counter(
                name, "paged prefills that hit/missed the prefix cache",
                tag_keys=("model",)).inc(1.0, tags=tags)
        if telemetry.enabled():
            telemetry.complete(
                "llm.prefill", "llm",
                req.created_wall_ns + (t_admit_perf - req.created_perf_ns),
                dur, request_id=req.id, prompt_tokens=len(req.prompt_ids),
                prefix_hit_tokens=max(req.prefix_hit_tokens, 0),
                prefill_tokens=self._prefill_tokens_of(req),
                cache_hit=req.prefix_hit_tokens > 0,
                trace_id=req.trace_id)

    def _record_kv_handoff(self, fetch) -> None:
        self._record_kv_handoff_raw(fetch.nbytes, fetch.dur_s or 0.0,
                                    fetch.t0_wall_ns, mode="paged",
                                    pages=fetch.n_pages, streams=fetch.streams)

    def _record_kv_handoff_raw(self, nbytes: int, dur_s: float,
                               t0_wall_ns: int, mode: str, pages: int = 1,
                               streams: int = 1) -> None:
        """P/D KV handoff signals: per-transfer GB/s histogram (surfaced in
        cluster_status()["llm"]) + an llm.kv_handoff span covering the
        transfer wall time."""
        try:
            if dur_s <= 0 or nbytes <= 0:
                return
            gbps = nbytes / dur_s / 1e9
            tags = dict(self._model_tag(), mode=mode)
            telemetry.get_histogram(
                "llm_kv_handoff_gbps",
                "P/D KV handoff throughput per transfer (GB/s)",
                tag_keys=("model", "mode"),
                boundaries=[0.1, 0.25, 0.5, 1, 2, 4, 8, 16, 32]).observe(
                gbps, tags=tags)
            if telemetry.enabled():
                telemetry.complete(
                    "llm.kv_handoff", "llm", t0_wall_ns, int(dur_s * 1e9),
                    bytes=nbytes, pages=pages, streams=streams, mode=mode,
                    gbps=round(gbps, 3))
        except Exception as e:
            _metrics_guard_warn("_record_kv_handoff", e)

    def _record_first_token(self, req: _Request) -> None:
        req.first_token_perf_ns = time.perf_counter_ns()
        try:
            ttft_s = (req.first_token_perf_ns - req.created_perf_ns) / 1e9
            telemetry.get_histogram(
                "llm_ttft_seconds", "engine time-to-first-token",
                tag_keys=("model",)).observe(ttft_s, tags=self._model_tag())
        except Exception as e:
            _metrics_guard_warn("_record_first_token", e)

    def _record_finish(self, req: _Request) -> None:
        if req.first_token_perf_ns == 0 or req.finish_recorded:
            return
        req.finish_recorded = True
        try:
            self._record_finish_inner(req)
        except Exception as e:
            _metrics_guard_warn("_record_finish", e)

    def _record_finish_inner(self, req: _Request) -> None:
        now = time.perf_counter_ns()
        decode_ns = now - req.first_token_perf_ns
        decode_s = decode_ns / 1e9
        # decode throughput = tokens AFTER the first / decode time: dividing
        # by the full lifetime would fold queue+prefill in and understate the
        # engine exactly when it is loaded. Single-token requests have no
        # decode phase to rate.
        rate = ((req.generated - 1) / decode_s
                if decode_s > 0 and req.generated > 1 else None)
        if rate is not None:
            telemetry.get_histogram(
                "llm_tokens_per_s", "per-request decode throughput",
                tag_keys=("model",),
                boundaries=[1, 5, 10, 25, 50, 100, 250, 500, 1000]).observe(
                rate, tags=self._model_tag())
        if telemetry.enabled():
            wall_first = req.created_wall_ns + (req.first_token_perf_ns
                                                - req.created_perf_ns)
            telemetry.complete(
                "llm.decode", "llm", wall_first, decode_ns,
                request_id=req.id, generated=req.generated,
                prompt_tokens=len(req.prompt_ids),
                prefix_hit_tokens=max(req.prefix_hit_tokens, 0),
                prefill_tokens=self._prefill_tokens_of(req),
                tokens_per_s=round(rate, 2) if rate is not None else 0.0,
                trace_id=req.trace_id)

    # -- scheduler loop ------------------------------------------------------------
    def _free_slots(self) -> List[int]:
        free = [s for s, r in self._active.items() if r is None]
        c = self.config
        if c.kv_layout == "paged" and c.data_parallel_size > 1 and free:
            # admit into the dp replica with the most free blocks first (one
            # full partition must not head-of-line-block admission to others)
            free.sort(key=lambda s: -self._blocks.num_free_for(s))
        return free

    def _admit(self) -> None:
        cfg, c = self.model_config, self.config
        # paged P/D requests whose pages are still streaming: skipped this
        # pass, re-queued on exit so they admit at a later burst boundary —
        # their transfer overlaps the active batch's decode bursts instead of
        # head-of-line-blocking admission
        deferred: List[_Request] = []
        try:
            self._admit_inner(cfg, c, deferred)
        finally:
            for r in deferred:
                self._waiting.put(r)

    def _admit_inner(self, cfg, c, deferred: List["_Request"]) -> None:
        for slot in self._free_slots():
            try:
                req = self._waiting.get_nowait()
            except queue.Empty:
                return
            with self._lock:
                was_aborted = req.id in self._aborted
                self._aborted.discard(req.id)
            if was_aborted:
                self.num_aborted += 1
                if req.kv_fetch is not None:
                    req.kv_fetch.cancel()
                    req.kv_fetch = None
                self._fail_request(req, len(req.prompt_ids), "abort")
                continue
            if req.kv_fetch is not None:
                err = req.kv_fetch.failed()
                if err is not None:
                    # mid-transfer failure (producer died, export retracted,
                    # deadline): typed finish — generate_from_prefill's stream
                    # re-raises it as DevicePlaneError for the router fallback
                    req.kv_fetch_error = err
                    req.kv_fetch = None
                    self._fail_request(req, len(req.prompt_ids), "kv_transfer")
                    continue
                if not req.kv_fetch.ready():
                    deferred.append(req)
                    continue
                fetch, req.kv_fetch = req.kv_fetch, None
                kv = fetch.result()
                req.prefill_kv = (kv["k"], kv["v"], req.kv_first_token)
                req.kv_staging = fetch
                self._record_kv_handoff(fetch)
            # visible to the loop's crash handler: this request is in neither
            # _waiting nor _active right now, and must still be failed on error
            self._admitting = req
            t_admit_perf = time.perf_counter_ns()
            if not req.queue_recorded:
                # queue span: creation to FIRST admission attempt, once — a
                # request requeued on pool exhaustion (or preempted) must not
                # emit a later, longer llm.queue span. Marked even when
                # telemetry is off, so mid-flight enabling can't fabricate
                # queue time that includes a previous admission's decode.
                req.queue_recorded = True
                if telemetry.enabled():
                    telemetry.complete(
                        "llm.queue", "llm", req.created_wall_ns,
                        t_admit_perf - req.created_perf_ns, request_id=req.id,
                        prompt_tokens=len(req.prompt_ids),
                        trace_id=req.trace_id)
            p = req.params
            if req.prefill_kv is not None:
                # P/D disaggregation: KV computed by a prefill replica; install it
                # and emit the first token the prefill side already sampled.
                k, v, tok = req.prefill_kv
                if c.kv_layout == "paged":
                    if not self._admit_paged_kv(req, slot, jnp.asarray(k), jnp.asarray(v)):
                        self._admitting = None
                        return  # pool full: req (prefill_kv intact) requeued
                elif k.shape[2] > c.max_model_len:
                    # transfer padded past this engine's slot width: fail just
                    # this request (install_kv would crash the whole loop)
                    self._fail_request(req, len(req.prompt_ids))
                    if req.kv_staging is not None:
                        req.kv_staging.recycle()
                        req.kv_staging = None
                    self._admitting = None
                    continue
                else:
                    self.state = model_runner.install_kv(
                        self.state, jnp.asarray(k), jnp.asarray(v),
                        jnp.int32(len(req.prompt_ids)), jnp.int32(slot),
                    )
                req.prefill_kv = None
                if req.kv_staging is not None:
                    # jnp.asarray copied the KV out of the staging buffer
                    # above; hand it back for the next handoff's fetch
                    req.kv_staging.recycle()
                    req.kv_staging = None
            elif c.kv_layout == "paged":
                tok = self._prefill_paged(req, slot)
                if tok is None:
                    self._admitting = None
                    return  # pool full: requeued, stop admitting
            elif c.prefill_chunk and len(req.prompt_ids) > c.prefill_chunk:
                # chunked prefill works for the slot layout too: bound peak
                # activation memory, then install the assembled KV at once
                k, v, last_logits = self._prefill_kv_tensors(req.prompt_ids)
                self.state = model_runner.install_kv(
                    self.state, k, v, jnp.int32(len(req.prompt_ids)), jnp.int32(slot))
                tok = self._sample_one(last_logits, p)
            else:
                tokens = self._pad_to_bucket(req.prompt_ids)
                self.state, last_logits = model_runner.prefill(
                    self.params, self.state, jnp.asarray(tokens),
                    jnp.int32(len(req.prompt_ids)), jnp.int32(slot), cfg,
                )
                tok = self._sample_one(last_logits, p)
            self._record_prefill(req, t_admit_perf)
            req.slot = slot
            req.admitted_at = next(self._admission_counter)
            self._active[slot] = req
            self._temp[slot], self._top_p[slot], self._top_k[slot] = (
                p.temperature, p.top_p, p.top_k)
            self._last_tokens[slot] = tok
            with self._lock:
                self.num_pending -= 1
                self.num_active += 1
            self._admitting = None
            if not req.first_emitted:
                self._emit(req, tok)

    def _sample_one(self, last_logits, p: SamplingParams) -> int:
        return int(model_runner.sample_tokens(
            self._next_rng(), last_logits[None, :],
            jnp.asarray([p.temperature], jnp.float32),
            jnp.asarray([p.top_p], jnp.float32),
            jnp.asarray([p.top_k], jnp.int32),
        )[0])

    # -- paged KV (reference: vLLM PagedAttention block tables) --------------------
    def _fail_request(self, req: _Request, n: int, reason: str = "length") -> None:
        req.out_queue.put(RequestOutput(
            request_id=req.id, token_ids=[], finished=True,
            finish_reason=reason, num_prompt_tokens=n,
            num_generated_tokens=req.generated))
        with self._lock:
            self.num_pending -= 1
            self._requests.pop(req.id, None)
            self._aborted.discard(req.id)

    def _install_paged(self, req: _Request, slot: int, k, v, n: int) -> Optional[bool]:
        """Allocate blocks for [L,1,S_pad,...] prefill KV and install it.
        True = installed; False = pool busy (req requeued by the CALLER);
        None = can never fit (request failed here)."""
        c = self.config
        s_pad = k.shape[2]
        needed = self._blocks.blocks_needed(max(n + 1, s_pad))
        if needed > self._blocks.max_fit(slot):
            # exceeds this slot's pool (its dp replica's partition) OR the
            # per-slot table width (e.g. a P/D transfer padded past the decode
            # engine's max_model_len): can never fit, so fail instead of
            # requeueing forever
            self._fail_request(req, n)
            return None
        if not self._blocks.can_allocate_for(slot, needed):
            return False
        block_ids = self._blocks.allocate(slot, needed)
        if s_pad < needed * c.kv_block_size:
            extra = needed * c.kv_block_size - s_pad
            k = jnp.pad(k, ((0, 0), (0, 0), (0, extra), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, 0), (0, extra), (0, 0), (0, 0)))
        self.state = self._pops.install_prefill(
            self.state, k, v, jnp.asarray(block_ids, jnp.int32), jnp.int32(n),
            jnp.int32(slot), n_blocks=needed)
        return True

    def _prefill_kv_tensors(self, prompt: List[int]):
        """(k, v, last_logits) for a prompt — whole-bucket or chunked prefill."""
        from . import paged

        cfg, c = self.model_config, self.config
        n = len(prompt)
        chunk = c.prefill_chunk
        if chunk and n > chunk:
            return paged.chunked_prefill(self.params, prompt, cfg, chunk)
        s_pad = next(b for b in c.buckets() if b >= n)
        tokens = np.zeros((1, s_pad), np.int32)
        tokens[0, :n] = prompt
        return model_runner.prefill_detached(
            self.params, jnp.asarray(tokens), jnp.int32(n), cfg)

    def _prefill_paged(self, req: _Request, slot: int) -> Optional[int]:
        """Prefill into allocated blocks; None = not admitted (requeued/failed).
        With prefix caching (reference: vLLM automatic prefix caching) a prompt
        sharing full leading blocks with an earlier one skips their
        recomputation: cached blocks join the slot's table by reference and the
        model runs only over the uncached suffix."""
        prompt = req.token_history if req.generated else req.prompt_ids
        n = len(prompt)
        chunk = self.config.prefill_chunk
        bs = self.config.kv_block_size
        cached_ids: List[int] = []
        min_hit = self._prefix_min_hit_tokens()
        max_hit = ((n - 1) // bs) * bs  # full blocks; last token always computed
        if self.config.enable_prefix_caching and max_hit >= bs:
            # pay-or-skip (the prefix_cache_ttft_speedup:0.95 fix): use the
            # cache only when the predicted compute saving — hit tokens x the
            # measured per-token prefill time — clears the measured dispatch
            # round trip. Through a network tunnel the round trip dwarfs the
            # prefill FLOPs a few cached blocks save, so hashing/refcounting
            # them is pure overhead; skip the whole machinery then.
            if max_hit < min_hit:
                self.num_prefix_skipped += 1
            else:
                cached_ids = self._blocks.match_prefix(slot, prompt)
                if cached_ids and len(cached_ids) * bs < min_hit:
                    self._blocks.release(slot)  # detach: hit too small to pay
                    cached_ids = []
                    self.num_prefix_skipped += 1
        # telemetry groundwork for the prefix-cache speedup mystery: record
        # what the cache SERVED vs what the model computed, per request
        req.prefix_hit_tokens = len(cached_ids) * bs
        req.prefill_tokens = n - req.prefix_hit_tokens
        if cached_ids:
            suffix_len = n - len(cached_ids) * bs
            if not chunk or suffix_len <= chunk:
                # cached context + one whole-bucket suffix prefill
                return self._prefill_with_prefix(req, slot, prompt, cached_ids)
            # suffix still too long for one pass: fall back to chunked prefill
            # (no context support there yet) but release the attached prefix
            self._blocks.release(slot)
            req.prefix_hit_tokens, req.prefill_tokens = 0, n  # cache unused
        chunked = bool(chunk and n > chunk)
        # cheap pre-check before running the model (the padded length is at most
        # one bucket/chunk above n, so needed here is exact)
        s_pad = (-(-n // chunk) * chunk if chunked
                 else next(b for b in self.config.buckets() if b >= n))
        needed = self._blocks.blocks_needed(max(n + 1, s_pad))
        if needed > self._blocks.max_fit(slot):
            self._fail_request(req, n)
            return None
        if not self._blocks.can_allocate_for(slot, needed):
            self._waiting.put(req)  # stays pending; retried next cycle
            return None
        k, v, last_logits = self._prefill_kv_tensors(prompt)
        ok = self._install_paged(req, slot, k, v, n)
        if ok is not True:
            if ok is False:
                self._waiting.put(req)
            return None
        # publish this prompt's full blocks for future prefix hits (chunked
        # long prompts seed the cache for their shorter siblings too) — unless
        # the pay-or-skip gate says a hit of this size could never pay, in
        # which case hashing the blocks is wasted host work: a longer future
        # prompt can share at most max_hit tokens with this one
        if max_hit >= min_hit:
            self._blocks.register_blocks(slot, prompt,
                                         self._blocks.owned_for(slot),
                                         skip_blocks=0)
        return self._sample_one(last_logits, req.params)

    def _prefix_min_hit_tokens(self) -> int:
        """Cached-token floor below which a prefix hit is skipped. Fixed by
        RAY_TPU_LLM_PREFIX_MIN_HIT_TOKENS when set; otherwise auto — the hit
        must save at least one dispatch round trip's worth of prefill compute
        (hit_tokens * per_token_prefill >= host_rt). Unmeasured timings keep
        the cache on (the safe direction while EWMAs settle)."""
        from ray_tpu.config import CONFIG as _CFG

        fixed = _CFG.llm_prefix_min_hit_tokens
        if fixed > 0:
            return fixed
        rt, per_tok = self._host_rt_s, self._prefill_per_tok_s
        if rt <= 0 or per_tok <= 0:
            return 0
        return int(rt / per_tok)

    def _prefill_with_prefix(self, req: _Request, slot: int, prompt: List[int],
                             cached_ids: List[int]) -> Optional[int]:
        cfg, c = self.model_config, self.config
        n = len(prompt)
        cached_tokens = len(cached_ids) * c.kv_block_size
        suffix = prompt[cached_tokens:]
        s_pad = next(b for b in c.buckets() if b >= len(suffix))
        needed_new = self._blocks.blocks_needed(
            max(n + 1 - cached_tokens, s_pad))
        total_blocks = len(cached_ids) + needed_new
        if total_blocks > self._blocks.max_fit(slot):
            self._blocks.release(slot)  # undo the attached prefix refs
            self._fail_request(req, n)
            return None
        if not self._blocks.can_allocate_for(slot, needed_new):
            self._blocks.release(slot)
            self._waiting.put(req)
            return None
        tokens = np.zeros((1, s_pad), np.int32)
        tokens[0, : len(suffix)] = suffix
        # fused gather+suffix: ONE device dispatch (the split version paid an
        # extra host->device round trip per warm request — more than the
        # prefill compute the cache saves, through a network tunnel)
        k_suf, v_suf, last_logits = self._pops.prefill_suffix_from_state(
            self.params, self.state, jnp.asarray(cached_ids, jnp.int32),
            jnp.asarray(tokens), jnp.int32(len(suffix)),
            n_blocks=len(cached_ids), slot=slot)
        new_ids = self._blocks.allocate(slot, needed_new)
        pad_blocks = s_pad // c.kv_block_size
        if pad_blocks < needed_new:
            extra = (needed_new - pad_blocks) * c.kv_block_size
            k_suf = jnp.pad(k_suf, ((0, 0), (0, 0), (0, extra), (0, 0), (0, 0)))
            v_suf = jnp.pad(v_suf, ((0, 0), (0, 0), (0, extra), (0, 0), (0, 0)))
        row = np.zeros((self._blocks.max_blocks,), np.int32)
        row[: total_blocks] = cached_ids + new_ids
        self.state = self._pops.install_with_prefix(
            self.state, k_suf, v_suf, jnp.asarray(new_ids, jnp.int32),
            jnp.asarray(row), jnp.int32(n), jnp.int32(slot), n_new=needed_new)
        self._blocks.register_blocks(slot, prompt, cached_ids + new_ids,
                                     skip_blocks=len(cached_ids))
        self._blocks.add_hit_tokens(slot, cached_tokens)  # counted only on success
        return self._sample_one(last_logits, req.params)

    def _admit_paged_kv(self, req: _Request, slot: int, k, v) -> bool:
        """Install P/D-transferred KV into blocks; False = not admitted."""
        ok = self._install_paged(req, slot, k, v, len(req.prompt_ids))
        if ok is False:
            self._waiting.put(req)  # prefill_kv still set; stays pending
        return ok is True

    def _grow_or_preempt(self, headroom: int = 1, steps=None) -> None:
        """Before a decode step: every active slot whose next write crosses into
        an unallocated block gets one; when the pool is dry, preempt the
        YOUNGEST request in the SAME pool partition (recompute preemption:
        blocks freed, request re-queued and later re-prefilled from its token
        history; with dp>1 only the slot's own replica pool can relieve it).
        headroom > 1 reserves room for a fused K-step burst, whose block
        tables are frozen; `steps` (the per-slot burst budget from
        _burst_plan) caps each slot's reservation at the steps it will
        actually take, so a near-finished request doesn't grab K blocks."""
        for slot in list(self._active):
            req = self._active[slot]
            if req is None:
                continue
            # host mirror of state.lengths (== prompt + generated - 1, the next
            # write position): saves a device fetch per decode step
            next_write = len(req.prompt_ids) + req.generated - 1
            # graftlint: allow[host-sync-in-hot-path] steps is the host-side burst plan (numpy), not a device array
            slot_headroom = (min(headroom, int(steps[slot]))
                             if steps is not None else headroom)
            # re-check liveness each round: an earlier iteration (or this one)
            # may have preempted this very request — growing a preempted slot
            # would leak blocks into it and corrupt a later occupant's table
            # clamp at the table width: demanding capacity past max_model_len
            # would leak blocks (append index off the table) or preempt
            # innocents forever once the slot is already at full width
            target = min(next_write + slot_headroom, self.config.max_model_len)
            while (self._active[slot] is req
                   and target - 1 >= self._blocks.slot_capacity(slot)):
                if self._blocks.num_free_for(slot) > 0:
                    (bid,) = self._blocks.allocate(slot, 1)
                    index = self._blocks.slot_capacity(slot) // self.config.kv_block_size - 1
                    self.state = self._pops.append_block(
                        self.state, jnp.int32(slot), jnp.int32(index), jnp.int32(bid))
                    continue
                victim = max(
                    (r for r in self._active.values()
                     if r is not None and self._blocks.same_pool(r.slot, slot)),
                    key=lambda r: r.admitted_at)
                self._preempt(victim)
                if victim is req:
                    break  # this slot's request was the victim; nothing to grow

    def _preempt(self, req: _Request) -> None:
        self.num_preemptions += 1
        slot = req.slot
        self._blocks.release(slot)
        self._active[slot] = None
        req.slot = -1
        with self._lock:
            self.num_active -= 1
            self.num_pending += 1
        self._waiting.put(req)

    def _emit(self, req: _Request, tok: int) -> None:
        req.generated += 1
        req.token_history.append(tok)
        if req.first_token_perf_ns == 0:
            self._record_first_token(req)
        with self._lock:
            # _emit_prefill_first_token bumps this from the request thread
            self.total_generated += 1
        stops = req.params.stop_token_ids or [self.tokenizer.eos_token_id]
        finished, reason = False, None
        if tok in stops:
            finished, reason = True, "stop"
        elif req.generated >= req.params.max_tokens:
            finished, reason = True, "length"
        emit_ids = [] if reason == "stop" else [tok]
        text = self.tokenizer.decode(emit_ids) if emit_ids else ""
        req.out_queue.put(RequestOutput(
            request_id=req.id, token_ids=emit_ids, text=text, finished=finished,
            finish_reason=reason, num_prompt_tokens=len(req.prompt_ids),
            num_generated_tokens=req.generated,
        ))
        if finished:
            self._release(req)

    def _release(self, req: _Request) -> None:
        self._record_finish(req)
        if req.slot >= 0:
            if self.config.kv_layout == "paged":
                self._blocks.release(req.slot)
            self._active[req.slot] = None
            req.slot = -1
            with self._lock:
                self.num_active -= 1
                self._requests.pop(req.id, None)
                self._aborted.discard(req.id)

    def _propose_ngram(self, req: "_Request", k: int) -> List[int]:
        """Prompt-lookup drafts (reference vLLM ngram speculator): find the
        most recent earlier occurrence of the trailing n-gram (longest n
        first) and propose the tokens that followed it."""
        ctx = req.token_history  # prompt + every generated token
        if len(ctx) < 2:
            return []
        # graftlint: allow[host-sync-in-hot-path] ngram proposal runs on the host token history (python lists)
        arr = np.asarray(ctx, dtype=np.int32)
        total = len(arr)
        for n in range(min(self.config.ngram_prompt_lookup_max, total - 1), 0, -1):
            tail = arr[-n:]
            # vectorized shifted-equality scan (O(n*len) numpy, not Python
            # slicing per position — at 32k context this must not outweigh
            # the verify step itself); exclude the tail's own occurrence
            m = np.ones(total - n, dtype=bool)
            for j in range(n):
                m &= arr[j:total - n + j] == tail[j]
            hits = np.flatnonzero(m)
            if hits.size:
                # graftlint: allow[host-sync-in-hot-path] hits is a host numpy array from np.where
                start = int(hits[-1])
                cont = ctx[start + n:start + n + k]
                if cont:
                    return cont
        return []

    def _spec_burst_width(self) -> int:
        """Fused-spec burst cap: each window may emit up to k+1 tokens, so the
        per-slot room/budget math divides by the window length. Spec windows
        accept a variable token count, so (unlike plain fused decode) the
        burst width stays capped by the tightest slot."""
        c = self.config
        m = self.decode_steps_target()
        if m == 1:
            return 1
        wlen = c.num_speculative_tokens + 1
        for req in self._active.values():
            if req is None:
                continue
            next_write = len(req.prompt_ids) + req.generated - 1
            kv_room = (c.max_model_len - 1) - next_write
            budget = req.params.max_tokens - req.generated
            m = min(m, max(1, min(kv_room, budget) // wlen))
        return _pow2_floor(m)

    @hot_path
    def _step_decode_spec_fused(self, m: int) -> None:
        """m speculative windows fused per host sync (spec + multi-step
        composed): the n-gram proposal runs ON DEVICE against a per-slot
        history buffer, so successive windows chain without host round trips
        (model_runner.spec_multi)."""
        cfg = self.model_config
        c = self.config
        k = c.num_speculative_tokens
        n = c.max_num_seqs
        active_mask = np.array([r is not None for r in self._active.values()], bool)
        if not active_mask.any():
            return
        # history width bucketed to a power of two: bounds both the H2D upload
        # (not max_model_len when contexts are short) and the spec_multi trace
        # count (one program per width bucket)
        max_ctx = max(len(r.token_history) for r in self._active.values()
                      if r is not None)
        width = min(c.max_model_len,
                    1 << (max_ctx + m * (k + 1) - 1).bit_length())
        hist = np.zeros((n, width), np.int32)
        hlen = np.zeros((n,), np.int32)
        for slot, req in self._active.items():
            if req is None:
                continue
            ctx = req.token_history
            hist[slot, :len(ctx)] = ctx
            hlen[slot] = len(ctx)
        rngs = jnp.stack([self._next_rng() for _ in range(m)])
        t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
        if c.kv_layout == "paged":
            self.state, toks_m, acc_m, drafted_m = self._pops.spec_multi(
                self.params, self.state, jnp.asarray(hist), jnp.asarray(hlen),
                jnp.asarray(active_mask), rngs,
                jnp.asarray(self._temp), jnp.asarray(self._top_p),
                jnp.asarray(self._top_k), m, k, c.ngram_prompt_lookup_max)
        else:
            self.state, toks_m, acc_m, drafted_m = model_runner.spec_multi(
                self.params, self.state, jnp.asarray(hist), jnp.asarray(hlen),
                jnp.asarray(active_mask), cfg, rngs,
                jnp.asarray(self._temp), jnp.asarray(self._top_p),
                jnp.asarray(self._top_k), m, k, c.ngram_prompt_lookup_max)
        # graftlint: allow[host-sync-in-hot-path] the ONE designed fetch per fused spec window (PR 12 contract)
        toks_m, acc_m, drafted_m = jax.device_get((toks_m, acc_m, drafted_m))
        dur_ns = time.perf_counter_ns() - t0_perf
        # keep the auto-K probe live in fused-spec mode too (per-WINDOW cost,
        # the unit decode_steps_target counts here): without this the EWMA
        # would freeze at whatever the single-window phase measured
        self._note_burst_device_wall(m, dur_ns / 1e9)
        before = self.total_generated
        burst_reqs = {s: r for s, r in self._active.items() if r is not None}
        for step in range(m):
            for slot, req in burst_reqs.items():
                self._emit_spec_window(
                    # graftlint: allow[host-sync-in-hot-path] acc_m/toks_m already fetched by this window's device_get
                    slot, req, toks_m[step, slot], int(acc_m[step, slot]),
                    # graftlint: allow[host-sync-in-hot-path] drafted_m already fetched by this window's device_get
                    int(drafted_m[step, slot]))
        self._record_burst(m, self.total_generated - before,
                           int(active_mask.sum()), t0_wall, dur_ns)

    def _emit_spec_window(self, slot: int, req: "_Request", toks_row,
                          acc: int, drafted: int) -> None:
        """Emit one verify window's accepted prefix + bonus token for a slot
        (shared by the per-window and fused spec paths): counts acceptance,
        discards tokens past a mid-burst finish, force-finishes at the KV cap."""
        if self._active.get(slot) is not req:
            return  # finished (or aborted) earlier in this burst: discard tail
        if self._aborted and self._finish_abort(req):
            return  # cancelled mid-burst: tail discarded, blocks freed now
        c = self.config
        self.num_spec_drafted += drafted
        self.num_spec_accepted += min(acc, drafted)
        for t in range(acc + 1):
            if self._active.get(slot) is not req:
                break
            # graftlint: allow[host-sync-in-hot-path] toks_row is the already-fetched numpy burst row
            tok = int(toks_row[t])
            self._last_tokens[slot] = tok
            self._emit(req, tok)
            r2 = self._active.get(slot)
            if r2 is not None and (len(r2.prompt_ids) + r2.generated - 1
                                   >= c.max_model_len - 1):
                r2.out_queue.put(RequestOutput(
                    request_id=r2.id, token_ids=[], finished=True,
                    finish_reason="length",
                    num_prompt_tokens=len(r2.prompt_ids),
                    num_generated_tokens=r2.generated,
                ))
                self._release(r2)

    @hot_path
    def _step_decode_spec(self) -> None:
        """Speculative decode step: host proposes drafts by n-gram lookup, ONE
        verify forward scores the whole window, accepted prefix + bonus token
        all emit this step (greedy slots only; others ride along with k=0)."""
        cfg = self.model_config
        c = self.config
        if self.decode_steps_target() > 1:
            # pp engines never reach here with >1 (start() downgrades the
            # target): pp keeps per-step scheduling (microbatch ticks)
            m = self._spec_burst_width()
            if m > 1 and c.kv_layout == "paged":
                # every window position of the burst must land in an owned block
                self._grow_or_preempt(headroom=m * (c.num_speculative_tokens + 1))
                m = min(m, self._spec_burst_width())  # preemption changed the set
            if m > 1:
                self._step_decode_spec_fused(m)
                return
        k = c.num_speculative_tokens
        wlen = k + 1
        if c.kv_layout == "paged":
            # every window position must land in an owned block
            self._grow_or_preempt(headroom=wlen)
        n = c.max_num_seqs
        window = np.zeros((n, wlen), np.int32)
        draft_len = np.zeros((n,), np.int32)
        active_mask = np.zeros((n,), bool)
        for slot, req in self._active.items():
            if req is None:
                continue
            active_mask[slot] = True
            window[slot, 0] = self._last_tokens[slot]
            if self._temp[slot] > 0:
                continue  # greedy-accept is exact only at temperature 0
            next_write = len(req.prompt_ids) + req.generated - 1
            room = (c.max_model_len - 1) - next_write - 1
            budget = req.params.max_tokens - req.generated - 1
            cap = max(0, min(k, room, budget))
            drafts = self._propose_ngram(req, cap) if cap else []
            draft_len[slot] = len(drafts)
            if drafts:
                window[slot, 1:1 + len(drafts)] = drafts
        if not active_mask.any():
            return  # pool-exhaustion preemption may have drained every slot
        t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
        if c.kv_layout == "paged":
            self.state, out_toks, n_acc = self._pops.spec_verify(
                self.params, self.state, jnp.asarray(window),
                jnp.asarray(draft_len), jnp.asarray(active_mask),
                self._next_rng(), jnp.asarray(self._temp),
                jnp.asarray(self._top_p), jnp.asarray(self._top_k))
        elif c.pipeline_parallel_size > 1:
            self.state, out_toks, n_acc = self._spec_pp_jit(
                self.params, self.state, jnp.asarray(window),
                jnp.asarray(draft_len), jnp.asarray(active_mask),
                self._next_rng(), jnp.asarray(self._temp),
                jnp.asarray(self._top_p), jnp.asarray(self._top_k))
        else:
            self.state, out_toks, n_acc = model_runner.spec_verify_step(
                self.params, self.state, jnp.asarray(window),
                jnp.asarray(draft_len), jnp.asarray(active_mask), cfg,
                self._next_rng(), jnp.asarray(self._temp),
                jnp.asarray(self._top_p), jnp.asarray(self._top_k))
        # graftlint: allow[host-sync-in-hot-path] the ONE designed fetch per spec-decode step
        out_toks, n_acc = jax.device_get((out_toks, n_acc))
        dur_ns = time.perf_counter_ns() - t0_perf
        # the verify forward is close enough to a decode step to feed the
        # auto-K probe: once the EWMA settles, single-window spec engines in
        # auto mode graduate to fused multi-window bursts
        self._note_burst_device_wall(1, dur_ns / 1e9)
        before = self.total_generated
        burst_reqs = {s: r for s, r in self._active.items() if r is not None}
        for slot, req in burst_reqs.items():
            self._emit_spec_window(slot, req, out_toks[slot],
                                   # graftlint: allow[host-sync-in-hot-path] n_acc/draft_len already fetched by this step's device_get
                                   int(n_acc[slot]), int(draft_len[slot]))
        self._record_burst(1, self.total_generated - before,
                           # graftlint: allow[host-sync-in-hot-path] active_mask is a host-side bool array
                           int(np.asarray(active_mask).sum()), t0_wall, dur_ns)

    @hot_path
    def _step_decode(self) -> None:
        cfg = self.model_config
        if self.config.num_speculative_tokens:
            self._step_decode_spec()
            return
        k_steps, steps = self._burst_plan()
        if self.config.kv_layout == "paged":
            self._grow_or_preempt(headroom=k_steps, steps=steps)
            k_steps, steps = self._burst_plan()  # preemption changed the set
        active_mask = np.array([r is not None for r in self._active.values()], bool)
        if not active_mask.any():
            return  # preemption may have drained every slot this cycle
        t0_wall, t0_perf = time.time_ns(), time.perf_counter_ns()
        if k_steps > 1:
            # fused burst: K decode+sample iterations, ONE host sync (vLLM
            # multi-step scheduling; decisive over a network tunnel). The
            # per-slot steps budget rides along, so a request one token from
            # its max_tokens no longer caps the whole batch at K=1 — it stops
            # advancing on device and retires at the burst boundary while the
            # rest of the batch runs full-width.
            rngs = jnp.stack([self._next_rng() for _ in range(k_steps)])
            steps_dev = jnp.asarray(steps)
            if self.config.kv_layout == "paged":
                self.state, toks_k = self._pops.decode_multi(
                    self.params, self.state, jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask), rngs,
                    jnp.asarray(self._temp), jnp.asarray(self._top_p),
                    jnp.asarray(self._top_k), steps_dev)
            else:
                self.state, toks_k = model_runner.decode_multi(
                    self.params, self.state, jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask), cfg, rngs,
                    jnp.asarray(self._temp), jnp.asarray(self._top_p),
                    jnp.asarray(self._top_k), steps_dev)
            # graftlint: allow[host-sync-in-hot-path] the ONE designed host sync per K-step fused burst (PR 12)
            toks_burst = np.asarray(toks_k)  # [K, slots] — the only fetch
        else:
            if self.config.kv_layout == "paged":
                self.state, logits = self._pops.decode_step(
                    self.params, self.state, jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask),
                )
            elif self.config.pipeline_parallel_size > 1:
                self.state, logits = self._decode_pp_jit(
                    self.params, self.state, jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask),
                )
            else:
                self.state, logits = model_runner.decode_step(
                    self.params, self.state, jnp.asarray(self._last_tokens),
                    jnp.asarray(active_mask), cfg,
                )
            # graftlint: allow[host-sync-in-hot-path] the designed per-step token fetch on the K=1 path
            toks_burst = np.asarray(model_runner.sample_tokens(
                self._next_rng(), logits, jnp.asarray(self._temp),
                jnp.asarray(self._top_p), jnp.asarray(self._top_k)))[None, :]
        dur_ns = time.perf_counter_ns() - t0_perf
        self._note_burst_device_wall(k_steps, dur_ns / 1e9)
        burst_reqs = {slot: req for slot, req in self._active.items() if req is not None}
        emitted = 0
        for t in range(toks_burst.shape[0]):
            for slot, req in burst_reqs.items():
                if t >= steps[slot]:
                    continue  # this slot's own budget ended before the burst
                if self._active.get(slot) is not req:
                    continue  # finished (or aborted) earlier in this burst
                if self._aborted and self._finish_abort(req):
                    continue  # cancelled mid-burst: tail discarded, blocks freed
                # graftlint: allow[host-sync-in-hot-path] toks_burst is the already-fetched numpy burst
                tok = int(toks_burst[t, slot])
                self._last_tokens[slot] = tok
                self._emit(req, tok)
                emitted += 1
                r2 = self._active[slot]
                # host mirror of state.lengths: the last sampled token is not yet
                # written to KV, so device lengths == prompt + generated - 1.
                # Mirroring avoids a SECOND device round trip per decode step
                # (pure overhead; brutal through a network tunnel).
                if r2 is not None and (len(r2.prompt_ids) + r2.generated - 1
                                       >= self.config.max_model_len - 1):
                    r2.out_queue.put(RequestOutput(
                        request_id=r2.id, token_ids=[], finished=True,
                        finish_reason="length",
                        num_prompt_tokens=len(r2.prompt_ids),
                        num_generated_tokens=r2.generated,
                    ))
                    self._release(r2)
        self._record_burst(k_steps, emitted, int(active_mask.sum()),
                           t0_wall, dur_ns)

    def _record_burst(self, k: int, emitted: int, n_slots: int,
                      t0_wall_ns: int, dur_ns: int) -> None:
        """Per-burst decode telemetry: ONE span/observation per K-step burst
        (tagged with K and tokens emitted) instead of per host step, so the
        cross-worker timeline and the windowed quantiles stay truthful under
        fused mode. Guarded like _export_metrics — metrics must never take
        the engine down."""
        try:
            tags = self._model_tag()
            if emitted:
                telemetry.get_counter(
                    "llm_generated_tokens_total",
                    "tokens emitted by the engine (all requests)",
                    # graftlint: allow[host-sync-in-hot-path] emitted is a python int; metric emission is host-side
                    tag_keys=("model",)).inc(float(emitted), tags=tags)
                if dur_ns > 0:
                    telemetry.get_histogram(
                        "llm_burst_tokens_per_s",
                        "engine decode throughput per fused burst",
                        tag_keys=("model",),
                        boundaries=[10, 50, 100, 250, 500, 1000, 2500, 5000,
                                    10000, 25000]).observe(
                        emitted / (dur_ns / 1e9), tags=tags)
            if telemetry.enabled():
                telemetry.complete(
                    "llm.decode_burst", "llm", t0_wall_ns, dur_ns, k=k,
                    tokens=emitted, slots=n_slots,
                    model=str(self.config.model_id))
        except Exception as e:
            _metrics_guard_warn("_record_burst", e)

    @hot_path
    def _loop(self) -> None:
        import time as _time

        next_metrics_push = 0.0
        while not self._shutdown:
            try:
                # liveness heartbeat for LLMServer.check_health: a wedged
                # device call shows up as a stale tick while requests wait
                self._last_tick_monotonic = _time.monotonic()
                self._admit()
                self._process_aborts()
                # periodic gauge refresh: /metrics must serve current llm_*
                # values even when nothing polls engine.metrics() (ADVICE r3)
                now = _time.monotonic()
                if now >= next_metrics_push:
                    next_metrics_push = now + 5.0
                    self.metrics()
                if any(r is not None for r in self._active.values()):
                    self._step_decode()
                else:
                    from ray_tpu.config import CONFIG as _CFG

                    self._wakeup.wait(timeout=_CFG.llm_engine_idle_wait_s)
                    self._wakeup.clear()
            except Exception:
                import traceback

                traceback.print_exc()
                # fail all in-flight requests rather than hanging clients —
                # including one caught mid-admission (in neither _waiting nor
                # _active), whose client would otherwise block forever
                if self._admitting is not None:
                    self._admitting.out_queue.put(RequestOutput(
                        request_id=self._admitting.id, token_ids=[], finished=True,
                        finish_reason="error"))
                    with self._lock:
                        self.num_pending -= 1  # it left _waiting but never admitted
                        self._requests.pop(self._admitting.id, None)
                        self._aborted.discard(self._admitting.id)
                    self._admitting = None
                for slot, req in list(self._active.items()):
                    if req is not None:
                        req.out_queue.put(RequestOutput(
                            request_id=req.id, token_ids=[], finished=True,
                            finish_reason="error"))
                        self._release(req)
                while True:
                    try:
                        req = self._waiting.get_nowait()
                    except queue.Empty:
                        break
                    self._fail_request(req, len(req.prompt_ids), "error")
                time.sleep(0.1)


_INIT_CACHE: Dict[str, Any] = {}
_PROM_GAUGES: Dict[str, Any] = {}  # engine metric name -> shared Gauge


def llama_init_cached(cfg):
    """Random-init params once per config (tests/demo path; real use loads a checkpoint)."""
    from ray_tpu.models import llama

    key = cfg.name
    if key not in _INIT_CACHE:
        _INIT_CACHE[key] = llama.init(jax.random.PRNGKey(0), cfg)
    return _INIT_CACHE[key]
