"""LLMServer Serve deployment + OpenAI-compatible router.

Capability parity: reference python/ray/llm/_internal/serve/deployments/llm/
llm_server.py:409 (``LLMServer`` — Serve deployment wrapping an engine, OpenAI
chat/completions) and serve/routers/ + builders/ (``build_openai_app`` multi-model
ingress). The engine here is ``JaxLLMEngine`` (TP over the replica's device mesh)
instead of vLLM.
"""
from __future__ import annotations

import dataclasses
import logging
import time
import uuid
from typing import Any, Dict, List, Optional

from .config import LLMConfig, SamplingParams
from .engine import JaxLLMEngine

_LOGGER = logging.getLogger(__name__)


def _sampling_from_body(body: Dict[str, Any]) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(body.get("max_tokens", 64)),
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0)),
        seed=body.get("seed"),
    )


def render_chat_template(messages: List[Dict[str, str]]) -> str:
    """Minimal chat template (reference: HF chat templates via vLLM's tokenizer)."""
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages]
    return "\n".join(parts) + "\nassistant:"


def _usage(prompt_tokens: int, completion_tokens: int) -> Dict[str, int]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def _chat_envelope(model: str, text: str, finish_reason, usage) -> Dict[str, Any]:
    return {
        "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish_reason,
        }],
        "usage": usage,
    }


def _completion_envelope(model: str, text: str, finish_reason, usage) -> Dict[str, Any]:
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0, "text": text, "finish_reason": finish_reason}],
        "usage": usage,
    }


def _models_list(model_ids) -> Dict[str, Any]:
    return {
        "object": "list",
        "data": [{"id": m, "object": "model", "owned_by": "ray_tpu"}
                 for m in sorted(model_ids)],
    }


class LLMServer:
    """Serve deployment hosting one model's engine.

    Deploy via ``build_openai_app`` or directly:
        app = serve.deployment(LLMServer).bind(llm_config)
    """

    def __init__(self, llm_config: LLMConfig, engine: Optional[JaxLLMEngine] = None,
                 prefill_handle=None):
        self.llm_config = llm_config
        self.engine = engine or JaxLLMEngine(llm_config)
        # decode-pool replicas get a handle to the prefill pool so a
        # device-plane failure mid-stream can re-prefill over the host path
        # WITHOUT unwinding through the router (build_pd_openai_app wires it)
        self.prefill_handle = prefill_handle
        self.engine.start()

    # -- OpenAI endpoints --------------------------------------------------------
    def chat(self, body: Dict[str, Any]):
        prompt = render_chat_template(body.get("messages", []))
        if body.get("stream"):
            return self._sse_stream(prompt, body, chat=True)
        out = self.engine.generate_sync(prompt, _sampling_from_body(body))
        return _chat_envelope(
            body.get("model", self.llm_config.model_id), out.text, out.finish_reason,
            _usage(out.num_prompt_tokens, out.num_generated_tokens))

    def completions(self, body: Dict[str, Any]):
        if body.get("stream"):
            return self._sse_stream(body.get("prompt", ""), body, chat=False)
        out = self.engine.generate_sync(body.get("prompt", ""), _sampling_from_body(body))
        return _completion_envelope(
            body.get("model", self.llm_config.model_id), out.text, out.finish_reason,
            _usage(out.num_prompt_tokens, out.num_generated_tokens))

    def _sse_stream(self, prompt: str, body: Dict[str, Any], chat: bool):
        """OpenAI ``stream: true``: yield SSE frames ("data: {chunk}\\n\\n" ...
        "data: [DONE]\\n\\n") as the engine produces tokens. Runs as a streaming
        actor method through Serve (reference proxy.py:699 ASGI streaming)."""
        return self._sse_frames(
            lambda rid: self.engine.generate(
                prompt, _sampling_from_body(body), request_id=rid),
            body, chat)

    def decode_stream(self, prefill_result, body: Dict[str, Any],
                      chat: bool):
        """Streaming decode side of P/D disaggregation: continue from a
        transferred prefill and yield SSE frames (reference
        prefill_decode_disagg + ASGI streaming).

        Failure handling lives HERE, not in the router: the router hands this
        stream straight to the HTTP proxy (StreamHandoff) before the first
        frame, so nobody upstream can splice in a replacement. A device-plane
        failure — the prefill result itself, or the KV pull failing mid-page
        -stream — re-prefills over the host path through ``prefill_handle``
        and resumes the SAME SSE stream: tokens the first attempt already
        yielded are skipped by count, which replays exactly under
        deterministic decoding (greedy or seeded), the caveat the router's
        unary fallback shares."""
        sampling = _sampling_from_body(body)
        pre_err: Optional[BaseException] = None
        pre: Optional[Dict[str, Any]] = None
        try:
            pre = _materialize_prefill(prefill_result)
        except Exception as e:
            if self.prefill_handle is None or not _is_device_plane_error(e):
                raise
            pre_err = e

        def _host_re_prefill():
            if pre is not None:
                _release_orphan_export(pre)
            prompt = (render_chat_template(body.get("messages", []))
                      if chat else body.get("prompt", ""))
            fb_body = dict(body)
            fb_body["_kv_host_fallback"] = True
            return self.prefill_handle.options(method_name="prefill").remote(
                prompt, fb_body).result()

        def start_gen(rid):
            yielded = 0
            try:
                if pre_err is not None:
                    raise pre_err
                for out in self.engine.generate_from_prefill(
                        pre, sampling, request_id=rid):
                    yielded += len(out.token_ids)
                    yield out
                return
            except GeneratorExit:
                raise
            except Exception as e:
                if self.prefill_handle is None or not _is_device_plane_error(e):
                    raise
                _LOGGER.warning(
                    "device-plane KV handoff failed mid-stream for key %s "
                    "(%r); resuming over the host path",
                    (pre or {}).get("kv_key"), e)
            pre_fb = _host_re_prefill()
            skip = yielded
            fb_rid = uuid.uuid4().hex
            try:
                for out in self.engine.generate_from_prefill(
                        pre_fb, sampling, request_id=fb_rid):
                    ids = out.token_ids
                    if skip:
                        k = min(skip, len(ids))
                        skip -= k
                        ids = ids[k:]
                        if not ids and not out.finish_reason:
                            continue
                        out = dataclasses.replace(out, token_ids=ids)
                    yield out
            except GeneratorExit:
                self.engine.abort(fb_rid)
                raise

        return self._sse_frames(
            start_gen, body, chat,
            presynth=(pre or {}).get("first_text") or "")

    def _sse_frames(self, start_gen, body: Dict[str, Any], chat: bool,
                    presynth: str = ""):
        import json as _json

        model = body.get("model", self.llm_config.model_id)
        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        created = int(time.time())

        def frame(payload: Dict[str, Any]) -> str:
            return f"data: {_json.dumps(payload)}\n\n"

        def choices(delta_or_text, finish_reason):
            if chat:
                return [{"index": 0, "delta": delta_or_text,
                         "finish_reason": finish_reason}]
            return [{"index": 0, "text": delta_or_text,
                     "finish_reason": finish_reason}]

        obj = "chat.completion.chunk" if chat else "text_completion"

        tokenizer = self.engine.tokenizer

        def gen():
            if chat:
                yield frame({"id": rid, "object": obj, "created": created,
                             "model": model,
                             "choices": choices({"role": "assistant"}, None)})
            finish = None
            # deltas come from re-decoding the FULL id sequence: per-chunk
            # decode drops BPE leading-space markers and splits multi-byte
            # UTF-8, diverging from the non-streaming response text
            all_ids: List[int] = []
            emitted = ""

            def delta_frame(delta_text):
                delta = {"content": delta_text} if chat else delta_text
                return frame({"id": rid, "object": obj, "created": created,
                              "model": model, "choices": choices(delta, None)})

            if presynth:
                # P/D: prefill already sampled AND rendered the first token
                # (prefill_only's ``first_text``), so emit it before engine
                # admission — the first content frame doesn't wait for the KV
                # pull to start. The engine replays the same token id, whose
                # re-decode lands inside ``emitted`` and yields no frame.
                yield delta_frame(presynth)
                emitted = presynth
            eng_rid = uuid.uuid4().hex
            try:
                for out in start_gen(eng_rid):
                    finish = out.finish_reason
                    all_ids.extend(out.token_ids)
                    full = tokenizer.decode(all_ids)
                    if full.endswith("�"):
                        continue  # mid-codepoint: wait for the next chunk
                    delta_text = full[len(emitted):]
                    emitted = full
                    if delta_text:
                        yield delta_frame(delta_text)
            except GeneratorExit:
                # consumer abandoned the stream (client disconnect): stop the
                # engine request so its KV slot/blocks free now, not at max_tokens
                self.engine.abort(eng_rid)
                raise
            # flush a tail withheld by the mid-codepoint guard (generation can
            # legitimately stop mid-sequence at max_tokens): match generate_sync
            tail = tokenizer.decode(all_ids)[len(emitted):]
            if tail:
                yield delta_frame(tail)
            yield frame({"id": rid, "object": obj, "created": created,
                         "model": model,
                         "choices": choices({} if chat else "", finish or "stop")})
            yield "data: [DONE]\n\n"

        return gen()

    # -- P/D disaggregation endpoints (reference prefill_decode_disagg/) ---------
    def prefill(self, prompt: str, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.engine.prefill_only(
            prompt, _sampling_from_body(body),
            force_host=bool(body.get("_kv_host_fallback")))

    def release_prefill(self, kv_key: str) -> None:
        """Ack from the router after decode pulled the device-resident KV."""
        self.engine.release_prefill_export(kv_key)

    def decode_from_prefill(self, prefill_result,
                            body: Dict[str, Any]) -> Dict[str, Any]:
        prefill_result = _materialize_prefill(prefill_result)
        params = _sampling_from_body(body)
        ids: List[int] = []
        last = None
        for chunk in self.engine.generate_from_prefill(prefill_result, params):
            ids.extend(chunk.token_ids)
            last = chunk
        return {
            "text": self.engine.tokenizer.decode(ids),
            "token_ids": ids,
            "finish_reason": last.finish_reason,
            "num_prompt_tokens": len(prefill_result["prompt_ids"]),
            "num_generated_tokens": len(ids),
        }

    def model_id(self) -> str:
        return self.llm_config.model_id

    def metrics(self) -> Dict[str, Any]:
        return self.engine.metrics()

    # scheduler-loop stall bound for check_health: generous enough for a cold
    # XLA compile of a big model's burst program, far below a wedged device
    ENGINE_STALL_S = 300.0

    def check_health(self) -> None:
        if self.engine._shutdown:
            raise RuntimeError("engine stopped")
        import time as _time

        eng = self.engine
        # a live loop ticks every burst; requests in flight with a stale tick
        # means the scheduler thread is wedged (device hang, deadlock) — fail
        # health so the serve controller replaces this replica
        if eng._loop_thread is not None and (eng.num_active or eng.num_pending):
            stale = _time.monotonic() - eng._last_tick_monotonic
            if stale > self.ENGINE_STALL_S:
                raise RuntimeError(
                    f"engine scheduler loop stalled for {stale:.0f}s with "
                    f"{eng.num_active} active / {eng.num_pending} pending "
                    "requests")

    def shutdown(self) -> None:
        self.engine.shutdown()


class OpenAIRouter:
    """Multi-model ingress: routes /v1/* to per-model LLMServer deployments."""

    def __init__(self, **model_handles):
        # model_id -> DeploymentHandle to an LLMServer deployment
        self.handles = model_handles

    def _pick(self, model: Optional[str]):
        if model in self.handles:
            return self.handles[model]
        if model is None and len(self.handles) == 1:
            return next(iter(self.handles.values()))
        raise ValueError(f"unknown model {model!r}; served: {sorted(self.handles)}")

    def handle_http(self, request: Dict[str, Any]):
        path, body = request["path"], request.get("body") or {}
        if path.endswith("/models"):
            return _models_list(self.handles)
        model = body.get("model") if isinstance(body, dict) else None
        handle = self._pick(model)
        stream = bool(isinstance(body, dict) and body.get("stream"))
        if path.endswith("/chat/completions"):
            h = handle.options(method_name="chat", stream=stream)
        elif path.endswith("/completions"):
            h = handle.options(method_name="completions", stream=stream)
        else:
            raise ValueError(f"unsupported path {path!r}")
        resp = h.remote(body)
        # streaming: return the response generator itself — the router is called
        # with a streaming method too, so each SSE frame re-streams through it
        return resp if stream else resp.result()

    # direct-handle convenience (tests, in-cluster clients)
    def chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.handle_http({"path": "/v1/chat/completions", "method": "POST", "body": body})

    def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.handle_http({"path": "/v1/completions", "method": "POST", "body": body})


def _materialize_prefill(pre):
    """Resolve an overlapped prefill handoff on the decode side.

    The PDRouter forwards the prefill pool's response FUTURE straight into the
    decode call, so decode dispatch/scheduling overlaps prefill execution
    instead of waiting for the router to materialize the result first — one
    control round trip off the TTFT critical path. A prefill failure re-raises
    here and surfaces through the decode call's error path."""
    return pre.result() if hasattr(pre, "result") else pre


def _release_orphan_export(pre: Dict[str, Any]) -> None:
    """Free an orphaned prefill KV export now instead of waiting for its TTL.
    Dials the exporting process's arm channel directly off the handle —
    pool-safe: a ``release_prefill`` deployment call would p2c-route to an
    arbitrary pool replica, not the one that exported."""
    handle = pre.get("kv_handle")
    if handle is None:
        return
    try:
        from ray_tpu.core.device_plane import release_remote

        release_remote(handle)
    except Exception as rel_err:
        _LOGGER.warning(
            "could not release prefill KV export %s after host "
            "fallback (%r); the prefill engine pins it until the "
            "TTL backstop", pre.get("kv_key"), rel_err)


def _is_device_plane_error(e: BaseException) -> bool:
    """Match a DevicePlaneError surfaced through the actor-RPC boundary (the
    original may arrive re-raised, wrapped, or as a cause)."""
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if type(cur).__name__ == "DevicePlaneError":
            return True
        cur = cur.__cause__ or cur.__context__
    return "DevicePlaneError" in str(e)


class PDRouter:
    """Prefill/decode-disaggregated ingress: prompts prefill on one replica pool,
    the KV crosses to a decode pool that streams the completion (reference
    python/ray/llm/_internal/serve/deployments/prefill_decode_disagg/). The KV hop
    is device-to-device over the transfer plane (core/device_plane.py — DCN on
    pods) when available; only a ~1 KB handle rides the control message. Host
    arrays through the object store are the fallback."""

    def __init__(self, prefill_handle, decode_handle, model_id: str):
        self.prefill_handle = prefill_handle
        self.decode_handle = decode_handle
        self.model_id = model_id

    def _release_orphan(self, pre: Dict[str, Any]) -> None:
        _release_orphan_export(pre)

    def _settle_prefill(self, pre_resp, timeout_s: float = 5.0):
        """Materialize an overlapped prefill response for fallback handling.
        Returns the prefill dict, or None when the result is unobtainable
        (the producer died taking its result object with it) — the fallback
        path proceeds either way; only the early orphan release is skipped."""
        try:
            return pre_resp.result(timeout_s=timeout_s)
        # graftlint: allow[swallowed-exception] producer gone with its result: the export TTL backstop reaps it
        except Exception:
            return None

    def _run(self, prompt: str, body: Dict[str, Any]) -> Dict[str, Any]:
        # the decode call is dispatched IMMEDIATELY with the prefill pool's
        # response future: the decode replica resolves it itself
        # (_materialize_prefill), so decode dispatch/scheduling overlaps
        # prefill execution instead of serializing behind a router-side
        # result() round trip.
        pre_resp = self.prefill_handle.options(method_name="prefill").remote(
            prompt, body)
        # KV release: the decode replica acks the prefill side's device-plane
        # export right after its pull (fetch(..., release=True)); no router hop.
        try:
            return self.decode_handle.options(
                method_name="decode_from_prefill").remote(
                    pre_resp, body).result()
        except Exception as e:
            if not _is_device_plane_error(e):
                # a prefill failure is the request's real fate: surface it
                # (with the handle's replica-retry plane) instead of the
                # decode-side wrapper it arrived in
                pre_resp.result()
                raise
            # Device pull failed (topology mismatch, prefill replica restarted
            # or died mid-transfer): redo the request on the host path — the
            # old always-works behavior.
            pre = self._settle_prefill(pre_resp)
            if pre is not None and "kv_handle" not in pre:
                raise
            _LOGGER.warning(
                "device-plane KV handoff failed for key %s (%r); retrying "
                "over the host path", (pre or {}).get("kv_key"), e)
            if pre is not None:
                self._release_orphan(pre)
            body = dict(body)
            body["_kv_host_fallback"] = True
            pre = self.prefill_handle.options(method_name="prefill").remote(
                prompt, body).result()
            return self.decode_handle.options(
                method_name="decode_from_prefill").remote(pre, body).result()

    def chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        out = self._run(render_chat_template(body.get("messages", [])), body)
        return _chat_envelope(
            body.get("model", self.model_id), out["text"], out["finish_reason"],
            _usage(out["num_prompt_tokens"], out["num_generated_tokens"]))

    def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        out = self._run(body.get("prompt", ""), body)
        return _completion_envelope(
            body.get("model", self.model_id), out["text"], out["finish_reason"],
            _usage(out["num_prompt_tokens"], out["num_generated_tokens"]))

    def handle_http(self, request: Dict[str, Any]):
        path, body = request["path"], request.get("body") or {}
        if path.endswith("/models"):
            return _models_list([self.model_id])
        chat = path.endswith("/chat/completions")
        if not chat and not path.endswith("/completions"):
            raise ValueError(f"unsupported path {path!r}")
        if isinstance(body, dict) and body.get("stream"):
            # streaming P/D rides the same device-plane handle as unary: the
            # decode replica pulls KV pages directly from the prefill replica
            # (~1 KB handle in the control message, no object-store hop) and
            # the decode stream is handed to the HTTP proxy before its first
            # frame — SSE frames never re-stream through this router
            prompt = (render_chat_template(body.get("messages", []))
                      if chat else body.get("prompt", ""))
            return self._stream_pd(prompt, body, chat)
        return self.chat(body) if chat else self.completions(body)

    def _stream_pd(self, prompt: str, body: Dict[str, Any], chat: bool):
        """Streaming P/D: dispatch prefill, then hand the decode replica's
        SSE stream to the HTTP proxy (StreamHandoff) BEFORE its first frame,
        so frames flow decode -> proxy -> client with no per-frame re-put
        through this router and nothing router-side on the first-content
        critical path — the disaggregated stream has the same hop count as
        the colocated one. The decode replica materializes the prefill
        future itself (overlapped with its own admission) and owns ALL
        failure handling: ``decode_stream`` re-prefills over the host path
        through its own prefill-pool handle on a device-plane failure —
        whether in the prefill result or mid-KV-pull — and resumes the same
        SSE stream, mirroring the unary path's fallback. Handing off before
        the first frame is therefore safe: there is nothing left for this
        router to splice."""
        pre_resp = self.prefill_handle.options(method_name="prefill").remote(
            prompt, body)

        def gen():
            from ray_tpu.serve.handle import StreamHandoff

            resp = self.decode_handle.options(
                method_name="decode_stream", stream=True).remote(
                    pre_resp, body, chat)
            ho = StreamHandoff.of(resp)
            if ho is not None:
                yield ho
                return
            # no transferable stream (local-testing handles, or the handoff
            # pin failed): relay frames through this process instead —
            # decode_stream's internal fallback still covers failures
            yield from resp

        return gen()


def build_pd_openai_app(llm_config: LLMConfig, *, num_prefill: int = 1,
                        num_decode: int = 1, name_prefix: str = "llm-pd",
                        max_prefill: Optional[int] = None,
                        max_decode: Optional[int] = None,
                        ttft_slo_name: Optional[str] = None,
                        prefill_autoscaling=None, decode_autoscaling=None):
    """Prefill/decode-disaggregated serving app (reference build: P/D deployments).

    Each pool is an independently autoscaled multi-replica deployment — the
    two phases have different bottlenecks, so they get different signals:

    - the **prefill pool** scales off TTFT-SLO burn (``mode="slo"`` pinned to
      ``ttft_slo_name`` when given; register that SLO via
      ``telemetry.register_slo``). TTFT is prefill-bound, so burning the TTFT
      budget adds prefill replicas before touching decode.
    - the **decode pool** scales off live queue depth: decode holds each
      request for its whole generation, so backlog — not arrival rate — is
      the capacity signal.

    Autoscaling engages when ``max_prefill``/``max_decode`` exceed the
    ``num_*`` floors; either policy can be overridden wholesale with
    ``prefill_autoscaling``/``decode_autoscaling`` (AutoscalingConfig).
    Without caps the pools stay pinned at ``num_prefill``/``num_decode``.
    """
    from ray_tpu import serve
    from ray_tpu.serve.config import AutoscalingConfig

    if prefill_autoscaling is None and (max_prefill or 0) > num_prefill:
        prefill_autoscaling = AutoscalingConfig.for_slo(
            min_replicas=num_prefill, max_replicas=max_prefill,
            slo_names=[ttft_slo_name] if ttft_slo_name else None)
    if decode_autoscaling is None and (max_decode or 0) > num_decode:
        decode_autoscaling = AutoscalingConfig.for_slo(
            min_replicas=num_decode, max_replicas=max_decode)

    prefill = serve.deployment(LLMServer).options(
        name=f"{name_prefix}:prefill", num_replicas=num_prefill,
        max_ongoing_requests=32,
        autoscaling_config=prefill_autoscaling).bind(llm_config)
    decode = serve.deployment(LLMServer).options(
        name=f"{name_prefix}:decode", num_replicas=num_decode,
        max_ongoing_requests=64,
        autoscaling_config=decode_autoscaling).bind(
            llm_config, prefill_handle=prefill)
    router = serve.deployment(PDRouter).options(name=f"{name_prefix}-router")
    return router.bind(prefill, decode, llm_config.model_id)


def build_openai_app(llm_configs: List[LLMConfig], name_prefix: str = "llm"):
    """Build a Serve Application: OpenAIRouter ingress + one LLMServer per model.

    Reference builders/build_openai_app. Returns an Application for serve.run().
    """
    from ray_tpu import serve

    servers = {}
    for cfg in llm_configs:
        d = serve.deployment(LLMServer).options(
            name=f"{name_prefix}:{cfg.model_id}",
            num_replicas=cfg.deployment_config.get("num_replicas", 1),
            max_ongoing_requests=cfg.deployment_config.get("max_ongoing_requests", 64),
            ray_actor_options=cfg.deployment_config.get("ray_actor_options"),
        )
        servers[cfg.model_id] = d.bind(cfg)
    router = serve.deployment(OpenAIRouter).options(name=f"{name_prefix}-router")
    return router.bind(**servers)
