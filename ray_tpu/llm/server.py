"""LLMServer Serve deployment + OpenAI-compatible router.

Capability parity: reference python/ray/llm/_internal/serve/deployments/llm/
llm_server.py:409 (``LLMServer`` — Serve deployment wrapping an engine, OpenAI
chat/completions) and serve/routers/ + builders/ (``build_openai_app`` multi-model
ingress). The engine here is ``JaxLLMEngine`` (TP over the replica's device mesh)
instead of vLLM.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

from .config import LLMConfig, SamplingParams
from .engine import JaxLLMEngine


def _sampling_from_body(body: Dict[str, Any]) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(body.get("max_tokens", 64)),
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0)),
        seed=body.get("seed"),
    )


def render_chat_template(messages: List[Dict[str, str]]) -> str:
    """Minimal chat template (reference: HF chat templates via vLLM's tokenizer)."""
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages]
    return "\n".join(parts) + "\nassistant:"


class LLMServer:
    """Serve deployment hosting one model's engine.

    Deploy via ``build_openai_app`` or directly:
        app = serve.deployment(LLMServer).bind(llm_config)
    """

    def __init__(self, llm_config: LLMConfig, engine: Optional[JaxLLMEngine] = None):
        self.llm_config = llm_config
        self.engine = engine or JaxLLMEngine(llm_config)
        self.engine.start()

    # -- OpenAI endpoints --------------------------------------------------------
    def chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        prompt = render_chat_template(body.get("messages", []))
        out = self.engine.generate_sync(prompt, _sampling_from_body(body))
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model", self.llm_config.model_id),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": out.text},
                "finish_reason": out.finish_reason,
            }],
            "usage": {
                "prompt_tokens": out.num_prompt_tokens,
                "completion_tokens": out.num_generated_tokens,
                "total_tokens": out.num_prompt_tokens + out.num_generated_tokens,
            },
        }

    def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        out = self.engine.generate_sync(body.get("prompt", ""), _sampling_from_body(body))
        return {
            "id": f"cmpl-{uuid.uuid4().hex[:24]}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", self.llm_config.model_id),
            "choices": [{"index": 0, "text": out.text, "finish_reason": out.finish_reason}],
            "usage": {
                "prompt_tokens": out.num_prompt_tokens,
                "completion_tokens": out.num_generated_tokens,
                "total_tokens": out.num_prompt_tokens + out.num_generated_tokens,
            },
        }

    def model_id(self) -> str:
        return self.llm_config.model_id

    def metrics(self) -> Dict[str, Any]:
        return self.engine.metrics()

    def check_health(self) -> None:
        if self.engine._shutdown:
            raise RuntimeError("engine stopped")

    def shutdown(self) -> None:
        self.engine.shutdown()


class OpenAIRouter:
    """Multi-model ingress: routes /v1/* to per-model LLMServer deployments."""

    def __init__(self, **model_handles):
        # model_id -> DeploymentHandle to an LLMServer deployment
        self.handles = model_handles

    def _pick(self, model: Optional[str]):
        if model in self.handles:
            return self.handles[model]
        if model is None and len(self.handles) == 1:
            return next(iter(self.handles.values()))
        raise ValueError(f"unknown model {model!r}; served: {sorted(self.handles)}")

    def handle_http(self, request: Dict[str, Any]) -> Dict[str, Any]:
        path, body = request["path"], request.get("body") or {}
        if path.endswith("/models"):
            return {
                "object": "list",
                "data": [
                    {"id": m, "object": "model", "owned_by": "ray_tpu"}
                    for m in sorted(self.handles)
                ],
            }
        model = body.get("model") if isinstance(body, dict) else None
        handle = self._pick(model)
        if path.endswith("/chat/completions"):
            return handle.options(method_name="chat").remote(body).result()
        if path.endswith("/completions"):
            return handle.options(method_name="completions").remote(body).result()
        raise ValueError(f"unsupported path {path!r}")

    # direct-handle convenience (tests, in-cluster clients)
    def chat(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.handle_http({"path": "/v1/chat/completions", "method": "POST", "body": body})

    def completions(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.handle_http({"path": "/v1/completions", "method": "POST", "body": body})


def build_openai_app(llm_configs: List[LLMConfig], name_prefix: str = "llm"):
    """Build a Serve Application: OpenAIRouter ingress + one LLMServer per model.

    Reference builders/build_openai_app. Returns an Application for serve.run().
    """
    from ray_tpu import serve

    servers = {}
    for cfg in llm_configs:
        d = serve.deployment(LLMServer).options(
            name=f"{name_prefix}:{cfg.model_id}",
            num_replicas=cfg.deployment_config.get("num_replicas", 1),
            max_ongoing_requests=cfg.deployment_config.get("max_ongoing_requests", 64),
            ray_actor_options=cfg.deployment_config.get("ray_actor_options"),
        )
        servers[cfg.model_id] = d.bind(cfg)
    router = serve.deployment(OpenAIRouter).options(name=f"{name_prefix}-router")
    return router.bind(**servers)
