"""Tokenizer abstraction for the LLM stack.

The reference delegates tokenization to HF via vLLM (SURVEY.md §2.7 batch stages:
tokenize_stage.py). Here a minimal protocol with two impls: a dependency-free
byte-level tokenizer (hermetic tests, no downloads) and an optional HF wrapper.
"""
from __future__ import annotations

from typing import List, Protocol


class Tokenizer(Protocol):
    eos_token_id: int
    vocab_size: int

    def encode(self, text: str) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes + offset; ids 0..=2 reserved (0=pad, 1=bos, 2=eos)."""

    _OFFSET = 3

    def __init__(self):
        self.pad_token_id = 0
        self.bos_token_id = 1
        self.eos_token_id = 2
        self.vocab_size = 256 + self._OFFSET

    def encode(self, text: str) -> List[int]:
        return [self.bos_token_id] + [b + self._OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: List[int]) -> str:
        # ids beyond the byte range (a model vocab may exceed 256+3) are dropped,
        # like special/unknown tokens in a real tokenizer's skip_special_tokens path
        data = bytes(i - self._OFFSET for i in ids
                     if self._OFFSET <= i < self._OFFSET + 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers.AutoTokenizer wrapper (local paths only in hermetic envs)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        self.eos_token_id = self._tok.eos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def get_tokenizer(spec: str) -> Tokenizer:
    if spec == "byte":
        return ByteTokenizer()
    if spec.startswith("hf:"):
        return HFTokenizer(spec[3:])
    raise ValueError(f"unknown tokenizer spec: {spec!r}")
