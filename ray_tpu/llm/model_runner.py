"""Jitted prefill/decode over a slot-based device-resident KV cache.

This is the TPU replacement for vLLM's GPU model runner (reference
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180): instead
of paged attention over dynamically allocated GPU blocks, the cache is a static
[L, slots, max_len, kv_heads, head_dim] array — XLA-friendly static shapes, with
raggedness expressed as a per-slot ``lengths`` vector that masks attention and
indexes scatter-writes. Slots are the continuous-batching unit: prefill fills one
slot, decode advances all slots in a single fused step.

Sharding: params via INFER_RULES (heads/mlp/vocab → tp), cache kv_heads → tp and
slots → dp, so TP rides ICI inside each decode step and DP widens throughput.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.models.config import ModelConfig
from ray_tpu.parallel.sharding import INFER_RULES, named_sharding, shard_pytree

from . import sampling


class DecodeState(NamedTuple):
    """Device-resident serving state. lengths[s] = tokens currently cached in slot s."""

    k: jax.Array  # [L, slots, max_len, kv_heads, head_dim]
    v: jax.Array
    lengths: jax.Array  # [slots] int32


CACHE_SPEC = P(None, "dp", None, "tp", None)
LENGTHS_SPEC = P("dp")


def init_state(cfg: ModelConfig, slots: int, max_len: int, mesh: Mesh) -> DecodeState:
    shape = (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    dtype = cfg.activation_dtype
    kv_sh = NamedSharding(mesh, CACHE_SPEC)
    len_sh = NamedSharding(mesh, LENGTHS_SPEC)
    return DecodeState(
        k=jax.device_put(jnp.zeros(shape, dtype), kv_sh),
        v=jax.device_put(jnp.zeros(shape, dtype), kv_sh),
        lengths=jax.device_put(jnp.zeros((slots,), jnp.int32), len_sh),
    )


def shard_params(params, cfg: ModelConfig, mesh: Mesh):
    return shard_pytree(params, llama.param_axes(cfg), mesh, INFER_RULES)


# ------------------------------------------------------------------------- prefill

@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def prefill(
    params,
    state: DecodeState,
    tokens: jax.Array,  # [1, S_pad] int32 (padded to a bucket length)
    true_len: jax.Array,  # scalar int32
    slot: jax.Array,  # scalar int32
    cfg: ModelConfig,
) -> Tuple[DecodeState, jax.Array]:
    """Run the prompt through the model, install its KV into `slot`, return the
    logits at the last real token ([vocab] f32)."""
    s_pad = tokens.shape[1]
    tmp = llama.init_kv_cache(cfg, batch=1, max_len=s_pad, dtype=state.k.dtype)
    # pad positions beyond the real prompt must not claim MoE expert capacity
    token_mask = (jnp.arange(s_pad)[None, :] < true_len).astype(jnp.float32)
    logits, tmp, _ = llama.forward(params, tokens, cfg, cache=tmp,
                                   token_mask=token_mask, return_aux=True)
    # install [L, 1, S_pad, KV, HD] into the big cache at (slot, 0)
    start = (0, slot, 0, 0, 0)
    k = jax.lax.dynamic_update_slice(state.k, tmp.k, start)
    v = jax.lax.dynamic_update_slice(state.v, tmp.v, start)
    lengths = state.lengths.at[slot].set(true_len)
    last = logits[0, true_len - 1].astype(jnp.float32)
    return DecodeState(k=k, v=v, lengths=lengths), last


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill_detached(
    params,
    tokens: jax.Array,  # [1, S_pad]
    true_len: jax.Array,  # scalar int32
    cfg: ModelConfig,
):
    """Prefill WITHOUT installing into a decode state: returns (k, v, last_logits)
    with k/v [L, 1, S_pad, KV, HD]. The P/D-disaggregated serving path runs this on
    a prefill replica; the KV then travels (host/DCN) to a decode replica which
    installs it via install_kv (reference: prefill_decode_disagg deployments)."""
    s_pad = tokens.shape[1]
    tmp = llama.init_kv_cache(cfg, batch=1, max_len=s_pad, dtype=cfg.activation_dtype)
    token_mask = (jnp.arange(s_pad)[None, :] < true_len).astype(jnp.float32)
    logits, tmp, _ = llama.forward(params, tokens, cfg, cache=tmp,
                                   token_mask=token_mask, return_aux=True)
    last = logits[0, true_len - 1].astype(jnp.float32)
    return tmp.k, tmp.v, last


@functools.partial(jax.jit, donate_argnames=("state",))
def install_kv(
    state: DecodeState,
    k: jax.Array,  # [L, 1, S_pad, KV, HD]
    v: jax.Array,
    true_len: jax.Array,  # scalar int32
    slot: jax.Array,  # scalar int32
) -> DecodeState:
    """Install transferred prefill KV into a decode slot."""
    start = (0, slot, 0, 0, 0)
    nk = jax.lax.dynamic_update_slice(state.k, k.astype(state.k.dtype), start)
    nv = jax.lax.dynamic_update_slice(state.v, v.astype(state.v.dtype), start)
    lengths = state.lengths.at[slot].set(true_len)
    return DecodeState(k=nk, v=nv, lengths=lengths)


# -------------------------------------------------------------------------- decode

def _decode_block(x, lp, cfg: ModelConfig, ck, cv, lengths, active):
    """One layer's decode for all slots. x [S,1,D]; ck/cv [S,max_len,KV,HD];
    returns (x, ck, cv) with this step's K/V scattered in at position lengths[s].
    `active` [S] keeps inactive slots out of MoE expert capacity."""
    dt = x.dtype
    s, max_len = ck.shape[0], ck.shape[1]
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    g = cfg.n_heads // kvh
    pos = lengths[:, None]  # [S,1] — the new token's position

    h = llama.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("sld,dhk->slhk", h, lp["wq"].astype(dt))
    k = jnp.einsum("sld,dhk->slhk", h, lp["wk"].astype(dt))
    vv = jnp.einsum("sld,dhk->slhk", h, lp["wv"].astype(dt))
    q = llama.rope(q, pos, cfg.rope_theta)
    k = llama.rope(k, pos, cfg.rope_theta)

    rows = jnp.arange(s)
    ck = ck.at[rows, lengths].set(k[:, 0].astype(ck.dtype))
    cv = cv.at[rows, lengths].set(vv[:, 0].astype(cv.dtype))

    qg = q[:, 0].reshape(s, kvh, g, hd) * (hd**-0.5)
    scores = jnp.einsum("skgd,stkd->skgt", qg.astype(jnp.float32), ck.astype(jnp.float32))
    valid = (jnp.arange(max_len)[None, :] <= lengths[:, None])[:, None, None, :]
    scores = jnp.where(valid, scores, sampling.NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("skgt,stkd->skgd", w, cv.astype(jnp.float32)).astype(dt)
    o = o.reshape(s, 1, cfg.n_heads, hd)
    x = x + jnp.einsum("slhk,hkd->sld", o, lp["wo"].astype(dt))

    h = llama.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        from ray_tpu.models import moe as _moe

        y2, _ = _moe.moe_mlp(h[:, 0], lp["router"], lp["w_gate"], lp["w_up"],
                             lp["w_down"], cfg, mask=active.astype(jnp.float32))
        down = y2[:, None, :]
    else:
        gate = jnp.einsum("sld,df->slf", h, lp["w_gate"].astype(dt))
        up = jnp.einsum("sld,df->slf", h, lp["w_up"].astype(dt))
        down = jnp.einsum("slf,fd->sld", jax.nn.silu(gate) * up, lp["w_down"].astype(dt))
    return x + down, ck, cv


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_step(
    params,
    state: DecodeState,
    tokens: jax.Array,  # [slots] int32 — last sampled token per slot
    active: jax.Array,  # [slots] bool — inactive slots compute but don't advance
    cfg: ModelConfig,
) -> Tuple[DecodeState, jax.Array]:
    """One decode step for every slot. Returns (state, logits [slots, vocab] f32).

    Inactive slots still flow through the matmuls (static shapes) but their cache
    write lands at position lengths[s] of a slot whose contents the next prefill
    overwrites, and their length does not advance.
    """
    x = params["embed"].astype(cfg.activation_dtype)[tokens[:, None]]  # [S,1,D]

    if cfg.scan_layers:
        def body(carry, xs):
            h = carry
            lp, ck, cv = xs
            h, ck, cv = _decode_block(h, lp, cfg, ck, cv, state.lengths, active)
            return h, (ck, cv)

        x, (nk, nv) = jax.lax.scan(body, x, (params["layers"], state.k, state.v))
    else:
        nk, nv = [], []
        for i, lp in enumerate(params["layers"]):
            x, ck, cv = _decode_block(x, lp, cfg, state.k[i], state.v[i],
                                      state.lengths, active)
            nk.append(ck)
            nv.append(cv)
        nk, nv = jnp.stack(nk), jnp.stack(nv)

    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", x, head.astype(cfg.activation_dtype))[:, 0]
    lengths = jnp.where(active, state.lengths + 1, state.lengths)
    return DecodeState(k=nk, v=nv, lengths=lengths), logits.astype(jnp.float32)


# ------------------------------------------------------------------------- sampler

@jax.jit
def sample_tokens(rng, logits, temperature, top_p, top_k):
    return sampling.sample(rng, logits, temperature, top_p, top_k)
