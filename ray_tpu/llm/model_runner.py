"""Jitted prefill/decode over a slot-based device-resident KV cache.

This is the TPU replacement for vLLM's GPU model runner (reference
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180): instead
of paged attention over dynamically allocated GPU blocks, the cache is a static
[L, slots, max_len, kv_heads, head_dim] array — XLA-friendly static shapes, with
raggedness expressed as a per-slot ``lengths`` vector that masks attention and
indexes scatter-writes. Slots are the continuous-batching unit: prefill fills one
slot, decode advances all slots in a single fused step.

Sharding: params via INFER_RULES (heads/mlp/vocab → tp), cache kv_heads → tp and
slots → dp, so TP rides ICI inside each decode step and DP widens throughput.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.ops.quant import as_weight as _qw
from ray_tpu.models.config import ModelConfig
from ray_tpu.parallel.sharding import INFER_RULES, named_sharding, shard_pytree

from . import sampling


class DecodeState(NamedTuple):
    """Device-resident serving state. lengths[s] = tokens currently cached in slot s."""

    k: jax.Array  # [L, slots, max_len, kv_heads, head_dim]
    v: jax.Array
    lengths: jax.Array  # [slots] int32


CACHE_SPEC = P(None, "dp", None, "tp", None)
# pipelined engines: each pp stage holds its layers' cache slice; slots still
# shard over dp replicas (pp x dp composition)
CACHE_SPEC_PP = P("pp", "dp", None, "tp", None)
LENGTHS_SPEC = P("dp")


def _present(mesh: Mesh, spec: P) -> P:
    """Drop axis names the mesh doesn't have (engine-built meshes carry all of
    pp/dp/ep/tp; user-supplied meshes may name only a subset)."""
    return P(*((ax if ax in mesh.shape else None) for ax in spec))


def init_state(cfg: ModelConfig, slots: int, max_len: int, mesh: Mesh) -> DecodeState:
    shape = (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    dtype = cfg.activation_dtype
    pp = "pp" in mesh.shape and mesh.shape["pp"] > 1
    kv_sh = NamedSharding(mesh, _present(mesh, CACHE_SPEC_PP if pp else CACHE_SPEC))
    len_sh = NamedSharding(mesh, _present(mesh, LENGTHS_SPEC))
    return DecodeState(
        k=jax.device_put(jnp.zeros(shape, dtype), kv_sh),
        v=jax.device_put(jnp.zeros(shape, dtype), kv_sh),
        lengths=jax.device_put(jnp.zeros((slots,), jnp.int32), len_sh),
    )


def infer_rules_for_mesh(mesh: Mesh):
    """INFER_RULES, plus the scanned layer axis over "pp" when the mesh has it."""
    from ray_tpu.parallel.sharding import AxisRules

    if "pp" in mesh.shape and mesh.shape["pp"] > 1:
        return AxisRules({**INFER_RULES.rules, "layer": "pp"})
    return INFER_RULES


def shard_params(params, cfg: ModelConfig, mesh: Mesh):
    return shard_pytree(params, llama.param_axes(cfg), mesh, infer_rules_for_mesh(mesh))


# ------------------------------------------------------------------------- prefill

@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def prefill(
    params,
    state: DecodeState,
    tokens: jax.Array,  # [1, S_pad] int32 (padded to a bucket length)
    true_len: jax.Array,  # scalar int32
    slot: jax.Array,  # scalar int32
    cfg: ModelConfig,
) -> Tuple[DecodeState, jax.Array]:
    """Run the prompt through the model, install its KV into `slot`, return the
    logits at the last real token ([vocab] f32)."""
    s_pad = tokens.shape[1]
    tmp = llama.init_kv_cache(cfg, batch=1, max_len=s_pad, dtype=state.k.dtype)
    # pad positions beyond the real prompt must not claim MoE expert capacity
    token_mask = (jnp.arange(s_pad)[None, :] < true_len).astype(jnp.float32)
    logits, tmp, _ = llama.forward(params, tokens, cfg, cache=tmp,
                                   token_mask=token_mask, return_aux=True)
    # install [L, 1, S_pad, KV, HD] into the big cache at (slot, 0)
    start = (0, slot, 0, 0, 0)
    k = jax.lax.dynamic_update_slice(state.k, tmp.k, start)
    v = jax.lax.dynamic_update_slice(state.v, tmp.v, start)
    lengths = state.lengths.at[slot].set(true_len)
    last = logits[0, true_len - 1].astype(jnp.float32)
    return DecodeState(k=k, v=v, lengths=lengths), last


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill_detached(
    params,
    tokens: jax.Array,  # [1, S_pad]
    true_len: jax.Array,  # scalar int32
    cfg: ModelConfig,
):
    """Prefill WITHOUT installing into a decode state: returns (k, v, last_logits)
    with k/v [L, 1, S_pad, KV, HD]. The P/D-disaggregated serving path runs this on
    a prefill replica; the KV then travels (host/DCN) to a decode replica which
    installs it via install_kv (reference: prefill_decode_disagg deployments)."""
    s_pad = tokens.shape[1]
    tmp = llama.init_kv_cache(cfg, batch=1, max_len=s_pad, dtype=cfg.activation_dtype)
    token_mask = (jnp.arange(s_pad)[None, :] < true_len).astype(jnp.float32)
    logits, tmp, _ = llama.forward(params, tokens, cfg, cache=tmp,
                                   token_mask=token_mask, return_aux=True)
    last = logits[0, true_len - 1].astype(jnp.float32)
    return tmp.k, tmp.v, last


@functools.partial(jax.jit, donate_argnames=("state",))
def install_kv(
    state: DecodeState,
    k: jax.Array,  # [L, 1, S_pad, KV, HD]
    v: jax.Array,
    true_len: jax.Array,  # scalar int32
    slot: jax.Array,  # scalar int32
) -> DecodeState:
    """Install transferred prefill KV into a decode slot."""
    start = (0, slot, 0, 0, 0)
    nk = jax.lax.dynamic_update_slice(state.k, k.astype(state.k.dtype), start)
    nv = jax.lax.dynamic_update_slice(state.v, v.astype(state.v.dtype), start)
    lengths = state.lengths.at[slot].set(true_len)
    return DecodeState(k=nk, v=nv, lengths=lengths)


# -------------------------------------------------------------------------- decode

def _decode_core(x, lp, cfg: ModelConfig, lengths, active, cache_rw):
    """One layer's single-token decode math, shared by every cache layout.

    cache_rw(k_new [S,KV,HD], v_new) -> (ck_view [S,max_len,KV,HD], cv_view,
    storage) — the adapter writes this step's K/V into its layout and returns
    per-slot full-history views for attention plus the updated storage, which
    is threaded back to the caller untouched."""
    dt = x.dtype
    s = x.shape[0]
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    g = cfg.n_heads // kvh
    pos = lengths[:, None]  # [S,1] — the new token's position

    h = llama.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("sld,dhk->slhk", h, _qw(lp["wq"], dt))
    k = jnp.einsum("sld,dhk->slhk", h, _qw(lp["wk"], dt))
    vv = jnp.einsum("sld,dhk->slhk", h, _qw(lp["wv"], dt))
    q = llama.rope(q, pos, cfg.rope_theta)
    k = llama.rope(k, pos, cfg.rope_theta)

    ck, cv, storage = cache_rw(k[:, 0], vv[:, 0])
    max_len = ck.shape[1]

    qg = q[:, 0].reshape(s, kvh, g, hd) * (hd**-0.5)
    scores = jnp.einsum("skgd,stkd->skgt", qg.astype(jnp.float32), ck.astype(jnp.float32))
    valid = (jnp.arange(max_len)[None, :] <= lengths[:, None])[:, None, None, :]
    scores = jnp.where(valid, scores, sampling.NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("skgt,stkd->skgd", w, cv.astype(jnp.float32)).astype(dt)
    o = o.reshape(s, 1, cfg.n_heads, hd)
    x = x + jnp.einsum("slhk,hkd->sld", o, _qw(lp["wo"], dt))

    h = llama.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        from ray_tpu.models import moe as _moe

        y2, _ = _moe.moe_mlp(h[:, 0], lp["router"], lp["w_gate"], lp["w_up"],
                             lp["w_down"], cfg, mask=active.astype(jnp.float32))
        down = y2[:, None, :]
    else:
        gate = jnp.einsum("sld,df->slf", h, _qw(lp["w_gate"], dt))
        up = jnp.einsum("sld,df->slf", h, _qw(lp["w_up"], dt))
        down = jnp.einsum("slf,fd->sld", jax.nn.silu(gate) * up, _qw(lp["w_down"], dt))
    return x + down, storage


def _decode_block(x, lp, cfg: ModelConfig, ck, cv, lengths, active):
    """One layer's decode for all slots against the slot cache. x [S,1,D];
    ck/cv [S,max_len,KV,HD]; K/V scattered in at position lengths[s]."""

    def cache_rw(k_new, v_new):
        rows = jnp.arange(ck.shape[0])
        nk = ck.at[rows, lengths].set(k_new.astype(ck.dtype))
        nv = cv.at[rows, lengths].set(v_new.astype(cv.dtype))
        return nk, nv, (nk, nv)

    x, (nk, nv) = _decode_core(x, lp, cfg, lengths, active, cache_rw)
    return x, nk, nv


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_step(
    params,
    state: DecodeState,
    tokens: jax.Array,  # [slots] int32 — last sampled token per slot
    active: jax.Array,  # [slots] bool — inactive slots compute but don't advance
    cfg: ModelConfig,
) -> Tuple[DecodeState, jax.Array]:
    """One decode step for every slot. Returns (state, logits [slots, vocab] f32).

    Inactive slots still flow through the matmuls (static shapes) but their cache
    write lands at position lengths[s] of a slot whose contents the next prefill
    overwrites, and their length does not advance.
    """
    x = params["embed"].astype(cfg.activation_dtype)[tokens[:, None]]  # [S,1,D]

    if cfg.scan_layers:
        def body(carry, xs):
            h = carry
            lp, ck, cv = xs
            h, ck, cv = _decode_block(h, lp, cfg, ck, cv, state.lengths, active)
            return h, (ck, cv)

        x, (nk, nv) = jax.lax.scan(body, x, (params["layers"], state.k, state.v))
    else:
        nk, nv = [], []
        for i, lp in enumerate(params["layers"]):
            x, ck, cv = _decode_block(x, lp, cfg, state.k[i], state.v[i],
                                      state.lengths, active)
            nk.append(ck)
            nv.append(cv)
        nk, nv = jnp.stack(nk), jnp.stack(nv)

    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", x, _qw(head, cfg.activation_dtype))[:, 0]
    lengths = jnp.where(active, state.lengths + 1, state.lengths)
    return DecodeState(k=nk, v=nv, lengths=lengths), logits.astype(jnp.float32)


def _verify_core(x, lp, cfg: ModelConfig, lengths, cache_rw, active=None):
    """One layer over a W-token verify window for every slot (speculative
    decoding), shared by every cache layout: x [S,W,D], K/V written at
    positions lengths[s]+0..W-1 through the layout adapter, each query w
    attends to cache positions <= lengths[s]+w (causal within the window,
    full history before it).

    cache_rw(k_new [S,W,KV,HD], v_new) -> (ck [S,max_len,KV,HD], cv, storage).
    active [S] bool (MoE only): inactive slots' window tokens must not claim
    expert capacity.
    """
    dt = x.dtype
    s, wlen, _ = x.shape
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    g = cfg.n_heads // kvh
    pos = lengths[:, None] + jnp.arange(wlen)[None, :]  # [S,W]

    h = llama.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("sld,dhk->slhk", h, _qw(lp["wq"], dt))
    k = jnp.einsum("sld,dhk->slhk", h, _qw(lp["wk"], dt))
    vv = jnp.einsum("sld,dhk->slhk", h, _qw(lp["wv"], dt))
    q = llama.rope(q, pos, cfg.rope_theta)
    k = llama.rope(k, pos, cfg.rope_theta)

    ck, cv, storage = cache_rw(k, vv)
    max_len = ck.shape[1]

    qg = q.reshape(s, wlen, kvh, g, hd) * (hd**-0.5)
    scores = jnp.einsum("swkgd,stkd->swkgt", qg.astype(jnp.float32),
                        ck.astype(jnp.float32))
    valid = (jnp.arange(max_len)[None, None, :] <= pos[:, :, None])  # [S,W,T]
    scores = jnp.where(valid[:, :, None, None, :], scores, sampling.NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("swkgt,stkd->swkgd", w, cv.astype(jnp.float32)).astype(dt)
    o = o.reshape(s, wlen, cfg.n_heads, hd)
    x = x + jnp.einsum("slhk,hkd->sld", o, _qw(lp["wo"], dt))

    h = llama.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        from ray_tpu.models import moe as _moe

        tok_mask = None
        if active is not None:
            tok_mask = jnp.repeat(active.astype(jnp.float32), wlen)
        y2, _ = _moe.moe_mlp(h.reshape(s * wlen, -1), lp["router"], lp["w_gate"],
                             lp["w_up"], lp["w_down"], cfg, mask=tok_mask)
        down = y2.reshape(s, wlen, -1)
    else:
        gate = jnp.einsum("sld,df->slf", h, _qw(lp["w_gate"], dt))
        up = jnp.einsum("sld,df->slf", h, _qw(lp["w_up"], dt))
        down = jnp.einsum("slf,fd->sld", jax.nn.silu(gate) * up, _qw(lp["w_down"], dt))
    return x + down, storage


def _verify_block(x, lp, cfg: ModelConfig, ck, cv, lengths, active=None):
    """Slot-layout verify: K/V scattered at absolute positions (writes past
    max_len dropped)."""
    pos = lengths[:, None] + jnp.arange(x.shape[1])[None, :]
    rows = jnp.arange(x.shape[0])[:, None]

    def cache_rw(k_new, v_new):
        nk = ck.at[rows, pos].set(k_new.astype(ck.dtype), mode="drop")
        nv = cv.at[rows, pos].set(v_new.astype(cv.dtype), mode="drop")
        return nk, nv, (nk, nv)

    x, (nk, nv) = _verify_core(x, lp, cfg, lengths, cache_rw, active=active)
    return x, nk, nv


def spec_accept(window, greedy, draft_len, active, lengths, rng, temperature,
                top_p, top_k, logits0):
    """Shared accept logic: longest draft prefix matching argmax, +1 bonus;
    temperature>0 slots (no drafts) get a properly SAMPLED first token."""
    tok0 = sampling.sample(rng, logits0, temperature, top_p, top_k)
    greedy = greedy.at[:, 0].set(jnp.where(temperature > 0, tok0, greedy[:, 0]))
    wlen = window.shape[1]
    draft = window[:, 1:]
    idx = jnp.arange(wlen - 1)[None, :]
    match = (draft == greedy[:, :-1]) & (idx < draft_len[:, None])
    n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    advance = jnp.where(active, n_acc + 1, 0)
    return greedy, n_acc, lengths + advance


def spec_driver(params, k0, v0, lengths, window, draft_len, active, cfg,
                rng, temperature, top_p, top_k, layer_fn=None,
                layers_pass=None):
    """Shared speculative-verify pipeline (embed -> layers -> norm -> head ->
    accept); the cache layout differs only in layer_fn(h, lp, k, v). MoE models
    verify too: _verify_core routes the whole window through moe_mlp with
    inactive slots masked out of expert capacity. `layers_pass(x) -> (x, nk,
    nv)` replaces the whole layer loop (the pp schedule owns its own loop)."""
    x = params["embed"].astype(cfg.activation_dtype)[window]

    if layers_pass is not None:
        x, nk, nv = layers_pass(x)
    elif cfg.scan_layers:
        def body(carry, xs):
            h = carry
            lp, a, b = xs
            h, a, b = layer_fn(h, lp, a, b)
            return h, (a, b)

        x, (nk, nv) = jax.lax.scan(body, x, (params["layers"], k0, v0))
    else:
        nk, nv = [], []
        for i, lp in enumerate(params["layers"]):
            x, a, b = layer_fn(x, lp, k0[i], v0[i])
            nk.append(a)
            nv.append(b)
        nk, nv = jnp.stack(nk), jnp.stack(nv)

    x = llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", x, _qw(head, cfg.activation_dtype))
    greedy = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    greedy, n_acc, new_lengths = spec_accept(
        window, greedy, draft_len, active, lengths, rng, temperature,
        top_p, top_k, logits[:, 0].astype(jnp.float32))
    return nk, nv, new_lengths, greedy, n_acc


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def spec_verify_step(
    params,
    state: DecodeState,
    window: jax.Array,  # [S,W] int32 — [last_token, draft_1..draft_k] (0-padded)
    draft_len: jax.Array,  # [S] int32 — valid drafts per slot (<= W-1)
    active: jax.Array,  # [S] bool
    cfg: ModelConfig,
    rng: jax.Array,
    temperature: jax.Array,  # [S] f32
    top_p: jax.Array,  # [S] f32
    top_k: jax.Array,  # [S] i32
) -> Tuple[DecodeState, jax.Array, jax.Array]:
    """Speculative verify (reference: vLLM ngram/prompt-lookup spec decoding):
    ONE forward over the W-token window scores every draft; greedy
    accept = longest prefix where draft[i] == argmax(logits[i-1]).

    Returns (state, out_tokens [S,W], n_accepted [S]): out_tokens[s,:n+1] are
    this step's emitted tokens (n accepted drafts + 1 bonus/correction);
    lengths advance by n+1 for active slots."""
    nk, nv, lengths, greedy, n_acc = spec_driver(
        params, state.k, state.v, state.lengths, window, draft_len, active,
        cfg, rng, temperature, top_p, top_k,
        lambda h, lp, ck, cv: _verify_block(h, lp, cfg, ck, cv, state.lengths,
                                            active=active))
    return DecodeState(k=nk, v=nv, lengths=lengths), greedy, n_acc


def spec_verify_step_pp(params, state: DecodeState, window, draft_len, active,
                        rng, temperature, top_p, top_k, *,
                        cfg: ModelConfig, mesh: Mesh):
    """Speculative verify through the pipeline schedule (slot layout): same
    tick structure as decode_step_pp but the microbatch payload is the whole
    [smb, W, D] verify window. Slots shard over dp replicas, layers + cache
    over pp stages; bubble-tick cache writes are discarded with the same
    valid-mask select the decode schedule uses. The accept logic is
    spec_driver's, via its layers_pass seam."""
    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    s, w = window.shape
    if s % (pp * dp):
        raise ValueError(f"max_num_seqs {s} must be divisible by pp*dp {pp * dp}")

    def layers_pass(x):  # [S, W, D]
        return _pp_slot_layers(
            params, state.k, state.v, x, state.lengths, active, mesh, width=w,
            block_fn=lambda c, lp, ck, cv, ln, ac:
                _verify_block(c, lp, cfg, ck, cv, ln, active=ac))

    nk, nv, lengths, greedy, n_acc = spec_driver(
        params, state.k, state.v, state.lengths, window, draft_len, active,
        cfg, rng, temperature, top_p, top_k, layers_pass=layers_pass)
    return DecodeState(k=nk, v=nv, lengths=lengths), greedy, n_acc


def propose_ngram_device(hist: jax.Array, hlen: jax.Array, last: jax.Array,
                         k: int, nmax: int) -> Tuple[jax.Array, jax.Array]:
    """On-device prompt-lookup proposal (the host-side _propose_ngram, jittable
    so it can run INSIDE a fused burst): for each slot, find the most recent
    earlier occurrence of the trailing n-gram (longest n <= nmax first) in the
    slot's token history and propose the k tokens that followed it.

    hist [S,L] int32 (prompt + emitted tokens), hlen [S] valid length,
    last [S] == hist[hlen-1]. Returns (window [S,k+1], draft_len [S])."""
    s_n, L = hist.shape
    best_start = jnp.zeros((s_n,), jnp.int32)
    best_n = jnp.zeros((s_n,), jnp.int32)
    for n in range(nmax, 0, -1):  # static unroll: longest n wins
        tail = jax.vmap(
            lambda h, e: jax.lax.dynamic_slice(h, (jnp.maximum(e - n, 0),), (n,))
        )(hist, hlen)  # [S, n]
        eq = jnp.ones((s_n, L - n), bool)
        for i in range(n):
            eq &= hist[:, i:L - n + i] == tail[:, i:i + 1]
        j = jnp.arange(L - n)[None, :]
        eq &= j < (hlen - n)[:, None]  # strictly before the tail's own start
        start = jnp.max(jnp.where(eq, j, -1), axis=1)  # most recent occurrence
        # a match whose continuation is empty (occurrence butts against the
        # tail) is useless — fall through to a shorter n, like the host
        # proposer's `if cont:` retry
        found = eq.any(axis=1) & (hlen - (start + n) > 0)
        pick = found & (best_n == 0)
        best_start = jnp.where(pick, start.astype(jnp.int32), best_start)
        best_n = jnp.where(pick, n, best_n)
    cont = jnp.minimum(best_start + best_n, L - k)  # continuation start, clamped
    drafts = jax.vmap(
        lambda h, s: jax.lax.dynamic_slice(h, (s,), (k,)))(hist, cont)  # [S,k]
    avail = jnp.clip(hlen - (best_start + best_n), 0, k)
    draft_len = jnp.where(best_n > 0, avail, 0).astype(jnp.int32)
    keep = jnp.arange(k)[None, :] < draft_len[:, None]
    window = jnp.zeros((s_n, k + 1), jnp.int32)
    window = window.at[:, 0].set(last)
    window = window.at[:, 1:].set(jnp.where(keep, drafts, 0))
    return window, draft_len


def spec_multi_impl(params, state, hist, hlen, active, cfg, rngs, temperature,
                    top_p, top_k, m, k, nmax, proposer, layer_fn_for,
                    advance_state):
    """Layout-generic fused speculation: m propose->verify->accept windows
    chained in one lax.scan. The cache layout differs only in
    layer_fn_for(state) (the verify layer adapter) and
    advance_state(state, nk, nv, lengths) (how the storage threads forward)."""

    def body(carry, rng):
        st, h, hl, last = carry
        window, draft_len = proposer(h, hl, last, k, nmax)
        draft_len = jnp.where(temperature > 0, 0, draft_len)
        nk, nv, lengths, greedy, n_acc = spec_driver(
            params, st.k, st.v, st.lengths, window, draft_len, active,
            cfg, rng, temperature, top_p, top_k, layer_fn_for(st))
        st = advance_state(st, nk, nv, lengths)
        adv = jnp.where(active, n_acc + 1, 0)
        rows = jnp.arange(h.shape[0])
        for t in range(k + 1):  # static: scatter this window's emitted tokens
            pos = jnp.clip(hl + t, 0, h.shape[1] - 1)
            h = h.at[rows, pos].set(
                jnp.where(t < adv, greedy[:, t], h[rows, pos]))
        new_last = jnp.where(
            adv > 0,
            jnp.take_along_axis(
                greedy, jnp.maximum(adv - 1, 0)[:, None], axis=1)[:, 0],
            last)
        return (st, h, hl + adv, new_last), (greedy, n_acc, draft_len)

    last = jnp.take_along_axis(
        hist, jnp.maximum(hlen - 1, 0)[:, None], axis=1)[:, 0]
    (state, _, _, _), (toks_m, acc_m, drafted_m) = jax.lax.scan(
        body, (state, hist, hlen, last), rngs)
    return state, toks_m, acc_m, drafted_m


@functools.partial(
    jax.jit, static_argnames=("cfg", "m", "k", "nmax", "propose_fn"),
    donate_argnames=("state",))
def spec_multi(
    params,
    state: DecodeState,
    hist: jax.Array,  # [S, max_len] int32 — prompt + emitted tokens per slot
    hlen: jax.Array,  # [S] int32 — valid history length
    active: jax.Array,  # [S] bool — FIXED for the whole burst
    cfg: ModelConfig,
    rngs: jax.Array,  # [m] stacked PRNG keys
    temperature: jax.Array,
    top_p: jax.Array,
    top_k: jax.Array,
    m: int,
    k: int,
    nmax: int,
    propose_fn=None,  # test seam: (hist, hlen, last, k, nmax) -> (window, dlen)
):
    """m fused speculative windows per host sync: propose (on-device n-gram
    lookup) -> verify forward -> accept, chained in a lax.scan — composing
    vLLM's multi-step scheduling with prompt-lookup speculation. Per sync the
    engine emits between m and m*(k+1) tokens. Greedy slots speculate;
    temperature>0 slots ride along sampling one token per window.

    Returns (state, toks_m [m,S,k+1], acc_m [m,S], drafted_m [m,S])."""
    return spec_multi_impl(
        params, state, hist, hlen, active, cfg, rngs, temperature, top_p,
        top_k, m, k, nmax, propose_fn or propose_ngram_device,
        lambda st: lambda x, lp, ck, cv: _verify_block(
            x, lp, cfg, ck, cv, st.lengths, active=active),
        lambda st, nk, nv, lengths: DecodeState(k=nk, v=nv, lengths=lengths))


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnames=("state",))
def decode_multi(
    params,
    state: DecodeState,
    tokens: jax.Array,  # [slots] int32 — last sampled token per slot
    active: jax.Array,  # [slots] bool — FIXED for the whole burst
    cfg: ModelConfig,
    rngs: jax.Array,  # [K] stacked PRNG keys, one per step
    temperature: jax.Array,  # [slots] f32
    top_p: jax.Array,  # [slots] f32
    top_k: jax.Array,  # [slots] i32
    steps_left: jax.Array,  # [slots] int32 — per-slot step budget within K
) -> Tuple[DecodeState, jax.Array]:
    """K fused decode+sample steps per host sync (vLLM multi-step scheduling).

    Returns (state, tokens_k [K, slots]). ``steps_left`` makes the burst
    barrier-free: a slot near its max_tokens/KV budget stops advancing at its
    own limit (step t treats it as inactive) instead of capping K for the
    whole batch — so one short request no longer collapses everyone's burst.
    Slots that hit EOS mid-burst keep decoding (the host discards their tail);
    only the first steps_left[s] rows of tokens_k are meaningful for slot s.
    """
    def body(carry, xs):
        rng, t = xs
        st, toks = carry
        act_t = active & (t < steps_left)
        st, logits = decode_step(params, st, toks, act_t, cfg)
        nxt = sampling.sample(rng, logits, temperature, top_p, top_k)
        nxt = jnp.where(act_t, nxt, toks).astype(jnp.int32)
        return (st, nxt), nxt

    (state, _), toks_k = jax.lax.scan(
        body, (state, tokens.astype(jnp.int32)),
        (rngs, jnp.arange(rngs.shape[0], dtype=jnp.int32)))
    return state, toks_k


# ------------------------------------------------------- pipeline-parallel decode

def _pp_schedule(x_mb, kv, step_mb, *, axis_name: str = "pp"):
    """Shared GPipe-style inference tick skeleton (call inside a shard_map
    manual over `axis_name`): M microbatches through pp stages, activations
    hopping stage->stage via ppermute while stages work different microbatches.

    step_mb(x_in, kv, jc, valid) -> (h, kv): one stage's work on its CURRENT
    microbatch jc (clipped; `valid` is False on fill/drain bubble ticks — the
    callback must discard or redirect its cache writes then). kv is an
    arbitrary pytree threaded through the scan (slot caches, block pools).
    Returns (outs [M, ...] — the last stage's outputs psum-broadcast to every
    stage — and the final kv). One implementation so the slot-decode,
    spec-verify, and paged-decode pp variants cannot drift apart.
    """
    from ray_tpu.parallel.sharding import vary_like

    pp_size = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    ticks = m + pp_size - 1
    fwd = [(i, i + 1) for i in range(pp_size - 1)]

    def tick(carry, t):
        x_recv, kv, outs = carry
        j = t - stage
        jc = jnp.clip(j, 0, m - 1)
        valid = (j >= 0) & (j < m)
        x_in = jnp.where(stage == 0, x_mb[jc], x_recv)
        h, kv = step_mb(x_in, kv, jc, valid)
        out_j = t - (pp_size - 1)
        outs_new = jax.lax.dynamic_update_index_in_dim(
            outs, h, jnp.clip(out_j, 0, m - 1), 0)
        outs = jnp.where((stage == pp_size - 1) & (out_j >= 0), outs_new, outs)
        x_send = jax.lax.ppermute(h, axis_name, fwd) if pp_size > 1 else h
        return (x_send, kv, outs), None

    def _vary(z):
        return vary_like(z, x_mb, extra=(axis_name,))

    buf0 = _vary(jnp.zeros_like(x_mb[0]))
    outs0 = _vary(jnp.zeros_like(x_mb))
    (_, kv, outs), _ = jax.lax.scan(tick, (buf0, kv, outs0), jnp.arange(ticks))
    outs = jax.lax.psum(
        jnp.where(stage == pp_size - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs, kv


def _pp_shard_map(inner, params_layers, mesh: Mesh, arrays):
    """Shared shard_map scaffolding for every pp inference variant: layers
    manual over "pp" (stage-stacked leading axis), k/v over ("pp", dp), every
    other array over dp on its slot axis; dp joins the manual set only when
    the mesh names it. inner(layers_local, *local_arrays) -> (outs, k, v)."""
    from ray_tpu.parallel.sharding import manual_axes

    layer_specs = jax.tree_util.tree_map(lambda _: P("pp"), params_layers)
    dp_ax = "dp" if "dp" in mesh.shape else None
    manual = {"pp", "dp"} if dp_ax else {"pp"}
    n_rest = len(arrays) - 2  # beyond k and v
    mapped = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(layer_specs, P("pp", dp_ax), P("pp", dp_ax))
                 + (P(dp_ax),) * n_rest,
        out_specs=(P(dp_ax), P("pp", dp_ax), P("pp", dp_ax)),
        axis_names=manual,
    )
    with manual_axes(*manual):
        return mapped(params_layers, *arrays)


def _pp_slot_layers(params, k0, v0, x, lengths, active, mesh: Mesh, *,
                    width: int, block_fn):
    """Slot-cache layer pass through the pp schedule, shared by decode
    (width=1) and spec verify (width=W). block_fn(h, lp, ck, cv, mb_lengths,
    mb_active) -> (h, ck, cv) on one microbatch's slot-sliced cache; bubble
    ticks' cache writes are discarded wholesale by the valid mask."""
    m = mesh.shape["pp"]

    def inner(layers_local, k_local, v_local, x_local, lengths, active_i):
        s_l = x_local.shape[0]  # this dp replica's slot count
        smb = s_l // m
        x_mb = x_local.reshape(m, smb, width, x_local.shape[-1])

        def step_mb(x_in, kv, jc, valid):
            k, v = kv
            mb_lengths = jax.lax.dynamic_slice(lengths, (jc * smb,), (smb,))
            mb_active = jax.lax.dynamic_slice(active_i, (jc * smb,), (smb,)) > 0
            k_mb = jax.lax.dynamic_slice_in_dim(k, jc * smb, smb, axis=1)
            v_mb = jax.lax.dynamic_slice_in_dim(v, jc * smb, smb, axis=1)

            def lbody(c, xs):
                lp, ck, cv = xs
                h, ck, cv = block_fn(c, lp, ck, cv, mb_lengths, mb_active)
                return h, (ck, cv)

            h, (nk_mb, nv_mb) = jax.lax.scan(lbody, x_in,
                                             (layers_local, k_mb, v_mb))
            k_new = jax.lax.dynamic_update_slice_in_dim(k, nk_mb, jc * smb,
                                                        axis=1)
            v_new = jax.lax.dynamic_update_slice_in_dim(v, nv_mb, jc * smb,
                                                        axis=1)
            return h, (jnp.where(valid, k_new, k), jnp.where(valid, v_new, v))

        outs, (k, v) = _pp_schedule(x_mb, (k_local, v_local), step_mb)
        return outs.reshape(s_l, width, outs.shape[-1]), k, v

    return _pp_shard_map(inner, params["layers"], mesh,
                         (k0, v0, x, lengths, active.astype(jnp.int32)))


def decode_step_pp(params, state: DecodeState, tokens: jax.Array, active: jax.Array,
                   cfg: ModelConfig, mesh: Mesh):
    """Decode with the layer stack split across the "pp" mesh axis, microbatched
    over slots (reference: the reference passes pipeline_parallel_size to vLLM,
    vllm_models.py:125-139; here the schedule is native).

    Layout: params["layers"] leaves and the KV cache are sharded P("pp") on the
    layer axis, so each stage holds L/pp layers and THEIR cache — the point of
    inference PP is fitting a model + cache that one device group can't. Slots
    first shard over dp replicas (cache slot axis is P("dp"); each replica's
    slots are a contiguous range), then split into pp microbatches within the
    replica; activations hop stage→stage via ppermute while stages work
    different microbatches (GPipe-style fill/drain per step). tp and ep stay
    GSPMD auto axes inside the stage. Embedding/head run outside in auto mode.
    """
    pp = mesh.shape["pp"]
    dp = mesh.shape.get("dp", 1)
    s = tokens.shape[0]
    if s % (pp * dp):
        raise ValueError(f"max_num_seqs {s} must be divisible by pp*dp {pp * dp}")

    x = params["embed"].astype(cfg.activation_dtype)[tokens[:, None]]  # [S,1,D]
    h, nk, nv = _pp_slot_layers(
        params, state.k, state.v, x, state.lengths, active, mesh, width=1,
        block_fn=lambda c, lp, ck, cv, ln, ac:
            _decode_block(c, lp, cfg, ck, cv, ln, ac))

    h = llama.rms_norm(h, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("sld,dv->slv", h, _qw(head, cfg.activation_dtype))[:, 0]
    lengths = jnp.where(active, state.lengths + 1, state.lengths)
    return DecodeState(k=nk, v=nv, lengths=lengths), logits.astype(jnp.float32)


# ------------------------------------------------------------------------- sampler

@jax.jit
def sample_tokens(rng, logits, temperature, top_p, top_k):
    return sampling.sample(rng, logits, temperature, top_p, top_k)
