"""ray_tpu.llm — LLM serving + batch inference, TPU-native.

Capability parity with the reference's python/ray/llm/ (SURVEY.md §2.7): an
``LLMEngine`` ABC with a JAX engine instead of vLLM (slot-based continuous
batching, device-resident KV cache, TP over ICI via pjit), an ``LLMServer``
Serve deployment exposing OpenAI-compatible chat/completions, a multi-model
router (``build_openai_app``), and a Ray-Data batch-inference ``Processor``.
"""
from .config import LLMConfig, SamplingParams, SpecConfig
from .engine import JaxLLMEngine, LLMEngine, RequestOutput
from .server import LLMServer, PDRouter, build_openai_app, build_pd_openai_app
from .batch import (
    ChatTemplateStage,
    DetokenizeStage,
    HttpRequestStage,
    LLMEngineStage,
    PrepareImageStage,
    Processor,
    TokenizeStage,
    build_llm_processor,
)

__all__ = [
    "LLMConfig",
    "SamplingParams",
    "SpecConfig",
    "LLMEngine",
    "JaxLLMEngine",
    "RequestOutput",
    "LLMServer",
    "PDRouter",
    "build_openai_app",
    "build_pd_openai_app",
    "Processor",
    "build_llm_processor",
    "ChatTemplateStage",
    "TokenizeStage",
    "DetokenizeStage",
    "HttpRequestStage",
    "LLMEngineStage",
    "PrepareImageStage",
]
