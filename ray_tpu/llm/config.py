"""LLM engine/server configuration.

Capability parity: reference python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:40 (``VLLMEngineConfig`` — model id, engine_kwargs, TP/PP degrees
:125-139 mapped to resource bundles). Here the engine is JAX, so parallelism
degrees map to mesh axes instead of placement-group bundles.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Union


def _flag(name: str):
    from ray_tpu.config import flag

    return flag(name)


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling controls (reference vLLM SamplingParams surface)."""

    max_tokens: int = 64
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    stop_token_ids: Optional[List[int]] = None
    seed: Optional[int] = None

    def __post_init__(self):
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")


@dataclasses.dataclass
class SpecConfig:
    """First-class speculative-decoding mode (reference vLLM SpeculativeConfig).

    Composes with fused multi-step decode: each fused window proposes/verifies
    ``num_tokens`` drafts on device, so per host sync the engine emits between
    K and K*(num_tokens+1) tokens. ``method`` is the proposer; only "ngram"
    (prompt lookup) is implemented."""

    num_tokens: int = 4
    method: str = "ngram"
    ngram_max: int = 3  # longest trailing n-gram the proposer matches

    def __post_init__(self):
        if self.num_tokens < 1:
            raise ValueError("SpecConfig.num_tokens must be >= 1")
        if self.ngram_max < 1:
            raise ValueError("SpecConfig.ngram_max must be >= 1")


@dataclasses.dataclass
class LLMConfig:
    """Model + engine knobs for ``JaxLLMEngine`` / ``LLMServer``.

    ``model_id`` is the served-model name (OpenAI ``model`` field); ``model_source``
    picks the ray_tpu.models config (e.g. "byte-tiny", "llama3-8b") or is a
    ModelConfig instance directly.
    """

    model_id: str = "llama"
    model_source: Union[str, Any] = "byte-tiny"
    # engine (defaults env-overridable via the config registry)
    max_num_seqs: int = dataclasses.field(  # decode slots (batching width)
        default_factory=lambda: _flag("llm_max_num_seqs"))
    max_model_len: int = dataclasses.field(  # KV capacity per slot
        default_factory=lambda: _flag("llm_max_model_len"))
    prefill_buckets: Optional[List[int]] = None  # pad-to lengths; default powers of 2
    dtype: str = "bfloat16"
    # KV layout (reference: vLLM PagedAttention block tables):
    #   "slot"  — max_model_len tokens reserved per slot up front
    #   "paged" — one shared block pool; per-slot block tables; allocation per
    #             kv_block_size tokens, so HBM caps TOTAL tokens, not slots
    kv_layout: str = "slot"
    kv_block_size: int = 16
    # total pool blocks; None = same token capacity as the slot layout
    num_kv_blocks: Optional[int] = None
    # share full prompt blocks across requests (vLLM automatic prefix caching)
    enable_prefix_caching: bool = True
    # prompts longer than this prefill in chunks of this many tokens (peak
    # activation memory = one chunk); None = whole-prompt prefill
    prefill_chunk: Optional[int] = None
    # fused decode burst: run this many decode+sample iterations on-device per
    # host sync (lax.scan; vLLM multi-step scheduling). >1 amortizes the
    # per-step host round trip — decisive over a network tunnel, a few percent
    # on local chips — at the cost of K-token streaming granularity and up to
    # K-1 wasted steps after a mid-burst EOS. None (the default) resolves
    # RAY_TPU_LLM_FUSED_STEPS, whose 0 default auto-tunes K from the measured
    # host round trip vs device step time — fused decode is the standard
    # engine mode, not an opt-in
    num_decode_steps: Optional[int] = None
    # speculative decoding (reference: vLLM ngram / prompt-lookup): propose up
    # to this many draft tokens per step by matching the trailing n-gram
    # against earlier context, verify all of them in ONE forward pass, accept
    # the longest matching prefix + a bonus token. Greedy (temperature=0)
    # requests only; slot KV layout; dense models. 0 = off
    num_speculative_tokens: int = 0
    speculative_method: str = "ngram"
    ngram_prompt_lookup_max: int = 3
    # first-class speculation mode: a SpecConfig (or its dict form) here
    # overrides the three scalar knobs above, which remain as the resolved
    # values engine code reads
    speculative: Optional[Union["SpecConfig", Dict[str, Any]]] = None
    # weight-only quantization (reference: vLLM quantization engine_kwargs):
    #   None   — serve in `dtype` as loaded
    #   "int8" — per-output-channel int8 weights, bf16 activations (W8A16):
    #            halves the weight bytes every decode step streams from HBM
    quantization: Optional[str] = None
    # parallelism: mesh axes for the in-process device mesh
    tensor_parallel_size: int = 1
    data_parallel_size: int = 1
    expert_parallel_size: int = 1  # MoE models: experts shard over "ep"
    # layer stack split across pp stages with microbatched decode (reference
    # passes pipeline_parallel_size to vLLM, vllm_models.py:125-139)
    pipeline_parallel_size: int = 1
    # serving
    tokenizer: str = "byte"  # "byte" | "hf:<name-or-path>"
    accelerator_type: Optional[str] = None
    deployment_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    engine_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.speculative is not None:
            sp = self.speculative
            if isinstance(sp, dict):
                sp = SpecConfig(**sp)
                self.speculative = sp
            self.num_speculative_tokens = sp.num_tokens
            self.speculative_method = sp.method
            self.ngram_prompt_lookup_max = sp.ngram_max

    def resolve_decode_steps(self) -> int:
        """Configured fused burst width: explicit value, else the
        RAY_TPU_LLM_FUSED_STEPS flag. 0 means auto-tune (engine-side)."""
        if self.num_decode_steps is not None:
            return max(0, int(self.num_decode_steps))
        return max(0, int(_flag("llm_fused_steps")))

    def resolve_model_config(self):
        from ray_tpu.models.config import ModelConfig, get_config

        if isinstance(self.model_source, ModelConfig):
            return self.model_source
        from ray_tpu.models import checkpoint as ckpt_io

        if ckpt_io.looks_like_checkpoint_dir(self.model_source):
            # a local HF-layout checkpoint dir: architecture from its config.json,
            # weights loaded by the engine at start() (vllm_engine.py:180 contract)
            return ckpt_io.config_from_hf(self.model_source, **self.engine_kwargs)
        return get_config(self.model_source, **self.engine_kwargs)

    def resolve_tokenizer_name(self) -> str:
        """Default the tokenizer to the checkpoint's own HF tokenizer when the
        model is a checkpoint dir that ships one."""
        if self.tokenizer != "byte":
            return self.tokenizer
        import os

        from ray_tpu.models import checkpoint as ckpt_io

        if ckpt_io.looks_like_checkpoint_dir(self.model_source) and any(
            os.path.exists(os.path.join(self.model_source, f))
            for f in ("tokenizer.json", "tokenizer_config.json")
        ):
            return f"hf:{self.model_source}"
        return self.tokenizer

    def buckets(self) -> List[int]:
        if self.prefill_buckets:
            return sorted(self.prefill_buckets)
        out, b = [], 16
        while b < self.max_model_len:
            out.append(b)
            b *= 2
        out.append(self.max_model_len)
        return out
