"""Jittable token sampling with per-slot parameters.

Continuous batching means every decode step samples for all active slots at once,
each with its own temperature/top-p/top-k — so the sampler is a single vectorized
jit-compatible function over [B, V] logits (no per-request Python).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e10


def _apply_top_k(logits: jax.Array, top_k: jax.Array) -> jax.Array:
    """Mask logits outside each row's top-k. top_k[B] int32, 0 = disabled."""
    v = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]  # descending
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, v), v)
    kth = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    return jnp.where(logits < kth, NEG_INF, logits)


def _apply_top_p(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filtering per row. top_p[B] float, 1.0 = disabled."""
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob (exclusive) < top_p; always keep the first
    keep = (cum - probs) < top_p[:, None]
    cutoff = jnp.where(keep, sorted_logits, jnp.inf).min(axis=-1)
    return jnp.where(logits < cutoff[:, None], NEG_INF, logits)


def sample(
    rng: jax.Array,
    logits: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    top_k: jax.Array,
) -> jax.Array:
    """logits [B, V] f32 -> token ids [B]. temperature==0 rows sample greedily."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp
    scaled = _apply_top_k(scaled, top_k)
    scaled = _apply_top_p(scaled, top_p)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
