"""Lazy g++ build of the native libraries, cached by source mtime."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict = {}


class NativeBuildError(RuntimeError):
    pass


def load_library(stem: str, extra_flags=()) -> ctypes.CDLL:
    """Compile <stem>.cc to lib<stem>.so if stale, then dlopen it."""
    with _LOCK:
        if stem in _CACHE:
            return _CACHE[stem]
        src = os.path.join(_DIR, f"{stem}.cc")
        so = os.path.join(_DIR, f"lib{stem}.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            tmp = so + f".tmp.{os.getpid()}"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src,
                   "-lpthread", "-lrt", *extra_flags]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(f"native build failed:\n{proc.stderr}")
            os.replace(tmp, so)  # atomic vs concurrent builders
        lib = ctypes.CDLL(so)
        _CACHE[stem] = lib
        return lib
