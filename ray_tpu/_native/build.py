"""Lazy g++ build of the native libraries, cached by source mtime.

Sanitizer seams (reference: ray's BUILD.bazel asan/tsan configs + ci/ sanitizer
jobs): set RAY_TPU_SANITIZE=address|thread|undefined to rebuild every native
library under that sanitizer in a separate artifact (lib<stem>.asan.so, ...),
so an instrumented test run never poisons the cached production .so.

ASan/TSan caveat: dlopen-ing an instrumented .so into an uninstrumented python
requires the sanitizer runtime loaded FIRST —
    LD_PRELOAD=$(g++ -print-file-name=libasan.so) RAY_TPU_SANITIZE=address pytest ...
load_library detects the missing preload and raises with that exact command.
The primary sanitizer path (and what ci.yml runs) is the standalone stress
binary shm_store_stress.cc, which needs no preload.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict = {}

_SANITIZERS = {
    "address": ("asan", ["-fsanitize=address", "-fno-omit-frame-pointer", "-g"]),
    "thread": ("tsan", ["-fsanitize=thread", "-fno-omit-frame-pointer", "-g"]),
    "undefined": ("ubsan", ["-fsanitize=undefined", "-g"]),
}


class NativeBuildError(RuntimeError):
    pass


def sanitizer_mode() -> str:
    return os.environ.get("RAY_TPU_SANITIZE", "")


def load_library(stem: str, extra_flags=()) -> ctypes.CDLL:
    """Compile <stem>.cc to lib<stem>.so if stale, then dlopen it."""
    sanitize = sanitizer_mode()
    suffix, san_flags = "", []
    if sanitize:
        if sanitize not in _SANITIZERS:
            raise NativeBuildError(
                f"RAY_TPU_SANITIZE={sanitize!r}: expected one of {sorted(_SANITIZERS)}")
        tag, san_flags = _SANITIZERS[sanitize]
        suffix = f".{tag}"
    key = stem + suffix
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
        src = os.path.join(_DIR, f"{stem}.cc")
        so = os.path.join(_DIR, f"lib{stem}{suffix}.so")
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            tmp = so + f".tmp.{os.getpid()}"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src,
                   "-lpthread", "-lrt", *san_flags, *extra_flags]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(f"native build failed:\n{proc.stderr}")
            os.replace(tmp, so)  # atomic vs concurrent builders
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            if sanitize in ("address", "thread"):
                rt_lib = f"lib{'asan' if sanitize == 'address' else 'tsan'}.so"
                raise NativeBuildError(
                    f"dlopen of the {sanitize}-instrumented library failed ({e}); "
                    f"the sanitizer runtime must be loaded first:\n"
                    f"  LD_PRELOAD=$(g++ -print-file-name={rt_lib}) "
                    f"RAY_TPU_SANITIZE={sanitize} <your command>") from e
            raise
        _CACHE[key] = lib
        return lib
