"""Native (C++) plane of ray_tpu.

The reference's performance-critical runtime is C++ (SURVEY.md §2.1). Here the
native pieces live as C-ABI shared libraries loaded via ctypes (no pybind11 in
the image), built lazily by g++ with the compiled .so cached next to the source.
"""
from .build import load_library

__all__ = ["load_library"]
