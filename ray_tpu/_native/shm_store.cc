// Plasma-equivalent shared-memory object arena (C++, native plane).
//
// Capability parity: reference plasma store (src/ray/object_manager/plasma/store.h:55,
// plasma_allocator.h over dlmalloc, obj_lifecycle_mgr.h) — a per-node shared-memory
// region where any process creates/seals objects and any process maps them zero-copy.
// Designed differently from plasma: no store daemon and no socket protocol. The arena
// is one POSIX shm segment containing a boundary-tag heap plus an open-addressing
// object table, guarded by a robust process-shared mutex — so create/seal/get are
// nanosecond-scale library calls (plasma pays a round-trip through the store process;
// see plasma.fbs wire protocol). Crash-safety: the robust mutex recovers the lock from
// dead owners; unsealed objects from dead writers are garbage-collected by sweep().
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O2 -shared -fPIC -o libshm_store.so shm_store.cc -lpthread -lrt

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055534852ULL;  // "RTPUSHR"
constexpr uint32_t kAlign = 64;                   // cache-line align allocations
constexpr uint32_t kIdLen = 20;                   // ObjectID bytes

// Object table entry states.
enum : uint32_t {
  kEmpty = 0,
  kAllocated = 1,  // created, being written
  kSealed = 2,     // immutable, readable
  kTombstone = 3,  // deleted (keeps probe chains alive)
  kCondemned = 4,  // deleted while readers hold pins; freed on last unpin
};

struct Entry {
  uint8_t id[kIdLen];
  uint32_t state;
  uint32_t owner_pid;   // creator, for dead-writer GC of unsealed objects
  uint64_t offset;      // data offset from arena base
  uint64_t size;
  uint32_t pin_count;   // readers holding zero-copy views (delete defers on >0)
  uint32_t flags;       // bit0: is_error frame (survives a head restart)
};

// Free block header (boundary-tag list threaded through the heap).
struct FreeBlock {
  uint64_t size;       // total block size including header
  uint64_t next;       // offset of next free block (0 = end)
};

struct Header {
  uint64_t magic;
  uint64_t total_size;
  uint64_t table_offset;
  uint64_t table_cap;      // power of two
  uint64_t heap_offset;
  uint64_t free_head;      // offset of first free block (0 = none)
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t peak_used;
  pthread_mutex_t mutex;
};

struct Handle {
  void* base;
  uint64_t size;
  int owner;  // created (vs attached)
};

inline Header* H(Handle* h) { return reinterpret_cast<Header*>(h->base); }
inline Entry* table(Handle* h) {
  return reinterpret_cast<Entry*>(static_cast<char*>(h->base) + H(h)->table_offset);
}

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t x = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    x ^= id[i];
    x *= 1099511628211ULL;
  }
  return x;
}

void heap_rebuild(Handle* h);

int lock(Handle* h) {
  int rc = pthread_mutex_lock(&H(h)->mutex);
  if (rc == EOWNERDEAD) {
    // Previous holder was killed mid-critical-section: the free list may be
    // half-spliced. The object table is the authoritative record (entry state is
    // committed last), so rebuild the heap's free list from the table before
    // anyone walks it.
    heap_rebuild(h);
    pthread_mutex_consistent(&H(h)->mutex);
    rc = 0;
  }
  return rc;
}
void unlock(Handle* h) { pthread_mutex_unlock(&H(h)->mutex); }

Entry* find(Handle* h, const uint8_t* id, int for_insert) {
  Header* hd = H(h);
  Entry* t = table(h);
  uint64_t mask = hd->table_cap - 1;
  uint64_t i = hash_id(id) & mask;
  Entry* first_tomb = nullptr;
  for (uint64_t probes = 0; probes <= mask; probes++, i = (i + 1) & mask) {
    Entry* e = &t[i];
    if (e->state == kEmpty) {
      if (for_insert) return first_tomb ? first_tomb : e;
      return nullptr;
    }
    if (e->state == kTombstone) {
      if (for_insert && !first_tomb) first_tomb = e;
      continue;
    }
    if (memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return for_insert ? first_tomb : nullptr;
}

// Best-fit allocation from the free list. Returns data offset or 0.
uint64_t heap_alloc(Handle* h, uint64_t want) {
  Header* hd = H(h);
  want = align_up(want, kAlign);
  uint64_t best = 0, best_prev = 0, best_size = ~0ULL;
  uint64_t prev = 0, cur = hd->free_head;
  char* base = static_cast<char*>(h->base);
  while (cur) {
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(base + cur);
    if (fb->size >= want && fb->size < best_size) {
      best = cur;
      best_prev = prev;
      best_size = fb->size;
      if (fb->size == want) break;
    }
    prev = cur;
    cur = fb->next;
  }
  if (!best) return 0;
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(base + best);
  uint64_t remain = fb->size - want;
  uint64_t next = fb->next;
  if (remain >= kAlign + sizeof(FreeBlock)) {
    uint64_t rest = best + want;
    FreeBlock* rb = reinterpret_cast<FreeBlock*>(base + rest);
    rb->size = remain;
    rb->next = next;
    next = rest;
  } else {
    want = fb->size;  // absorb the sliver
  }
  if (best_prev) {
    reinterpret_cast<FreeBlock*>(base + best_prev)->next = next;
  } else {
    hd->free_head = next;
  }
  hd->used_bytes += want;
  if (hd->used_bytes > hd->peak_used) hd->peak_used = hd->used_bytes;
  return best;
}

// Free with address-ordered insert + coalescing of adjacent blocks.
void heap_free(Handle* h, uint64_t off, uint64_t size) {
  Header* hd = H(h);
  size = align_up(size, kAlign);
  char* base = static_cast<char*>(h->base);
  uint64_t prev = 0, cur = hd->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(base + cur)->next;
  }
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(base + off);
  nb->size = size;
  nb->next = cur;
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(base + prev);
    pb->next = off;
    if (prev + pb->size == off) {  // merge prev+new
      pb->size += nb->size;
      pb->next = nb->next;
      nb = pb;
      off = prev;
    }
  } else {
    hd->free_head = off;
  }
  if (nb->next && off + nb->size == nb->next) {  // merge new+next
    FreeBlock* xb = reinterpret_cast<FreeBlock*>(base + nb->next);
    nb->size += xb->size;
    nb->next = xb->next;
  }
  hd->used_bytes -= size;
}

// Reconstruct the free list from the object table after a lock owner died
// mid-heap-op. Live extents = entries in Allocated/Sealed/Condemned state with
// in-bounds offsets; everything else in [heap_offset, total_size) becomes free.
// Entries with corrupt extents (half-written before the state commit) are dropped.
void heap_rebuild(Handle* h) {
  Header* hd = H(h);
  Entry* t = table(h);
  char* base = static_cast<char*>(h->base);
  uint64_t heap_lo = hd->heap_offset, heap_hi = hd->total_size;

  // collect + validate live extents
  uint64_t n_live = 0;
  for (uint64_t i = 0; i < hd->table_cap; i++) {
    Entry* e = &t[i];
    if (e->state != kAllocated && e->state != kSealed && e->state != kCondemned) continue;
    uint64_t sz = align_up(e->size ? e->size : 1, kAlign);
    // overflow-safe: align_up can wrap to 0, a garbage size can exceed the heap
    // (making heap_hi - sz underflow), offset+sz can wrap past heap_hi
    if (sz == 0 || sz > heap_hi - heap_lo || e->offset < heap_lo ||
        e->offset > heap_hi - sz || (e->offset & (kAlign - 1))) {
      e->state = kTombstone;  // half-written entry from the dead owner
      if (hd->num_objects) hd->num_objects--;
      continue;
    }
    n_live++;
  }
  // collect extent (start, size) pairs, then qsort — this runs under the
  // cross-process mutex, so it must stay O(n log n) even for ~1M-entry tables
  uint64_t* starts = static_cast<uint64_t*>(malloc((n_live ? n_live : 1) * 2 * sizeof(uint64_t)));
  if (!starts) {
    // can't rebuild without scratch: drop the (possibly corrupt) free list
    // entirely — allocations fail OOM-style until restart, but nothing walks
    // a half-spliced list
    hd->free_head = 0;
    return;
  }
  uint64_t m = 0;
  for (uint64_t i = 0; i < hd->table_cap; i++) {
    Entry* e = &t[i];
    if (e->state != kAllocated && e->state != kSealed && e->state != kCondemned) continue;
    starts[m * 2] = e->offset;
    starts[m * 2 + 1] = align_up(e->size ? e->size : 1, kAlign);
    m++;
  }
  qsort(starts, m, 2 * sizeof(uint64_t), [](const void* a, const void* b) {
    uint64_t x = *static_cast<const uint64_t*>(a);
    uint64_t y = *static_cast<const uint64_t*>(b);
    return x < y ? -1 : (x > y ? 1 : 0);
  });
  // rebuild address-ordered free list from the gaps
  uint64_t used = 0;
  uint64_t cursor = heap_lo;
  uint64_t prev_free = 0;
  hd->free_head = 0;
  for (uint64_t k = 0; k <= m; k++) {
    uint64_t gap_end = (k < m) ? starts[k * 2] : heap_hi;
    if (gap_end > cursor && gap_end - cursor >= kAlign) {
      FreeBlock* fb = reinterpret_cast<FreeBlock*>(base + cursor);
      fb->size = gap_end - cursor;
      fb->next = 0;
      if (prev_free) {
        reinterpret_cast<FreeBlock*>(base + prev_free)->next = cursor;
      } else {
        hd->free_head = cursor;
      }
      prev_free = cursor;
    }
    if (k < m) {
      uint64_t ext_end = starts[k * 2] + starts[k * 2 + 1];
      used += starts[k * 2 + 1];
      if (ext_end > cursor) cursor = ext_end;
    }
  }
  hd->used_bytes = used;
  free(starts);
}

}  // namespace

extern "C" {

// Create + initialize an arena. Returns handle or null.
void* rt_store_create(const char* name, uint64_t total_size, uint64_t table_cap) {
  // round table_cap up to a power of two
  uint64_t cap = 1;
  while (cap < table_cap) cap <<= 1;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(total_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hd = reinterpret_cast<Header*>(base);
  memset(hd, 0, sizeof(Header));
  hd->total_size = total_size;
  hd->table_offset = align_up(sizeof(Header), kAlign);
  hd->table_cap = cap;
  hd->heap_offset = align_up(hd->table_offset + cap * sizeof(Entry), kAlign);
  if (hd->heap_offset + kAlign + sizeof(FreeBlock) > total_size) {
    munmap(base, total_size);
    shm_unlink(name);
    return nullptr;  // table does not leave room for a heap
  }
  memset(static_cast<char*>(base) + hd->table_offset, 0, cap * sizeof(Entry));
  // one big free block
  hd->free_head = hd->heap_offset;
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(static_cast<char*>(base) + hd->heap_offset);
  fb->size = total_size - hd->heap_offset;
  fb->next = 0;
  hd->used_bytes = 0;
  hd->num_objects = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hd->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  hd->magic = kMagic;  // last: marks init complete for attachers

  Handle* h = new Handle{base, total_size, 1};
  return h;
}

void* rt_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* hd = reinterpret_cast<Header*>(base);
  if (hd->magic != kMagic) {
    munmap(base, st.st_size);
    return nullptr;
  }
  Handle* h = new Handle{base, static_cast<uint64_t>(st.st_size), 0};
  return h;
}

void rt_store_close(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  if (!h) return;
  munmap(h->base, h->size);
  delete h;
}

int rt_store_unlink(const char* name) { return shm_unlink(name); }

// Allocate an object. Returns data offset; 0 = OOM; -1 (as uint64 max) = exists.
uint64_t rt_alloc(void* hv, const uint8_t* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return 0;
  Entry* e = find(h, id, 0);
  if (e) {
    unlock(h);
    return ~0ULL;
  }
  uint64_t off = heap_alloc(h, size ? size : 1);
  if (off) {
    Entry* slot = find(h, id, 1);
    if (!slot) {  // table full
      heap_free(h, off, size ? size : 1);
      off = 0;
    } else {
      memcpy(slot->id, id, kIdLen);
      slot->owner_pid = static_cast<uint32_t>(getpid());
      slot->offset = off;
      slot->size = size;
      slot->flags = 0;  // reused tombstone slots must not leak stale flags
      slot->state = kAllocated;  // commit point last: a crash here leaks only the
                                 // extent, which heap_rebuild/sweep reclaims
      H(h)->num_objects++;
    }
  }
  unlock(h);
  return off;
}

int rt_seal(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Entry* e = find(h, id, 0);
  int rc = -1;
  if (e && e->state == kAllocated) {
    e->state = kSealed;
    rc = 0;
  }
  unlock(h);
  return rc;
}

// Look up a sealed object and take a reader pin (zero-copy view protection).
// 0 = found (pinned); -1 = missing; -2 = present but unsealed.
int rt_get(void* hv, const uint8_t* id, uint64_t* offset, uint64_t* size) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Entry* e = find(h, id, 0);
  int rc = -1;
  if (e) {
    if (e->state == kSealed) {
      *offset = e->offset;
      *size = e->size;
      e->pin_count++;
      rc = 0;
    } else {
      rc = -2;
    }
  }
  unlock(h);
  return rc;
}

// Drop a reader pin taken by rt_get. Frees the block if the object was deleted
// while pinned (kCondemned) and this was the last pin.
int rt_unpin(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Entry* e = find(h, id, 0);
  int rc = -1;
  if (e && (e->state == kSealed || e->state == kCondemned) && e->pin_count > 0) {
    e->pin_count--;
    if (e->state == kCondemned && e->pin_count == 0) {
      heap_free(h, e->offset, e->size ? e->size : 1);
      e->state = kTombstone;
    }
    rc = 0;
  }
  unlock(h);
  return rc;
}

int rt_delete(void* hv, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Entry* e = find(h, id, 0);
  int rc = -1;
  if (e && (e->state == kAllocated || e->state == kSealed)) {
    if (e->pin_count > 0) {
      // readers still hold views; defer the free to the last unpin
      e->state = kCondemned;
    } else {
      heap_free(h, e->offset, e->size ? e->size : 1);
      e->state = kTombstone;
    }
    H(h)->num_objects--;
    rc = 0;
  }
  unlock(h);
  return rc;
}

// Coordinator-driven GC: delete entries whose creator is dead and whose id is
// not in the keep set (dead workers' unsealed writes AND sealed-but-unreported
// outputs; keep = every id the coordinator's object directory still references).
// keep_blob is n_keep contiguous 20-byte ids. Returns entries collected.
int rt_gc_dead_owners(void* hv, const uint8_t* keep_blob, uint64_t n_keep) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Header* hd = H(h);
  Entry* t = table(h);
  int n = 0;
  for (uint64_t i = 0; i < hd->table_cap; i++) {
    Entry* e = &t[i];
    if (e->state != kAllocated && e->state != kSealed) continue;
    if (!e->owner_pid || kill(e->owner_pid, 0) == 0 || errno != ESRCH) continue;
    bool keep = false;
    for (uint64_t k = 0; k < n_keep; k++) {
      if (memcmp(keep_blob + k * kIdLen, e->id, kIdLen) == 0) {
        keep = true;
        break;
      }
    }
    if (keep) continue;
    if (e->pin_count > 0) {
      e->state = kCondemned;
    } else {
      heap_free(h, e->offset, e->size ? e->size : 1);
      e->state = kTombstone;
    }
    hd->num_objects--;
    n++;
  }
  unlock(h);
  return n;
}

// Set per-object flags (bit0 = is_error). Returns 0 on success.
int rt_set_flags(void* hv, const uint8_t* id, uint32_t flags) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Entry* e = find(h, id, 0);
  if (e) e->flags = flags;
  unlock(h);
  return e ? 0 : -1;
}

// List sealed objects: writes up to max_n records of
// [id (kIdLen) | size (u64 LE) | flags (u32 LE)] into out. Returns count.
// Lets a node agent re-report its arena contents to a restarted head
// (directory reconstruction without journaling every object mutation).
int rt_list(void* hv, uint8_t* out, uint64_t max_n) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Header* hd = H(h);
  Entry* t = table(h);
  uint64_t n = 0;
  const uint64_t rec = kIdLen + 8 + 4;
  for (uint64_t i = 0; i < hd->table_cap && n < max_n; i++) {
    Entry* e = &t[i];
    if (e->state != kSealed) continue;
    uint8_t* p = out + n * rec;
    memcpy(p, e->id, kIdLen);
    memcpy(p + kIdLen, &e->size, 8);
    memcpy(p + kIdLen + 8, &e->flags, 4);
    n++;
  }
  unlock(h);
  return static_cast<int>(n);
}

// GC unsealed objects whose creator died (crash during write). Returns count freed.
int rt_sweep(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return -1;
  Header* hd = H(h);
  Entry* t = table(h);
  int n = 0;
  for (uint64_t i = 0; i < hd->table_cap; i++) {
    Entry* e = &t[i];
    if (e->state == kAllocated && e->owner_pid && kill(e->owner_pid, 0) != 0 &&
        errno == ESRCH) {
      heap_free(h, e->offset, e->size ? e->size : 1);
      e->state = kTombstone;
      hd->num_objects--;
      n++;
    }
  }
  unlock(h);
  return n;
}

void rt_stats(void* hv, uint64_t* used, uint64_t* capacity, uint64_t* num_objects,
              uint64_t* peak) {
  Handle* h = static_cast<Handle*>(hv);
  if (lock(h) != 0) return;
  Header* hd = H(h);
  *used = hd->used_bytes;
  *capacity = hd->total_size - hd->heap_offset;
  *num_objects = hd->num_objects;
  *peak = hd->peak_used;
  unlock(h);
}

}  // extern "C"
