"""ctypes wrapper over the C++ shared-memory object arena (shm_store.cc).

Python maps the same POSIX shm segment with mmap for zero-copy buffer views; the
C++ side owns all metadata (object table, heap) inside the segment, so any number
of processes share one arena with no daemon (contrast: reference plasma store
socket protocol, src/ray/object_manager/plasma/plasma.fbs).
"""
from __future__ import annotations

import ctypes
import mmap
import os
from typing import Optional, Tuple

from .build import load_library

_ID_LEN = 20


def _lib():
    lib = load_library("shm_store")
    if not getattr(lib, "_rt_configured", False):
        u64 = ctypes.c_uint64
        lib.rt_store_create.restype = ctypes.c_void_p
        lib.rt_store_create.argtypes = [ctypes.c_char_p, u64, u64]
        lib.rt_store_open.restype = ctypes.c_void_p
        lib.rt_store_open.argtypes = [ctypes.c_char_p]
        lib.rt_store_close.argtypes = [ctypes.c_void_p]
        lib.rt_store_unlink.argtypes = [ctypes.c_char_p]
        lib.rt_alloc.restype = u64
        lib.rt_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64]
        lib.rt_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.POINTER(u64), ctypes.POINTER(u64)]
        lib.rt_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_sweep.argtypes = [ctypes.c_void_p]
        lib.rt_gc_dead_owners.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64]
        lib.rt_stats.argtypes = [ctypes.c_void_p] + [ctypes.POINTER(u64)] * 4
        lib.rt_set_flags.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.rt_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64]
        lib._rt_configured = True
    return lib


class Arena:
    """One node-wide shared-memory object arena."""

    def __init__(self, name: str, handle, size: int, owner: bool):
        import threading

        self.name = name
        self._h = handle
        self._lib = _lib()
        self.owner = owner
        # serializes close() against the background maintenance calls (sweep /
        # gc_dead_owners) that walk the mapping — closing mid-walk segfaults.
        # RLock, not Lock: unpin runs from weakref.finalize GC callbacks, which can
        # fire on the same thread while it already holds the lock inside get/seal.
        self._maint_lock = threading.RLock()
        fd = os.open(f"/dev/shm{name}", os.O_RDWR)
        try:
            self._map = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._view = memoryview(self._map)

    # -- lifecycle -------------------------------------------------------------
    @classmethod
    def create(cls, name: str, capacity: int, table_cap: int = 0) -> "Arena":
        if table_cap <= 0:
            # ~48 B/entry; keep the table under ~3% of the arena, within [1024, 1M].
            table_cap = max(1024, min(1 << 20, capacity // 2048))
        h = _lib().rt_store_create(name.encode(), capacity, table_cap)
        if not h:
            raise OSError(f"failed to create arena {name}")
        return cls(name, h, capacity, owner=True)

    @classmethod
    def open(cls, name: str) -> "Arena":
        h = _lib().rt_store_open(name.encode())
        if not h:
            raise OSError(f"failed to open arena {name}")
        size = os.stat(f"/dev/shm{name}").st_size
        return cls(name, h, size, owner=False)

    def close(self) -> None:
        with self._maint_lock:
            if not self._h:
                return
            self._lib.rt_store_close(self._h)
            self._h = None
            try:
                self._view.release()
                self._map.close()
            except BufferError:
                # zero-copy views of objects are still alive; the mapping stays until
                # they are dropped (process exit at the latest)
                pass

    def unlink(self) -> None:
        self._lib.rt_store_unlink(self.name.encode())

    # -- object ops ------------------------------------------------------------
    @staticmethod
    def _id(oid: bytes) -> bytes:
        if len(oid) != _ID_LEN:
            oid = (oid + b"\0" * _ID_LEN)[:_ID_LEN]
        return oid

    def create_object(self, oid: bytes, size: int) -> Optional[memoryview]:
        """Allocate; returns a writable view or None (OOM / already exists)."""
        with self._maint_lock:
            if not self._h:
                return None
            off = self._lib.rt_alloc(self._h, self._id(oid), size)
        if off in (0, 0xFFFFFFFFFFFFFFFF):
            return None
        return self._view[off:off + size]

    def seal(self, oid: bytes) -> None:
        with self._maint_lock:
            if not self._h or self._lib.rt_seal(self._h, self._id(oid)) != 0:
                raise KeyError(f"seal failed for {oid.hex()}")

    def get(self, oid: bytes) -> Optional[memoryview]:
        """Read-side lookup; returns a view of the sealed object or None.

        Takes a reader PIN: the caller (object_store.resolve) must arrange a
        matching unpin() once no zero-copy views of this object remain. A
        delete() while pinned defers the free until the last unpin."""
        off, size = ctypes.c_uint64(), ctypes.c_uint64()
        with self._maint_lock:
            if not self._h:
                return None
            rc = self._lib.rt_get(self._h, self._id(oid), ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return self._view[off.value:off.value + size.value]

    def unpin(self, oid: bytes) -> None:
        with self._maint_lock:
            if self._h:  # no-op after close (late weakref finalizers at shutdown)
                self._lib.rt_unpin(self._h, self._id(oid))

    def delete(self, oid: bytes) -> bool:
        with self._maint_lock:
            if not self._h:
                return False
            return self._lib.rt_delete(self._h, self._id(oid)) == 0

    def sweep(self) -> int:
        """GC unsealed objects from dead writers; returns number collected."""
        with self._maint_lock:
            if not self._h:
                return 0
            return self._lib.rt_sweep(self._h)

    def gc_dead_owners(self, keep_ids) -> int:
        """GC all objects whose creator process died, except ids in keep_ids
        (the coordinator's live object directory)."""
        blob = b"".join(self._id(i) for i in keep_ids)
        with self._maint_lock:
            if not self._h:
                return 0
            return self._lib.rt_gc_dead_owners(self._h, blob, len(keep_ids))

    def set_flags(self, oid: bytes, flags: int) -> None:
        """Per-object flag bits (bit0 = is_error frame); survive a head restart."""
        with self._maint_lock:
            if self._h:
                self._lib.rt_set_flags(self._h, self._id(oid), flags)

    def list_sealed(self) -> list:
        """[(oid_bytes, size, flags)] for every sealed object — a node agent
        re-reports these to a restarted head so the object directory can be
        rebuilt without journaling every mutation."""
        rec = _ID_LEN + 12
        with self._maint_lock:
            if not self._h:
                return []
            _, _, num, _ = self.stats()
            cap = max(int(num) + 64, 128)
            buf = ctypes.create_string_buffer(cap * rec)
            n = self._lib.rt_list(self._h, buf, cap)
        out = []
        raw = buf.raw
        for i in range(max(n, 0)):
            p = i * rec
            oid = raw[p:p + _ID_LEN]
            size = int.from_bytes(raw[p + _ID_LEN:p + _ID_LEN + 8], "little")
            flags = int.from_bytes(raw[p + _ID_LEN + 8:p + _ID_LEN + 12], "little")
            out.append((oid, size, flags))
        return out

    def stats(self) -> Tuple[int, int, int, int]:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        n = ctypes.c_uint64()
        peak = ctypes.c_uint64()
        with self._maint_lock:
            if not self._h:
                return 0, 0, 0, 0
            self._lib.rt_stats(self._h, ctypes.byref(used), ctypes.byref(cap),
                               ctypes.byref(n), ctypes.byref(peak))
        return used.value, cap.value, n.value, peak.value
