// Concurrency stress driver for the shm arena, built as a standalone binary so
// it can run under -fsanitize=thread / address without ctypes LD_PRELOAD games.
//
// Reference capability: ray's C++ plasma store is exercised by TSAN/ASAN CI
// jobs (BUILD.bazel sanitizer configs + ci/ test suites); this is the same
// seam for our store. N threads hammer one arena through the public C API —
// alloc/seal/get(pin)/unpin/delete with colliding ids plus a sweeper thread —
// then invariants are checked: every surviving sealed object still carries its
// write pattern, and used_bytes returns to zero after a full delete pass.
//
// Build + run (ci.yml "native-sanitizers" job):
//   g++ -std=c++17 -O1 -g -fsanitize=thread shm_store_stress.cc -o stress -lpthread -lrt
//   ./stress
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

// The store is a single translation unit with a C API; include it directly so
// the sanitizer instruments the whole thing.
#include "shm_store.cc"

namespace {

constexpr int kThreads = 8;
constexpr int kIdsPerThread = 64;
constexpr int kRounds = 40;
constexpr uint64_t kObjSize = 1024;

void make_id(uint8_t* id, int thread, int slot) {
  memset(id, 0, kIdLen);
  snprintf(reinterpret_cast<char*>(id), kIdLen, "t%02d-s%03d", thread, slot);
}

std::atomic<int> failures{0};

void worker(void* h, int tid) {
  uint8_t id[kIdLen];
  std::vector<char> buf(kObjSize);
  for (int round = 0; round < kRounds; ++round) {
    for (int slot = 0; slot < kIdsPerThread; ++slot) {
      make_id(id, tid, slot);
      uint64_t off = rt_alloc(h, id, kObjSize);
      if (off == ~0ULL) continue;  // lost the race to a colliding round
      if (off == 0) continue;      // transient OOM under churn is legal
      char* data = static_cast<char*>(static_cast<Handle*>(h)->base) + off;
      memset(data, 'a' + (tid % 26), kObjSize);
      if (rt_seal(h, id) != 0) failures.fetch_add(1);

      uint64_t got_off = 0, got_size = 0;
      if (rt_get(h, id, &got_off, &got_size) == 0) {
        const char* view =
            static_cast<char*>(static_cast<Handle*>(h)->base) + got_off;
        // pinned read: pattern must be intact while the pin is held
        if (view[0] != 'a' + (tid % 26) || view[kObjSize - 1] != view[0])
          failures.fetch_add(1);
        if (got_size != kObjSize) failures.fetch_add(1);
        rt_unpin(h, id);
      }
      // every other round, delete to force heap reuse + tombstone recycling
      if ((round + slot) % 2 == 0) rt_delete(h, id);
    }
  }
}

void sweeper(void* h, std::atomic<bool>* stop) {
  while (!stop->load()) {
    rt_sweep(h);
    usleep(1000);
  }
}

}  // namespace

int main() {
  std::string name = "/rt_stress_" + std::to_string(getpid());
  // heap sized so threads hit transient OOM sometimes (exercises free-list merge)
  void* h = rt_store_create(name.c_str(), 16ull << 20, 4096);
  if (!h) {
    fprintf(stderr, "create failed\n");
    return 2;
  }

  std::atomic<bool> stop{false};
  std::thread sw(sweeper, h, &stop);
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t) ts.emplace_back(worker, h, t);
  for (auto& t : ts) t.join();
  stop.store(true);
  sw.join();

  // full delete pass: the heap must drain to zero live objects
  uint8_t id[kIdLen];
  for (int t = 0; t < kThreads; ++t)
    for (int s = 0; s < kIdsPerThread; ++s) {
      make_id(id, t, s);
      rt_delete(h, id);
    }
  uint64_t used = 0, cap = 0, n = 0, peak = 0;
  rt_stats(h, &used, &cap, &n, &peak);
  int rc = 0;
  if (n != 0) {
    fprintf(stderr, "leak: %llu objects survive the delete pass\n",
            static_cast<unsigned long long>(n));
    rc = 1;
  }
  if (failures.load() != 0) {
    fprintf(stderr, "%d data-integrity failures\n", failures.load());
    rc = 1;
  }
  rt_store_close(h);
  shm_unlink(name.c_str());
  if (rc == 0) printf("ok: %d threads x %d rounds x %d ids, no leaks\n",
                      kThreads, kRounds, kIdsPerThread);
  return rc;
}
