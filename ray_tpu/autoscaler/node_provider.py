"""NodeProvider ABC + fake provider.

Capability parity: reference python/ray/autoscaler/node_provider.py (NodeProvider
ABC: create_node/terminate_node/non_terminated_nodes) and
_private/fake_multi_node/node_provider.py (nodes "launched" locally so autoscaler
logic is testable without a cloud). A TPU provider creates pod-slices: the unit
of scaling is a whole slice (you cannot add half a v5e-64), mirroring how the
reference's TPUAcceleratorManager models `TPU-{pod}-head` resources (tpu.py:376).
"""
from __future__ import annotations

import abc
import dataclasses
import threading
import uuid
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class NodeType:
    """A provisionable node shape (reference: available_node_types in cluster YAML)."""

    name: str
    resources: Dict[str, float]
    max_nodes: int = 10
    min_nodes: int = 0


@dataclasses.dataclass
class NodeInstance:
    instance_id: str
    node_type: str
    status: str  # "requested" | "running" | "terminated"


class NodeProvider(abc.ABC):
    """Provision/terminate nodes of declared types."""

    def __init__(self, node_types: List[NodeType]):
        self.node_types = {t.name: t for t in node_types}

    @abc.abstractmethod
    def create_node(self, node_type: str) -> NodeInstance: ...

    @abc.abstractmethod
    def terminate_node(self, instance_id: str) -> None: ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[NodeInstance]: ...

    def adopt_node(self, instance: NodeInstance) -> None:
        """Re-learn a node created by a previous process so terminate_node works
        on it (the launcher records instance ids across process boundaries).
        Providers without cross-process state can leave this a no-op."""


class FakeNodeProvider(NodeProvider):
    """Adds/removes nodes on the in-process Cluster — the fake_multi_node analogue.

    `launch_delay_steps` simulates slow cloud provisioning: a created node stays
    "requested" for N polls before joining, which exercises the autoscaler's
    pending-request accounting.
    """

    def __init__(self, node_types: List[NodeType], launch_delay_steps: int = 0):
        super().__init__(node_types)
        self._lock = threading.Lock()
        self._instances: Dict[str, NodeInstance] = {}
        self._countdown: Dict[str, int] = {}
        self._node_ids: Dict[str, object] = {}  # instance -> core NodeID
        self.launch_delay_steps = launch_delay_steps

    def create_node(self, node_type: str) -> NodeInstance:
        t = self.node_types[node_type]
        inst = NodeInstance(instance_id=f"fake-{uuid.uuid4().hex[:8]}",
                            node_type=t.name, status="requested")
        with self._lock:
            self._instances[inst.instance_id] = inst
            self._countdown[inst.instance_id] = self.launch_delay_steps
        return inst

    def terminate_node(self, instance_id: str) -> None:
        from ray_tpu.core import global_state

        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None or inst.status == "terminated":
                return
            inst.status = "terminated"
            node_id = self._node_ids.pop(instance_id, None)
        if node_id is not None:
            cluster = global_state.try_cluster()
            if cluster is not None:
                cluster.remove_node(node_id)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [i for i in self._instances.values() if i.status != "terminated"]

    def adopt_node(self, instance: NodeInstance) -> None:
        with self._lock:
            self._instances.setdefault(instance.instance_id, instance)

    def poll(self) -> None:
        """Advance simulated provisioning; 'requested' nodes join the cluster."""
        from ray_tpu.core import global_state

        with self._lock:
            pending = [i for i in self._instances.values() if i.status == "requested"]
        for inst in pending:
            with self._lock:
                if self._countdown[inst.instance_id] > 0:
                    self._countdown[inst.instance_id] -= 1
                    continue
            cluster = global_state.try_cluster()
            if cluster is None:
                continue
            node = cluster.add_node(dict(self.node_types[inst.node_type].resources))
            with self._lock:
                inst.status = "running"
                self._node_ids[inst.instance_id] = node.node_id
