"""NodeProvider ABC + fake provider.

Capability parity: reference python/ray/autoscaler/node_provider.py (NodeProvider
ABC: create_node/terminate_node/non_terminated_nodes) and
_private/fake_multi_node/node_provider.py (nodes "launched" locally so autoscaler
logic is testable without a cloud). A TPU provider creates pod-slices: the unit
of scaling is a whole slice (you cannot add half a v5e-64), mirroring how the
reference's TPUAcceleratorManager models `TPU-{pod}-head` resources (tpu.py:376).
"""
from __future__ import annotations

import abc
import dataclasses
import threading
import uuid
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class NodeType:
    """A provisionable node shape (reference: available_node_types in cluster YAML)."""

    name: str
    resources: Dict[str, float]
    max_nodes: int = 10
    min_nodes: int = 0


@dataclasses.dataclass
class NodeInstance:
    instance_id: str
    node_type: str
    status: str  # "requested" | "running" | "terminated"


class NodeProvider(abc.ABC):
    """Provision/terminate nodes of declared types."""

    def __init__(self, node_types: List[NodeType]):
        self.node_types = {t.name: t for t in node_types}

    @abc.abstractmethod
    def create_node(self, node_type: str) -> NodeInstance: ...

    @abc.abstractmethod
    def terminate_node(self, instance_id: str) -> None: ...

    @abc.abstractmethod
    def non_terminated_nodes(self) -> List[NodeInstance]: ...

    def adopt_node(self, instance: NodeInstance) -> None:
        """Re-learn a node created by a previous process so terminate_node works
        on it (the launcher records instance ids across process boundaries).
        Providers without cross-process state can leave this a no-op."""


class NodeAgentProvider(NodeProvider):
    """Scales REAL capacity: every created node is a node-agent OS process
    (core/node_agent.py) joining this head's node server over TCP — the local
    form of what a cloud provider does with fresh VMs; a TPU pod provider runs
    the same agent binary on newly provisioned slice hosts. Termination kills
    the agent process; the head's agent-death path drains the node."""

    def __init__(self, node_types: List[NodeType], address: Optional[str] = None,
                 host: str = "127.0.0.1"):
        super().__init__(node_types)
        self._lock = threading.Lock()
        self._instances: Dict[str, NodeInstance] = {}
        self._node_ids: Dict[str, object] = {}  # instance -> core NodeID
        self._procs: Dict[str, object] = {}
        self._host = host
        self._address = address  # None = lazily bind this cluster's node server

    def _resolve_address(self) -> str:
        if self._address is None:
            from ray_tpu.core import global_state

            cluster = global_state.try_cluster()
            if cluster is None:
                raise RuntimeError("NodeAgentProvider needs a running cluster "
                                   "or an explicit head address")
            port = cluster.start_node_server(host=self._host)
            self._address = f"{self._host}:{port}"
        return self._address

    def create_node(self, node_type: str) -> NodeInstance:
        import subprocess
        import sys

        t = self.node_types[node_type]
        inst = NodeInstance(instance_id=f"agent-{uuid.uuid4().hex[:8]}",
                            node_type=t.name, status="requested")
        argv = [sys.executable, "-m", "ray_tpu.core.node_agent",
                "--address", self._resolve_address(),
                "--label", f"instance_id={inst.instance_id}",
                "--label", f"node_type={t.name}"]
        if t.resources.get("CPU") is not None:
            argv += ["--num-cpus", str(t.resources["CPU"])]
        if t.resources.get("TPU"):
            argv += ["--num-tpus", str(t.resources["TPU"])]
        proc = subprocess.Popen(argv)
        with self._lock:
            self._instances[inst.instance_id] = inst
            self._procs[inst.instance_id] = proc
        return inst

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None or inst.status == "terminated":
                return
            inst.status = "terminated"
            proc = self._procs.pop(instance_id, None)
            self._node_ids.pop(instance_id, None)
        if proc is not None:
            try:
                proc.terminate()
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [i for i in self._instances.values() if i.status != "terminated"]

    def poll(self) -> None:
        """Correlate registered agents with instances (via the instance_id
        label they carry) and reap agent processes that died on their own."""
        from ray_tpu.core import global_state

        cluster = global_state.try_cluster()
        by_label: Dict[str, object] = {}
        if cluster is not None:
            for info in cluster.gcs.nodes(alive_only=True):
                iid = (info.labels or {}).get("instance_id")
                if iid:
                    by_label[iid] = info.node_id
        with self._lock:
            for iid, inst in self._instances.items():
                if inst.status == "terminated":
                    continue
                proc = self._procs.get(iid)
                if proc is not None and proc.poll() is not None:
                    inst.status = "terminated"  # the agent process died
                    self._procs.pop(iid, None)
                    self._node_ids.pop(iid, None)
                    continue
                if inst.status == "requested" and iid in by_label:
                    inst.status = "running"
                    self._node_ids[iid] = by_label[iid]

    def shutdown(self) -> None:
        for iid in list(self._instances):
            self.terminate_node(iid)


class FakeNodeProvider(NodeProvider):
    """Adds/removes nodes on the in-process Cluster — the fake_multi_node analogue.

    `launch_delay_steps` simulates slow cloud provisioning: a created node stays
    "requested" for N polls before joining, which exercises the autoscaler's
    pending-request accounting.
    """

    def __init__(self, node_types: List[NodeType], launch_delay_steps: int = 0):
        super().__init__(node_types)
        self._lock = threading.Lock()
        self._instances: Dict[str, NodeInstance] = {}
        self._countdown: Dict[str, int] = {}
        self._node_ids: Dict[str, object] = {}  # instance -> core NodeID
        self.launch_delay_steps = launch_delay_steps

    def create_node(self, node_type: str) -> NodeInstance:
        t = self.node_types[node_type]
        inst = NodeInstance(instance_id=f"fake-{uuid.uuid4().hex[:8]}",
                            node_type=t.name, status="requested")
        with self._lock:
            self._instances[inst.instance_id] = inst
            self._countdown[inst.instance_id] = self.launch_delay_steps
        return inst

    def terminate_node(self, instance_id: str) -> None:
        from ray_tpu.core import global_state

        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None or inst.status == "terminated":
                return
            inst.status = "terminated"
            node_id = self._node_ids.pop(instance_id, None)
        if node_id is not None:
            cluster = global_state.try_cluster()
            if cluster is not None:
                cluster.remove_node(node_id)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [i for i in self._instances.values() if i.status != "terminated"]

    def adopt_node(self, instance: NodeInstance) -> None:
        with self._lock:
            self._instances.setdefault(instance.instance_id, instance)

    def poll(self) -> None:
        """Advance simulated provisioning; 'requested' nodes join the cluster."""
        from ray_tpu.core import global_state

        with self._lock:
            pending = [i for i in self._instances.values() if i.status == "requested"]
        for inst in pending:
            with self._lock:
                if self._countdown[inst.instance_id] > 0:
                    self._countdown[inst.instance_id] -= 1
                    continue
            cluster = global_state.try_cluster()
            if cluster is None:
                continue
            node = cluster.add_node(dict(self.node_types[inst.node_type].resources))
            with self._lock:
                inst.status = "running"
                self._node_ids[inst.instance_id] = node.node_id
