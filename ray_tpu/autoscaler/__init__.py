"""ray_tpu.autoscaler — demand-driven node scaling (autoscaler v2 shape).

Capability parity: reference python/ray/autoscaler/v2/ (Autoscaler autoscaler.py:42,
instance_manager/, scheduler.py bin-packing against pending demand, monitor.py) +
the v1 NodeProvider ABC (node_provider.py) and the fake provider used for tests
(_private/fake_multi_node/node_provider.py). TPU-shaped: node types are pod-slices
(a v5e-8 slice is one schedulable node with 8 TPU resources + a slice-head
resource), and the provider contract is "provision a slice", not "launch a VM".
"""
from .node_provider import FakeNodeProvider, NodeAgentProvider, NodeProvider, NodeType
from .autoscaler import (
    Autoscaler,
    AutoscalingConfig,
    clear_demand_hint,
    demand_hints,
    post_demand_hint,
)

__all__ = [
    "NodeProvider",
    "FakeNodeProvider",
    "NodeAgentProvider",
    "NodeType",
    "Autoscaler",
    "AutoscalingConfig",
    "post_demand_hint",
    "clear_demand_hint",
    "demand_hints",
]
