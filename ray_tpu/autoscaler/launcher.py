"""Cluster launcher: declarative YAML -> cluster up/down (autoscaler v1 surface).

Capability parity: reference python/ray/autoscaler/ (StandardAutoscaler's cluster
launcher half) — `ray up cluster.yaml` / `ray down` with a YAML schema
(ray-schema.json): cluster_name, provider, available_node_types with resources
and min/max counts, head_node_type, setup/start commands. Providers here:
`fake` (in-process nodes, reference fake_multi_node/node_provider.py — the test
workhorse) and `tpu-pod` (launches TPU-VM workers via a user-supplied command
template; gated, since cloud CLIs aren't assumed).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import random
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .autoscaler import Autoscaler, AutoscalingConfig
from .node_provider import FakeNodeProvider, NodeInstance, NodeProvider, NodeType

logger = logging.getLogger(__name__)


class NodeLaunchError(RuntimeError):
    """A classified node-provision failure (reference: autoscaler v2
    instance_manager launch-failure handling + node_launcher.py's
    NodeLaunchException). `kind` is the taxonomy bucket, `retryable` says
    whether launching the same node type later can succeed without operator
    action, and `backoff_hint_s` is the provider's suggested wait."""

    def __init__(self, message: str, *, kind: str, retryable: bool,
                 backoff_hint_s: float = 30.0):
        super().__init__(message)
        self.kind = kind
        self.retryable = retryable
        self.backoff_hint_s = backoff_hint_s


# Provision-failure taxonomy: (kind, inline_retry, retryable, backoff_hint_s,
# lowercase stderr substrings). Order matters — first match wins. Patterns come
# from GCP API error semantics (googleapis.com error model) as surfaced through
# gcloud stderr; the reference's GCP provider classifies the same families in
# python/ray/autoscaler/_private/gcp/node.py (GCPNodeError / retry loops).
_PROVISION_TAXONOMY: List[Tuple[str, bool, bool, float, Tuple[str, ...]]] = [
    # API rate limiting: short inline retry with jitter is the correct cure.
    # Must precede quota: "Rate Limit Exceeded" would otherwise match the
    # quota bucket's "limit exceeded" and stall scale-up for minutes.
    ("rate_limit", True, True, 15.0,
     ("ratelimitexceeded", "rate limit", "too many requests", "429")),
    # Quota: retrying in seconds never helps; the autoscaler should back off
    # long and keep the demand queued (operator may raise quota meanwhile).
    ("quota", False, True, 300.0,
     ("quota", "resource_exhausted", "limit exceeded")),
    # Stockout: zone has no capacity for the accelerator right now. Same
    # handling as quota but distinct for observability — operators respond
    # differently (wait/queued-resources vs quota increase request).
    ("stockout", False, True, 120.0,
     ("no more capacity", "resource pool exhausted",
      "zone_resource_pool_exhausted", "not enough resources",
      "insufficient capacity", "stockout", "resources_unavailable",
      "capacity in the zone")),
    # Transient service/network hiccups: inline retry.
    ("transient", True, True, 15.0,
     ("unavailable", "deadline_exceeded", "deadline exceeded", "timed out",
      "timeout",
      "connection reset", "internal error", "backend error",
      "temporarily", " 500", " 502", " 503")),
    # Operator-actionable misconfiguration: fail fast, never retry.
    ("permanent", False, False, 0.0,
     ("permission_denied", "permission denied", "forbidden", "unauthenticated",
      "invalid_argument", "invalid value", "not_found", "not found",
      "does not exist", "already_exists", "already exists", "unsupported")),
]


def classify_provision_error(stderr: str) -> Tuple[str, bool, bool, float]:
    """Map provider CLI stderr -> (kind, inline_retry, retryable, backoff_hint_s).

    Unknown errors are treated as retryable-with-backoff (not inline): an
    autoscaler that gives up on demand because of an unrecognized message
    strands the workload, while capped exponential backoff bounds the cost of
    retrying a genuinely permanent failure."""
    low = " " + (stderr or "").lower()
    for kind, inline, retryable, hint, pats in _PROVISION_TAXONOMY:
        if any(p in low for p in pats):
            return kind, inline, retryable, hint
    return "unknown", False, True, 60.0


@dataclasses.dataclass
class ClusterConfig:
    """Parsed cluster YAML (reference ray-schema.json, trimmed to what runs here)."""

    cluster_name: str
    provider: Dict[str, Any]
    available_node_types: Dict[str, Dict[str, Any]]
    head_node_type: str
    max_workers: int = 8
    idle_timeout_minutes: float = 5.0
    initialization_commands: List[str] = dataclasses.field(default_factory=list)
    setup_commands: List[str] = dataclasses.field(default_factory=list)
    head_start_ray_commands: List[str] = dataclasses.field(default_factory=list)
    worker_start_ray_commands: List[str] = dataclasses.field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterConfig":
        required = ("cluster_name", "provider", "available_node_types", "head_node_type")
        missing = [k for k in required if k not in d]
        if missing:
            raise ValueError(f"cluster config missing required keys: {missing}")
        if d["head_node_type"] not in d["available_node_types"]:
            raise ValueError(f"head_node_type {d['head_node_type']!r} not in available_node_types")
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @classmethod
    def from_yaml(cls, path: str) -> "ClusterConfig":
        try:
            import yaml

            with open(path) as f:
                return cls.from_dict(yaml.safe_load(f))
        except ImportError:
            # pyyaml isn't guaranteed; accept JSON-formatted configs too
            import json

            with open(path) as f:
                return cls.from_dict(json.load(f))

    def node_types(self) -> List[NodeType]:
        out = []
        for name, spec in self.available_node_types.items():
            out.append(NodeType(
                name=name,
                resources=dict(spec.get("resources", {})),
                min_nodes=int(spec.get("min_workers", 0)),
                max_nodes=int(spec.get("max_workers", self.max_workers)),
            ))
        return out


class TPUPodProvider(NodeProvider):
    """Launches TPU-VM hosts with user-supplied command templates.

    The provider config carries `create_command` / `terminate_command` templates
    with {node_type} / {instance_id} placeholders (e.g. gcloud compute tpus
    tpu-vm create ...). No cloud SDK is imported — the reference's per-cloud
    NodeProvider subclasses (aws/gcp/azure) are all shell-outs at this layer."""

    def __init__(self, node_types: List[NodeType], provider_config: Dict[str, Any]):
        super().__init__(node_types)
        self.provider_config = dict(provider_config)
        self.create_command = provider_config.get("create_command")
        self.terminate_command = provider_config.get("terminate_command")
        if not self.create_command:
            raise ValueError("tpu-pod provider needs provider.create_command")
        self._nodes: Dict[str, NodeInstance] = {}
        self._counter = 0

    def create_node(self, node_type: str) -> NodeInstance:
        self._counter += 1
        instance_id = f"{node_type}-{self._counter}"
        cmd = self.create_command.format(node_type=node_type, instance_id=instance_id)
        subprocess.run(cmd, shell=True, check=True)
        inst = NodeInstance(instance_id=instance_id, node_type=node_type, status="running")
        self._nodes[instance_id] = inst
        return inst

    def terminate_node(self, instance_id: str) -> None:
        if self.terminate_command:
            inst = self._nodes.get(instance_id)
            cmd = self.terminate_command.format(
                instance_id=instance_id,
                node_type=inst.node_type if inst else "")
            subprocess.run(cmd, shell=True, check=False)
        self._nodes.pop(instance_id, None)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        return list(self._nodes.values())

    def adopt_node(self, instance: NodeInstance) -> None:
        self._nodes.setdefault(instance.instance_id, instance)
        # keep the id counter ahead of adopted ids so new nodes never collide
        tail = instance.instance_id.rsplit("-", 1)[-1]
        if tail.isdigit():
            self._counter = max(self._counter, int(tail))

    def terminate_all(self) -> None:
        """Tear down nodes launched by a previous process: in-memory tracking is
        gone, so run the provider's terminate_all_command (tag/name-scoped)."""
        cmd = self.provider_config.get("terminate_all_command")
        if cmd:
            subprocess.run(cmd, shell=True, check=False)
        self._nodes.clear()


def _rfc1035(name: str) -> str:
    """Sanitize to an RFC1035 label fragment (GCP resource-name charset)."""
    import re as _re

    return _re.sub(r"[^a-z0-9-]", "-", name.lower()).strip("-")


class GCPTPUProvider(NodeProvider):
    """First-class GCP TPU-VM provider over the gcloud CLI (reference
    python/ray/autoscaler/_private/gcp/node_provider.py — which drives the GCP
    API; at this layer the CLI is the same contract without vendoring the SDK).

    provider config: project, zone, accelerator_type (e.g. v5litepod-8),
    runtime_version, optional name_prefix + create_extra_args. Discovery goes
    through `gcloud ... list --format=json` filtered by the name prefix, so
    non_terminated_nodes reflects cloud truth and `down` can adopt nodes a
    previous process created."""

    def __init__(self, node_types: List[NodeType], provider_config: Dict[str, Any],
                 cluster_name: str = ""):
        super().__init__(node_types)
        import shutil

        if shutil.which(provider_config.get("gcloud_bin", "gcloud")) is None:
            raise RuntimeError(
                "gcp-tpu provider requires the gcloud CLI on PATH "
                "(or set provider.gcloud_bin)")
        for key in ("project", "zone", "accelerator_type", "runtime_version"):
            if not provider_config.get(key):
                raise ValueError(f"gcp-tpu provider needs provider.{key}")
        self.cfg = dict(provider_config)
        self.gcloud = self.cfg.get("gcloud_bin", "gcloud")
        # prefix scoped by CLUSTER NAME (reference: cluster-name labels) so two
        # clusters in one project/zone never adopt or delete each other's TPUs
        default_prefix = _rfc1035("-".join(filter(None, ["ray-tpu", cluster_name])))
        self.prefix = self.cfg.get("name_prefix", default_prefix)
        self._counter = 0
        self._preempted: set = set()  # instance names seen PREEMPTED, un-reaped

    def _base_args(self) -> List[str]:
        return [self.gcloud, "compute", "tpus", "tpu-vm"]

    def create_node(self, node_type: str) -> NodeInstance:
        from ray_tpu.config import CONFIG

        max_attempts = max(1, int(self.cfg.get(
            "create_max_attempts", CONFIG.provision_max_attempts)))
        backoff = float(CONFIG.provision_backoff_s)
        for attempt in range(1, max_attempts + 1):
            self._counter += 1
            # GCP resource names are RFC1035 (lowercase/digits/hyphens). A
            # FRESH name per attempt: a timed-out create whose server-side LRO
            # later completes must not turn the retry into "already exists"
            # (the orphan from the earlier attempt is invisible to
            # non_terminated_nodes only until the next list; prefix-scoped
            # discovery adopts it, and terminate_all/down clean it up).
            name = (f"{self.prefix}-{_rfc1035(node_type)}-{self._counter}-"
                    f"{uuid.uuid4().hex[:6]}")
            cmd = self._base_args() + [
                "create", name,
                "--project", self.cfg["project"],
                "--zone", self.cfg["zone"],
                "--accelerator-type", self.cfg["accelerator_type"],
                "--version", self.cfg["runtime_version"],
            ] + list(self.cfg.get("create_extra_args", []))
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                return NodeInstance(instance_id=name, node_type=node_type,
                                    status="running")
            except subprocess.CalledProcessError as e:
                # surface gcloud's actual complaint (quota, zone capacity, bad
                # version) instead of a bare non-zero-exit error (ADVICE r3)
                stderr = (e.stderr or e.stdout or "").strip()
                kind, inline, retryable, hint = classify_provision_error(stderr)
                msg = (f"gcloud create failed (rc={e.returncode}, kind={kind}, "
                       f"attempt {attempt}/{max_attempts}): {stderr[-2000:]}")
                if inline and attempt < max_attempts:
                    sleep_s = backoff * (2 ** (attempt - 1)) * random.uniform(0.7, 1.3)
                    logger.warning("%s; retrying in %.1fs", msg, sleep_s)
                    time.sleep(sleep_s)
                    continue
                raise NodeLaunchError(msg, kind=kind, retryable=retryable,
                                      backoff_hint_s=hint) from e
        raise AssertionError("unreachable")  # loop always returns or raises

    def terminate_node(self, instance_id: str) -> None:
        cmd = self._base_args() + [
            "delete", instance_id,
            "--project", self.cfg["project"],
            "--zone", self.cfg["zone"], "--quiet",
        ]
        subprocess.run(cmd, check=False, capture_output=True, text=True)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        cmd = self._base_args() + [
            "list", "--project", self.cfg["project"], "--zone", self.cfg["zone"],
            "--format=json",
        ]
        proc = subprocess.run(cmd, check=True, capture_output=True, text=True)
        out: List[NodeInstance] = []
        seen_preempted: set = set()
        for item in json.loads(proc.stdout or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(self.prefix + "-"):
                continue  # not ours: never adopt someone else's TPUs
            # name layout: <prefix>-<rfc1035(node_type)>-<counter>-<rand>.
            # The type segment must be one of OUR node types: a prefix match
            # alone would adopt cluster "prod-2"'s nodes from cluster "prod"
            # (prefixes "ray-tpu-prod-2-..." start with "ray-tpu-prod-").
            # This ownership check gates EVERYTHING below — including the
            # preemption reaper, which deletes what lands in the set.
            body = name[len(self.prefix) + 1:]
            if body.count("-") < 2:
                continue
            sanitized = body.rsplit("-", 2)[0]
            node_type = next((t for t in self.node_types
                              if _rfc1035(t) == sanitized), None)
            if node_type is None:
                continue  # someone else's TPU: never adopt, never delete
            state = item.get("state", "")
            if state == "PREEMPTED":
                # remember it so poll() can reap the husk and the autoscaler
                # relaunches (preempted TPU-VMs stay listed until deleted)
                seen_preempted.add(name)
            if state in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            out.append(NodeInstance(instance_id=name, node_type=node_type,
                                    status="running" if state == "READY"
                                    else "requested"))
        # rebuilt per listing: names deleted out-of-band don't linger forever
        self._preempted = seen_preempted
        return out

    def preempted_nodes(self) -> List[str]:
        """Instance names observed in PREEMPTED state since the last reap."""
        return sorted(self._preempted)

    def reap_preempted(self) -> List[str]:
        """Delete preempted TPU-VM husks so their names free up and capacity
        accounting reflects reality; the autoscaler's next bin-pack relaunches
        for the demand they were serving. Reference: the GCP provider treats
        preempted instances as dead and the StandardAutoscaler recreates them."""
        reaped = sorted(self._preempted)
        for name in reaped:
            logger.warning("reaping preempted TPU %s", name)
            self.terminate_node(name)
            self._preempted.discard(name)
        return reaped

    def poll(self) -> None:
        """Autoscaler per-step hook: refresh cloud view and reap preemptions."""
        try:
            self.non_terminated_nodes()
        except subprocess.CalledProcessError:
            return  # listing hiccup: keep last view, reap on a later pass
        if self.cfg.get("reap_preempted", True):
            self.reap_preempted()

    def terminate_all(self) -> None:
        for inst in self.non_terminated_nodes():
            self.terminate_node(inst.instance_id)
        # the listing above also refreshed the preempted set: teardown must
        # delete those husks too, not just live nodes (they stay listed in the
        # project until explicitly deleted)
        self.reap_preempted()


def make_provider(config: ClusterConfig) -> NodeProvider:
    ptype = config.provider.get("type", "fake")
    if ptype == "fake":
        return FakeNodeProvider(config.node_types(),
                                launch_delay_steps=int(config.provider.get("launch_delay_steps", 0)))
    if ptype == "tpu-pod":
        return TPUPodProvider(config.node_types(), config.provider)
    if ptype == "gcp-tpu":
        return GCPTPUProvider(config.node_types(), config.provider,
                              cluster_name=config.cluster_name)
    raise ValueError(
        f"unknown provider type {ptype!r} (supported: fake, tpu-pod, gcp-tpu)")


class ClusterLauncher:
    """`up` brings the head + min workers alive and starts the autoscaler loop;
    `down` terminates everything (reference `ray up` / `ray down`)."""

    def __init__(self, config: ClusterConfig):
        self.config = config
        self.provider = make_provider(config)
        self.autoscaler: Optional[Autoscaler] = None
        self.head: Optional[NodeInstance] = None

    def up(self, *, start_autoscaler: bool = True) -> NodeInstance:
        for cmd in self.config.initialization_commands + self.config.setup_commands:
            subprocess.run(cmd, shell=True, check=True)
        self.head = self.provider.create_node(self.config.head_node_type)
        for cmd in self.config.head_start_ray_commands:
            subprocess.run(cmd, shell=True, check=True)
        # min_workers come up immediately; the autoscaler handles the rest
        for nt in self.config.node_types():
            existing = sum(1 for n in self.provider.non_terminated_nodes()
                           if n.node_type == nt.name)
            for _ in range(max(0, nt.min_nodes - existing)):
                self.provider.create_node(nt.name)
        if start_autoscaler:
            self.autoscaler = Autoscaler(
                self.provider,
                config=AutoscalingConfig(
                    idle_timeout_s=self.config.idle_timeout_minutes * 60.0),
            )
            self.autoscaler.start()
        return self.head

    def adopt(self, instances: List[Dict[str, str]]) -> None:
        """Re-learn nodes created by a previous process (reference `ray down`
        re-discovers nodes by tag; here the CLI persists instance ids)."""
        for inst in instances:
            self.provider.adopt_node(NodeInstance(
                instance_id=inst["instance_id"], node_type=inst["node_type"],
                status="running"))

    def down(self) -> int:
        """Terminate all nodes; returns how many were torn down. If the provider
        tracks nothing (down from a fresh process), fall back to its
        terminate_all hook (reference `ray down` re-discovers nodes by tag)."""
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        nodes = self.provider.non_terminated_nodes()
        for n in nodes:
            self.provider.terminate_node(n.instance_id)
        if not nodes and hasattr(self.provider, "terminate_all"):
            self.provider.terminate_all()
        self.head = None
        return len(nodes)
