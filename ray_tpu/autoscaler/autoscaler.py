"""Autoscaler: bin-pack pending demand onto node types, scale the provider.

Capability parity: reference python/ray/autoscaler/v2/ — `Autoscaler`
(autoscaler.py:42) polling `GcsAutoscalerStateManager`-style cluster state,
`scheduler.py` bin-packing pending resource requests onto `available_node_types`,
launching/terminating through the instance manager; plus v1's idle-node
termination (StandardAutoscaler, _private/autoscaler.py:172).

Demand sources here: the Cluster's pending task/actor queue (resource shapes that
could not be placed) and pending placement groups (whole-bundle-list demand —
slices must fit atomically, the TPU analogue of STRICT_PACK on `TPU-...-head`).
"""
from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from .node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscalingConfig:
    idle_timeout_s: float = 60.0
    upscale_interval_s: float = 1.0
    max_concurrent_launches: int = 100


def _fits(resources: Dict[str, float], capacity: Dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in resources.items() if v > 0)


# -- demand hints: other control planes hand anticipated demand to the node
# autoscaler BEFORE their actors hit the pending queue (the serve autoscaler
# posts "serve:<app>/<deployment>" hints for scale-ups no host has room for,
# so node launch overlaps replica-start retries instead of serializing after
# them). Module-level so hint producers need no Autoscaler handle.
_hints_lock = threading.Lock()
_demand_hints: Dict[str, List[Dict[str, float]]] = {}


def post_demand_hint(key: str, shapes: List[Dict[str, float]]) -> None:
    """Publish (replace) anticipated resource demand under `key`. Each shape
    is one resource bundle the producer will try to place soon."""
    with _hints_lock:
        if shapes:
            _demand_hints[key] = [dict(s) for s in shapes]
        else:
            _demand_hints.pop(key, None)


def clear_demand_hint(key: str) -> None:
    with _hints_lock:
        _demand_hints.pop(key, None)


def demand_hints() -> Dict[str, List[Dict[str, float]]]:
    with _hints_lock:
        return {k: [dict(s) for s in v] for k, v in _demand_hints.items()}


def bin_pack(demands: List[Dict[str, float]], node_types: List, existing_headroom:
             List[Dict[str, float]]) -> Dict[str, int]:
    """First-fit-decreasing pack of resource demands; returns {node_type: count} to add.

    Reference analog: autoscaler v2 scheduler.py's ResourceDemandScheduler.
    """
    headroom = [dict(h) for h in existing_headroom]
    to_launch: Dict[str, int] = defaultdict(int)
    virtual: List[Dict[str, float]] = []

    for demand in sorted(demands, key=lambda d: -sum(d.values())):
        placed = False
        for cap in headroom + virtual:
            if _fits(demand, cap):
                for k, v in demand.items():
                    cap[k] = cap.get(k, 0.0) - v
                placed = True
                break
        if placed:
            continue
        # pick the smallest node type that fits the demand
        candidates = [t for t in node_types if _fits(demand, t.resources)]
        if not candidates:
            continue  # infeasible demand: surfaced via pending_infeasible
        best = min(candidates, key=lambda t: sum(t.resources.values()))
        to_launch[best.name] += 1
        cap = dict(best.resources)
        for k, v in demand.items():
            cap[k] = cap.get(k, 0.0) - v
        virtual.append(cap)
    return dict(to_launch)


class Autoscaler:
    """Reconciles cluster demand against the provider. Runs as a driver thread."""

    def __init__(self, provider: NodeProvider,
                 config: Optional[AutoscalingConfig] = None,
                 cluster=None):
        from ray_tpu.core import global_state

        self.provider = provider
        self.config = config or AutoscalingConfig()
        self._cluster = cluster or global_state.try_cluster()
        if self._cluster is None:
            raise RuntimeError("ray_tpu is not initialized")
        self._stop = threading.Event()
        self._idle_since: Dict[object, float] = {}
        self._thread: Optional[threading.Thread] = None
        # per-node-type launch backoff (quota/stockout/transient failures):
        # {node_type: (next_attempt_ts, current_backoff_s)}
        self._launch_backoff: Dict[str, tuple] = {}
        # last classified failure per node type, for observability/tests
        self.launch_failures: Dict[str, str] = {}

    def _launch(self, node_type: str) -> bool:
        """create_node with classified-failure handling: on a retryable
        NodeLaunchError (quota/stockout/rate-limit/unknown) the node type goes
        into capped exponential backoff instead of being hammered every
        reconcile tick; on a permanent one it backs off at the cap so a
        misconfigured type cannot spin the loop, while the error stays visible
        in launch_failures. Reference: autoscaler v2 instance_manager's launch
        failure handling + node_launcher exponential backoff."""
        from ray_tpu.config import CONFIG

        from .launcher import NodeLaunchError

        now = time.time()
        entry = self._launch_backoff.get(node_type)
        if entry is not None and now < entry[0]:
            return False  # still cooling down
        try:
            self.provider.create_node(node_type)
        except NodeLaunchError as e:
            prev = entry[1] if entry is not None else 0.0
            base = max(e.backoff_hint_s, float(CONFIG.provision_backoff_s))
            cap = float(CONFIG.launch_backoff_max_s)
            backoff = min(cap, max(base, prev * 2.0))
            if not e.retryable:
                backoff = cap
            self._launch_backoff[node_type] = (now + backoff, backoff)
            self.launch_failures[node_type] = f"{e.kind}: {e}"
            logger.warning("launch of %s failed (%s); backing off %.0fs",
                           node_type, e.kind, backoff)
            return False
        self._launch_backoff.pop(node_type, None)
        self.launch_failures.pop(node_type, None)
        return True

    # -- demand/cluster views ----------------------------------------------------
    def pending_demands(self) -> List[Dict[str, float]]:
        c = self._cluster
        out = []
        with c._lock:
            for spec in c.pending:
                if spec.resources:
                    out.append(dict(spec.resources))
            for pg in c.pending_pgs:
                out.extend(dict(b) for b in pg.bundle_specs)
        # anticipated demand other control planes handed off (serve
        # autoscaler scale-ups stuck without room): bin-packed like pending
        # work so capacity launches before the actors themselves queue up
        for shapes in demand_hints().values():
            out.extend(shapes)
        return out

    def _headroom(self) -> List[Dict[str, float]]:
        return [n.ledger.available() for n in self._cluster.nodes() if n.alive]

    def _provider_count(self, node_type: str) -> int:
        return sum(1 for i in self.provider.non_terminated_nodes()
                   if i.node_type == node_type)

    # -- reconciliation ----------------------------------------------------------
    def step(self) -> Dict[str, int]:
        """One reconcile pass: launch for unmet demand, terminate idle nodes.
        Returns the launch decision (for tests/observability)."""
        poll = getattr(self.provider, "poll", None)
        if poll is not None:
            poll()

        demands = self.pending_demands()
        launched: Dict[str, int] = {}
        if demands:
            pending_caps = [dict(self.provider.node_types[i.node_type].resources)
                            for i in self.provider.non_terminated_nodes()
                            if i.status == "requested"]
            decision = bin_pack(demands, list(self.provider.node_types.values()),
                                self._headroom() + pending_caps)
            for node_type, count in decision.items():
                t = self.provider.node_types[node_type]
                have = self._provider_count(node_type)
                count = min(count, max(0, t.max_nodes - have),
                            self.config.max_concurrent_launches)
                done = sum(1 for _ in range(count) if self._launch(node_type))
                if done:
                    launched[node_type] = done

        # min_nodes floors
        for t in self.provider.node_types.values():
            deficit = t.min_nodes - self._provider_count(t.name)
            for _ in range(max(0, deficit)):
                self._launch(t.name)

        self._terminate_idle()
        return launched

    def _terminate_idle(self) -> None:
        """Terminate provider nodes idle past the timeout (never the head node).

        Idle = full resource headroom (nothing scheduled) and no live workers
        holding state (actors pin their node implicitly via held resources).
        """
        now = time.time()
        c = self._cluster
        by_node_id = {}
        get_nid = getattr(self.provider, "_node_ids", None)
        if get_nid is None:
            return  # provider doesn't expose node identity; skip scale-down
        with self.provider._lock:
            for inst_id, nid in self.provider._node_ids.items():
                by_node_id[nid] = inst_id
        for node in c.nodes():
            inst_id = by_node_id.get(node.node_id)
            if inst_id is None or not node.alive:
                continue
            avail = node.ledger.available()
            if avail == node.ledger.total:
                since = self._idle_since.setdefault(node.node_id, now)
                if now - since >= self.config.idle_timeout_s:
                    self.provider.terminate_node(inst_id)
                    self._idle_since.pop(node.node_id, None)
            else:
                self._idle_since.pop(node.node_id, None)

    # -- lifecycle ----------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.config.upscale_interval_s):
                try:
                    self.step()
                except Exception as e:
                    # a silently-dead autoscaler means no scaling at all:
                    # log every failed step (interval-paced, so not spammy)
                    logger.warning("autoscaler step failed: %r", e)

        self._thread = threading.Thread(target=loop, daemon=True, name="rt-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
