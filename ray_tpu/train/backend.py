"""Backend plugin interface: framework-specific worker-group setup.

Reference capability: python/ray/train/backend.py — BackendConfig (:16), Backend (:32)
with hooks on_start (:45), on_training_start (:53), on_shutdown (:49). The reference's
_TorchBackend runs torch.distributed rendezvous here; our JaxBackend (jax_backend.py)
bootstraps the jax.distributed universe the same way.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Type

if TYPE_CHECKING:
    from .worker_group import WorkerGroup


@dataclass
class BackendConfig:
    @property
    def backend_cls(self) -> Type["Backend"]:
        return Backend


class Backend:
    """Hooks run by BackendExecutor around worker-group lifecycle."""

    share_cwd: bool = True

    def on_start(self, worker_group: "WorkerGroup", backend_config: BackendConfig) -> None:
        """After workers are up, before the user loop starts (process-group setup)."""

    def on_training_start(self, worker_group: "WorkerGroup", backend_config: BackendConfig) -> None:
        """Right before user train loops launch."""

    def on_shutdown(self, worker_group: "WorkerGroup", backend_config: BackendConfig) -> None:
        """Before workers are torn down."""

    def on_failure(self, worker_group: "WorkerGroup", backend_config: BackendConfig,
                   error: BaseException) -> None:
        """After a worker-group failure, before the non-graceful teardown.

        Must not raise and must not block on the (possibly half-dead) group:
        used to abort collective state so surviving ranks blocked in an op
        fail fast instead of pinning the restart behind the op timeout."""
