"""Device-plane gradient synchronization for data-parallel training.

The train step's remaining MFU lever (ROADMAP "Device-plane training
collectives"): the stock step expresses gradient sync implicitly — GSPMD
inserts one combined all-reduce after the whole backward — and the optimizer
state replicates across data-parallel replicas. This module makes the sync an
explicit, tunable stage with three composable pieces:

1. **Bucketed all-reduce** (`mode="bucketed"`): the grad pytree is partitioned
   into size-bounded buckets (`RAY_TPU_TRAIN_BUCKET_BYTES`) and each bucket is
   reduced by its own `jax.lax.pmean` over the `dp` mesh axis inside a
   `shard_map` manual region. Each bucket is an independent collective in the
   compiled HLO (`overlap_report` verifies reductions are not all sunk to the
   end), so XLA's scheduler can overlap bucket k's reduction with bucket k-1's
   optimizer math and with backward compute instead of serializing one
   monolithic all-reduce after the last gradient.

2. **On-device int8 block-quantized reduction** (`compression="int8"`): each
   rank quantizes its local bucket contribution with the block-scale scheme of
   `ops/quant.py` (device-side `quantize_blockwise`, EQuARX-style — arxiv
   2506.17615), all-gathers the int8 payload + f32 block scales over `dp`, and
   dequant-sums locally. Wire bytes per contribution drop from 4n (f32) to
   n + 4*ceil(n/block) (~3.9x at the default block of 1024). Optional
   stochastic rounding keeps the quantizer unbiased across steps.

   Accuracy contract (mirrors the host-plane int8 wire path from PR 1): per
   element, each rank's contribution carries absolute error <= amax_block/254
   (round-nearest) or <= amax_block/127 (stochastic), where amax_block is the
   max |grad| within that contribution's scale block; the reduced value's
   error is bounded by the mean of the per-rank bounds. f32 mode is bit-exact
   with the monolithic path; int8 is NOT bit-exact and is gated by loss-curve
   parity in `bench.py --grad-sync`. Leaves smaller than `min_quant_elems`
   skip quantization (scales would dominate the payload).

3. **Cross-replica sharded optimizer update** (`sharded_update=True`): the
   ZeRO-style weight-update sharding of arxiv 2004.13336. Grads are constrained
   to a per-leaf spec that extends the parameter sharding with the `(dp, fsdp)`
   axes (GSPMD lowers all-reduce + consumer slice to reduce-scatter), Adam
   state lives and updates shard-local (`optax.tree_map_params` walks the
   param-shaped moment leaves), and only the updated params are all-gathered
   back to their compute sharding. Per-chip optimizer HBM drops by the added
   sharding factor — the knob that lets dp x fsdp mixed meshes fit v5e HBM
   (see `__graft_entry__.hbm_budget_sharded_opt`).

Semantics notes:
- The explicit (bucketed) path computes grads per-dp-shard and averages them
  with `pmean`, which equals the monolithic global-mean gradient when every dp
  shard sees the same number of loss tokens (true for the repo's training
  paths; with a ragged `loss_mask` the shards are weighted equally instead of
  per-token).
- The explicit path owns ONLY the `dp` axis; fsdp/tp sharding stays in GSPMD
  "auto" mode inside the manual region, so it composes with the fsdp param
  sharding. It does not compose with model code that opens its own shard_map
  (pipeline_stages > 1, ring/ulysses attention) — `make_step` rejects those.
- jax <= 0.4.x ships a partial-auto shard_map that miscompiles when a
  NON-TRIVIAL auto axis (size > 1) crosses the manual region; `_shard_map`
  raises a clear error there instead of letting XLA hard-crash. Pure-dp meshes
  work on every supported jax; dp x fsdp needs the newer shard_map.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.util.hot_path import hot_path

DEFAULT_BUCKET_BYTES = 4 << 20  # 4 MiB: ~8 buckets on a 500M-param f32 tree

_TRUE = ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class GradSyncConfig:
    """Gradient-sync strategy for `make_train_step` (env-overridable so the
    JaxTrainer backend can hand it to worker loops — see `JaxConfig.grad_sync`).

    mode: "gspmd" (default; the implicit monolithic sync — alias "monolithic")
        or "bucketed" (explicit per-bucket collectives, overlap-friendly).
    bucket_bytes: max payload per bucket (RAY_TPU_TRAIN_BUCKET_BYTES).
    compression: None (f32, bit-exact) or "int8" (block-quantized, see module
        docstring for the tolerance contract).
    stochastic_rounding: unbiased quantizer (int8 only).
    quant_block_elems: elements per int8 scale block.
    min_quant_elems: leaves smaller than this stay f32 even under int8.
    sharded_update: ZeRO-style cross-replica sharded optimizer update.
    update_axes: mesh axes the update shards over (on top of each param's own
        sharding); axes absent from the mesh or sized 1 are ignored.
    telemetry: time grad-sync phases (`train.step_phase` spans +
        `train_grad_sync_seconds{phase}`) by splitting the step into a grads
        stage and an update stage with per-bucket waits in between. Costs the
        grads/update fusion — leave off for headline MFU runs.
    """

    mode: str = "gspmd"
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    compression: Optional[str] = None
    stochastic_rounding: bool = False
    quant_block_elems: int = 1024
    min_quant_elems: int = 256
    sharded_update: bool = False
    update_axes: Tuple[str, ...] = ("dp", "fsdp")
    axis: str = "dp"
    telemetry: bool = False

    def __post_init__(self):
        mode = {"monolithic": "gspmd"}.get(self.mode, self.mode)
        if mode not in ("gspmd", "bucketed"):
            raise ValueError(f"unknown grad-sync mode {self.mode!r}")
        object.__setattr__(self, "mode", mode)
        if self.compression not in (None, "", "int8"):
            raise ValueError(f"unknown grad compression {self.compression!r}")
        if not self.compression:
            object.__setattr__(self, "compression", None)
        if self.compression and mode != "bucketed":
            # silently running the stock uncompressed step while the user
            # believes int8 is on would be the worst failure mode
            raise ValueError(
                "compression requires mode='bucketed' (the gspmd/monolithic "
                "sync is implicit — there is no stage to compress)")
        if isinstance(self.update_axes, list):
            object.__setattr__(self, "update_axes", tuple(self.update_axes))

    @property
    def is_default(self) -> bool:
        """True when the config changes nothing vs the stock fused step."""
        return (self.mode == "gspmd" and not self.sharded_update
                and not self.telemetry)

    @staticmethod
    def from_env() -> "GradSyncConfig":
        axes = os.environ.get("RAY_TPU_TRAIN_UPDATE_AXES", "") or "dp,fsdp"
        return GradSyncConfig(
            mode=os.environ.get("RAY_TPU_TRAIN_GRAD_SYNC_MODE", "gspmd") or "gspmd",
            bucket_bytes=_env_int("RAY_TPU_TRAIN_BUCKET_BYTES", DEFAULT_BUCKET_BYTES),
            compression=os.environ.get("RAY_TPU_TRAIN_GRAD_COMPRESSION", "") or None,
            stochastic_rounding=os.environ.get(
                "RAY_TPU_TRAIN_GRAD_STOCHASTIC_ROUNDING", "").lower() in _TRUE,
            quant_block_elems=_env_int("RAY_TPU_TRAIN_QUANT_BLOCK_ELEMS", 1024),
            min_quant_elems=_env_int("RAY_TPU_TRAIN_MIN_QUANT_ELEMS", 256),
            sharded_update=os.environ.get(
                "RAY_TPU_TRAIN_SHARDED_UPDATE", "").lower() in _TRUE,
            update_axes=tuple(a for a in axes.split(",") if a),
            axis=os.environ.get("RAY_TPU_TRAIN_GRAD_SYNC_AXIS", "") or "dp",
            telemetry=os.environ.get(
                "RAY_TPU_TRAIN_GRAD_SYNC_TELEMETRY", "").lower() in _TRUE,
        )

    def to_env(self) -> Dict[str, str]:
        """Env representation (inverse of from_env) for worker propagation."""
        return {
            "RAY_TPU_TRAIN_GRAD_SYNC_MODE": self.mode,
            "RAY_TPU_TRAIN_BUCKET_BYTES": str(self.bucket_bytes),
            "RAY_TPU_TRAIN_GRAD_COMPRESSION": self.compression or "",
            "RAY_TPU_TRAIN_GRAD_STOCHASTIC_ROUNDING":
                "1" if self.stochastic_rounding else "",
            "RAY_TPU_TRAIN_QUANT_BLOCK_ELEMS": str(self.quant_block_elems),
            "RAY_TPU_TRAIN_MIN_QUANT_ELEMS": str(self.min_quant_elems),
            "RAY_TPU_TRAIN_SHARDED_UPDATE": "1" if self.sharded_update else "",
            "RAY_TPU_TRAIN_UPDATE_AXES": ",".join(self.update_axes),
            "RAY_TPU_TRAIN_GRAD_SYNC_AXIS": self.axis,
            "RAY_TPU_TRAIN_GRAD_SYNC_TELEMETRY": "1" if self.telemetry else "",
        }


# ---------------------------------------------------------------- bucketing

def partition_buckets(tree: Any, bucket_bytes: int) -> List[List[int]]:
    """Partition a pytree's leaves into size-bounded buckets.

    Returns a list of buckets, each a list of flat-leaf indices (tree_flatten
    order, so the grouping is deterministic for a given tree structure). A
    leaf larger than `bucket_bytes` gets its own bucket; every leaf lands in
    exactly one bucket. Works on concrete arrays and ShapeDtypeStructs.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = int(np.prod(leaf.shape or (1,))) * jnp.dtype(leaf.dtype).itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        if cur_bytes >= bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def sync_payload_bytes(tree: Any, sync: GradSyncConfig) -> Dict[str, int]:
    """Analytic per-rank payload bytes one sync moves, f32 vs the configured
    compression — the `reduced_bytes` accounting behind TRAIN_SYNC_BENCH."""
    f32 = 0
    compressed = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        n = int(np.prod(leaf.shape or (1,)))
        f32 += 4 * n
        if sync.compression == "int8" and n >= sync.min_quant_elems:
            compressed += n + 4 * (-(-n // sync.quant_block_elems))
        else:
            compressed += 4 * n
    return {"f32_bytes": f32, "compressed_bytes": compressed}


# ------------------------------------------------------------- mesh compat

def _mesh_of(tree: Any) -> Optional[Mesh]:
    """Concrete mesh from any NamedSharding-carrying leaf, else the ambient
    (version-compat probe shared with parallel/sharding.py)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        s = getattr(leaf, "sharding", None)
        if isinstance(s, NamedSharding):
            return s.mesh
    from ray_tpu.parallel.sharding import ambient_mesh

    return ambient_mesh()


def _shard_map(f, mesh: Mesh, in_specs, out_specs, manual: Sequence[str]):
    """shard_map with the given axes manual and the rest in GSPMD auto mode,
    across jax versions — shared impl in parallel/sharding.compat_shard_map."""
    from ray_tpu.parallel.sharding import compat_shard_map

    return compat_shard_map(f, mesh, in_specs, out_specs, manual)


# ----------------------------------------------------- in-jit sync kernels

def _quantized_pmean(leaf: jax.Array, axis: str, sync: GradSyncConfig,
                     key: Optional[jax.Array]) -> jax.Array:
    """int8 block-quantized mean-reduce over `axis` (inside a manual region):
    quantize local contribution -> all-gather int8+scales -> dequant-sum."""
    from ray_tpu.ops.quant import quantize_blockwise

    n = int(np.prod(leaf.shape or (1,)))
    q, scales = quantize_blockwise(leaf, sync.quant_block_elems, key=key)
    qg = jax.lax.all_gather(q, axis)          # [W, nblocks, block] int8
    sg = jax.lax.all_gather(scales, axis)     # [W, nblocks, 1] f32
    w = jax.lax.psum(1, axis)
    total = jnp.sum(qg.astype(jnp.float32) * sg, axis=0)
    return (total.reshape(-1)[:n] / w).reshape(leaf.shape).astype(leaf.dtype)


def _sync_bucketed(grads: Any, axis: str, sync: GradSyncConfig,
                   key: Optional[jax.Array]) -> Any:
    """Reduce a grad pytree over `axis`, one collective (pmean) per bucket —
    call inside a shard_map region with `axis` manual."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    buckets = partition_buckets(grads, sync.bucket_bytes)
    out: List[Optional[jax.Array]] = [None] * len(leaves)
    for b, idxs in enumerate(buckets):
        plain = [i for i in idxs
                 if sync.compression != "int8"
                 or int(np.prod(leaves[i].shape or (1,))) < sync.min_quant_elems]
        quant = [i for i in idxs if i not in plain]
        if plain:
            reduced = jax.lax.pmean([leaves[i] for i in plain], axis)
            for i, r in zip(plain, reduced):
                out[i] = r
        for i in quant:
            k = None
            if key is not None:
                k = jax.random.fold_in(jax.random.fold_in(key, i),
                                       jax.lax.axis_index(axis))
            out[i] = _quantized_pmean(leaves[i], axis, sync, k)
    return jax.tree_util.tree_unflatten(treedef, out)


# ------------------------------------------------- sharded optimizer update

def _spec_axes(spec: P) -> set:
    used = set()
    for e in spec:
        if isinstance(e, tuple):
            used |= set(e)
        elif e is not None:
            used.add(e)
    return used


def build_update_specs(params: Any, mesh: Mesh,
                       axes: Sequence[str] = ("dp", "fsdp")) -> Any:
    """Per-leaf PartitionSpec tree for the cross-replica sharded update: each
    param's own sharding extended with the (non-trivial, not-already-used)
    `axes` on the dimension with the largest evenly-divisible shard extent.
    Leaves with no eligible dimension keep their original spec (replicated
    update for that leaf). Works on arrays and sharded ShapeDtypeStructs."""

    def leaf_spec(x):
        s = getattr(x, "sharding", None)
        base = s.spec if isinstance(s, NamedSharding) else P()
        add = tuple(a for a in axes
                    if a not in _spec_axes(base) and mesh.shape.get(a, 1) > 1)
        if not add or not getattr(x, "shape", ()):
            return base
        entries = list(base) + [None] * (len(x.shape) - len(base))

        def factor(e):
            if e is None:
                return 1
            names = e if isinstance(e, tuple) else (e,)
            return int(np.prod([mesh.shape[a] for a in names]))

        addf = int(np.prod([mesh.shape[a] for a in add]))
        best, best_local = None, 0
        for i, dim in enumerate(x.shape):
            local = dim // factor(entries[i])
            if local % addf == 0 and local >= addf and local > best_local:
                best, best_local = i, local
        if best is None:
            return base
        cur = entries[best]
        cur = () if cur is None else (cur if isinstance(cur, tuple) else (cur,))
        entries[best] = tuple(cur) + add
        return P(*entries)

    return jax.tree_util.tree_map(leaf_spec, params)


def param_specs(params: Any) -> Any:
    """The params' own PartitionSpec tree (the compute sharding updated params
    are all-gathered back to)."""
    return jax.tree_util.tree_map(
        lambda x: x.sharding.spec
        if isinstance(getattr(x, "sharding", None), NamedSharding) else P(),
        params)


def _constrain(tree: Any, specs: Any, mesh: Mesh) -> Any:
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        tree, specs)


def constrain_opt_state(tx: optax.GradientTransformation, opt_state: Any,
                        specs: Any, mesh: Mesh) -> Any:
    """Constrain the param-shaped leaves of an optax state (Adam moments) to
    the update shardings; non-param leaves (step counts) pass through."""
    return optax.tree_map_params(
        tx,
        lambda leaf, s: jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, s)),
        opt_state, specs,
        transform_non_params=lambda leaf: leaf)


def shard_opt_state(tx: optax.GradientTransformation, params: Any,
                    opt_state: Any, sync: "GradSyncConfig",
                    mesh: Optional[Mesh] = None) -> Any:
    """Re-layout a fresh optimizer state for the sharded update (used by
    `init_state`): moments land sharded over `sync.update_axes` so they never
    materialize replicated."""
    mesh = mesh or _mesh_of(params)
    if mesh is None or not sync.sharded_update:
        return opt_state
    specs = build_update_specs(params, mesh, sync.update_axes)
    return jax.jit(lambda o: constrain_opt_state(tx, o, specs, mesh))(opt_state)


def abstract_sharded_opt_state(tx: optax.GradientTransformation,
                               params_structs: Any, mesh: Mesh,
                               axes: Sequence[str] = ("dp", "fsdp")) -> Any:
    """ShapeDtypeStructs of tx.init(params) with the sharded-update shardings
    attached — AOT-lowering input for HBM-budget dryruns (nothing
    materializes)."""
    opt_shapes = jax.eval_shape(tx.init, params_structs)
    specs = build_update_specs(params_structs, mesh, axes)
    return optax.tree_map_params(
        tx,
        lambda leaf, s: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, s)),
        opt_shapes, specs,
        transform_non_params=lambda leaf: leaf)


def opt_state_bytes_per_shard(opt_state_structs: Any) -> int:
    """Per-device bytes of an (abstract or concrete) optimizer state, honoring
    each leaf's sharding — the HBM-budget number the dryrun asserts on."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(opt_state_structs):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        s = getattr(leaf, "sharding", None)
        if isinstance(s, NamedSharding):
            shape = s.shard_shape(shape)
        total += int(np.prod(shape or (1,))) * jnp.dtype(leaf.dtype).itemsize
    return total


# ------------------------------------------------------------ step factory

def _check_model_compat(cfg) -> None:
    if getattr(cfg, "pipeline_stages", 1) > 1:
        raise ValueError(
            "bucketed grad sync opens its own dp-manual shard_map and does "
            "not compose with pipeline_stages > 1 (nested shard_map)")
    if getattr(cfg, "attention_impl", "auto") in ("ring", "ulysses"):
        raise ValueError(
            "bucketed grad sync does not compose with ring/ulysses attention "
            "(nested shard_map); use mode='gspmd'")


class GradSyncStep:
    """A train step with explicit grad sync. Callable like the stock jitted
    step (`state, batch -> state, metrics`) and `.lower()`-able for AOT
    compiles; builds its jitted program lazily on first use because the
    bucket layout and update specs depend on the state's actual shardings."""

    def __init__(self, cfg, tx, loss_fn, sync: GradSyncConfig, donate: bool):
        self.cfg = cfg
        self.tx = tx
        self.loss_fn = loss_fn
        self.sync = sync
        self.donate = donate
        self.buckets: Optional[List[List[int]]] = None
        self.mesh: Optional[Mesh] = None
        self._fn = None
        self._batch_treedef = None

    # -- lazy build
    def _setup(self, state, batch) -> Optional[dict]:
        """Shared first-call analysis: mesh/spec discovery, model-compat
        checks, and the traced sub-functions both step flavors compose.
        Returns None when the program is already built (after guarding
        against a changed batch schema)."""
        treedef = jax.tree_util.tree_structure(batch)
        if self._fn is not None:
            if treedef != self._batch_treedef:
                raise ValueError(
                    f"batch structure changed after the step was built "
                    f"({self._batch_treedef} -> {treedef}); create a new "
                    "train step per batch schema")
            return None
        self._batch_treedef = treedef
        sync = self.sync
        mesh = _mesh_of(state.params)
        self.mesh = mesh
        # explicit sync needs a mesh carrying the sync axis; otherwise
        # (single device / unsharded state) there is nothing to reduce over
        # and the implicit GSPMD path is the same program minus the wrapper
        explicit = sync.mode == "bucketed" and mesh is not None \
            and sync.axis in mesh.axis_names
        if explicit:
            _check_model_compat(self.cfg)
        sharded = sync.sharded_update and mesh is not None
        return {
            "mesh": mesh,
            "explicit": explicit,
            "sharded": sharded,
            "uspecs": build_update_specs(state.params, mesh, sync.update_axes)
                      if sharded else None,
            "pspecs": param_specs(state.params) if sharded else None,
            "grads_of": self._make_grads_fn(mesh, state, batch)
                        if explicit else None,
        }

    def _grads_stage(self, ctx, params, step, batch):
        """(loss, aux, synced grads) — explicit bucketed sync or the stock
        implicit GSPMD gradient. Traced inside the jitted step."""
        if ctx["explicit"]:
            key = None
            sync = self.sync
            if sync.compression == "int8" and sync.stochastic_rounding:
                key = jax.random.fold_in(jax.random.PRNGKey(0xE0A), step)
            return ctx["grads_of"](params, batch, key)
        (loss, aux), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(params, batch, self.cfg)
        return loss, aux, grads

    def _update_stage(self, ctx, state, grads, aux):
        """(new TrainState, metrics) — replicated or cross-replica-sharded
        optimizer update. Traced inside the jitted step."""
        from .step import TrainState

        tx, mesh = self.tx, ctx["mesh"]
        metrics = dict(aux)
        if ctx["explicit"] and "tokens" in metrics:
            metrics["tokens"] = metrics["tokens"] * mesh.shape[self.sync.axis]
        metrics["grad_norm"] = optax.global_norm(grads)
        if ctx["sharded"]:
            uspecs, pspecs = ctx["uspecs"], ctx["pspecs"]
            g = _constrain(grads, uspecs, mesh)
            p = _constrain(state.params, uspecs, mesh)
            opt = constrain_opt_state(tx, state.opt_state, uspecs, mesh)
            updates, new_opt = tx.update(g, opt, p)
            new_opt = constrain_opt_state(tx, new_opt, uspecs, mesh)
            new_params = optax.apply_updates(p, updates)
            new_params = _constrain(new_params, pspecs, mesh)
        else:
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        return TrainState(state.step + 1, new_params, new_opt), metrics

    def _ensure(self, state, batch) -> None:
        ctx = self._setup(state, batch)
        if ctx is None:
            return

        def impl(state, batch):
            loss, aux, grads = self._grads_stage(ctx, state.params, state.step,
                                                 batch)
            return self._update_stage(ctx, state, grads, aux)

        self._fn = jax.jit(impl, donate_argnums=(0,) if self.donate else ())

    def _make_grads_fn(self, mesh, state, batch):
        """(params, batch, key) -> (loss, aux, synced grads): the dp-manual
        shard_map region with per-bucket collectives."""
        sync, cfg, loss_fn = self.sync, self.cfg, self.loss_fn
        from ray_tpu.parallel.sharding import manual_axes

        grads_shape = jax.eval_shape(
            lambda p, b: jax.grad(lambda q: loss_fn(q, b, cfg)[0])(p),
            state.params, batch)
        self.buckets = partition_buckets(grads_shape, sync.bucket_bytes)
        aux_shape = jax.eval_shape(
            lambda p, b: loss_fn(p, b, cfg)[1], state.params, batch)

        def body(params, batch, key):
            with manual_axes(sync.axis):
                (loss, aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, batch, cfg)
                grads = _sync_bucketed(grads, sync.axis, sync, key)
                loss = jax.lax.pmean(loss, sync.axis)
                aux = jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(x, sync.axis), aux)
            return loss, aux, grads

        pspec = jax.tree_util.tree_map(lambda _: P(), state.params)
        bspec = jax.tree_util.tree_map(lambda _: P(sync.axis), batch)
        aux_spec = jax.tree_util.tree_map(lambda _: P(), aux_shape)
        gspec = jax.tree_util.tree_map(lambda _: P(), grads_shape)
        return _shard_map(
            body, mesh,
            in_specs=(pspec, bspec, P()),
            out_specs=(P(), aux_spec, gspec),
            manual=(sync.axis,))

    # -- public surface
    @hot_path
    def __call__(self, state, batch):
        self._ensure(state, batch)
        return self._fn(state, batch)

    def lower(self, state, batch):
        self._ensure(state, batch)
        return self._fn.lower(state, batch)


class InstrumentedGradSyncStep(GradSyncStep):
    """Two-stage variant for `GradSyncConfig(telemetry=True)`: a grads program
    and an update program, so the host observes per-bucket readiness and
    reports grad-sync phases (`train.step_phase` spans around bucket waits +
    `train_grad_sync_seconds{phase}`). Trades the grads/update fusion for
    observability — a diagnostics mode, not the headline-MFU path."""

    def _ensure(self, state, batch) -> None:
        ctx = self._setup(state, batch)
        if ctx is None:
            return
        self._grads_fn = jax.jit(
            lambda params, step, batch: self._grads_stage(ctx, params, step,
                                                          batch))
        self._update_fn = jax.jit(
            lambda state, grads, aux: self._update_stage(ctx, state, grads,
                                                         aux),
            donate_argnums=(0, 1) if self.donate else ())
        self._fn = self._run

    def _phase(self, name: str):
        from . import session
        from ray_tpu.util import telemetry

        class _Ctx:
            def __enter__(_s):
                _s.t0 = time.perf_counter()
                _s.inner = session.step_phase(name)
                _s.inner.__enter__()
                return _s

            def __exit__(_s, *exc):
                _s.inner.__exit__(*exc)
                telemetry.get_histogram(
                    "train_grad_sync_seconds",
                    "per-phase gradient-sync time (grad_sync telemetry mode)",
                    tag_keys=("phase",)).observe(
                        time.perf_counter() - _s.t0, tags={"phase": name})
                return False

        return _Ctx()

    def _run(self, state, batch):
        with self._phase("grad_sync.forward_backward"):
            loss, aux, grads = self._grads_fn(state.params, state.step, batch)
            # jit dispatch is async: without a sync point this phase would
            # time only the enqueue and the fwd/bwd compute would be
            # misattributed to the first bucket wait. Blocking on the loss
            # bounds the phase at loss production; bucket waits then measure
            # each bucket's readiness tail beyond that point.
            jax.block_until_ready(loss)
        leaves = jax.tree_util.tree_leaves(grads)
        for b, idxs in enumerate(self.buckets or [list(range(len(leaves)))]):
            with self._phase("grad_sync.bucket_wait"):
                jax.block_until_ready([leaves[i] for i in idxs])
        with self._phase("grad_sync.optimizer"):
            new_state, metrics = self._update_fn(state, grads, aux)
            jax.block_until_ready(new_state.params)
        return new_state, metrics

    def lower(self, state, batch):  # pragma: no cover - diagnostics mode
        raise NotImplementedError(
            "InstrumentedGradSyncStep is a two-program step; AOT-lower the "
            "fused step (telemetry=False) instead")


def make_step(cfg, tx, loss_fn, sync: GradSyncConfig, donate: bool = True):
    """Factory `train.step.make_train_step` delegates to for non-default
    sync configs."""
    cls = InstrumentedGradSyncStep if sync.telemetry else GradSyncStep
    return cls(cfg, tx, loss_fn, sync, donate)


# -------------------------------------------------------- HLO inspection

_RED_RE = r"=\s*\S+\s+(all-reduce|reduce-scatter|all-gather)"
_COMPUTE_RE = r"=\s*\S+\s+(fusion|dot|while|convolution|custom-call)"


def overlap_report(compiled_or_text) -> Dict[str, Any]:
    """Inspect a compiled step's HLO for reduction placement — the check that
    bucketed reductions are NOT all sunk to the end of the program.

    Returns op counts and positions within the entry computation:
    `n_reductions` (distinct collective ops), `first_reduction_pos` /
    `last_compute_pos` (instruction indices), and `all_sunk_to_end` (True when
    every collective sits after the last compute op — the monolithic
    pathology the bucketed mode exists to break up).
    """
    import re

    txt = compiled_or_text if isinstance(compiled_or_text, str) \
        else compiled_or_text.as_text()
    entry: List[str] = []
    in_entry = False
    for line in txt.splitlines():
        s = line.strip()
        if s.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry:
            if s.startswith("}"):
                break
            entry.append(s)
    red = [i for i, l in enumerate(entry) if re.search(_RED_RE, l)]
    compute = [i for i, l in enumerate(entry) if re.search(_COMPUTE_RE, l)]
    return {
        "n_instructions": len(entry),
        "n_reductions": len(red),
        "first_reduction_pos": red[0] if red else None,
        "last_reduction_pos": red[-1] if red else None,
        "last_compute_pos": compute[-1] if compute else None,
        "n_compute_after_first_reduction":
            sum(1 for i in compute if i > red[0]) if red else 0,
        "all_sunk_to_end":
            bool(red) and bool(compute) and red[0] > compute[-1],
    }
