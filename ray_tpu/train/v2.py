"""Train v2: controller-actor architecture with pluggable failure/scaling policies.

Capability parity: reference python/ray/train/v2/ (gated by RAY_TRAIN_V2_ENABLED) —
TrainController state machine (v2/_internal/execution/controller/controller.py:94),
FailurePolicy (failure_handling/failure_policy.py:14), ScalingPolicy /
FixedScalingPolicy (scaling_policy/scaling_policy.py:29, fixed.py:13). The
controller drives the existing BackendExecutor through explicit state
transitions, consulting the failure policy on worker-group failure and the
scaling policy before each (re)start — the seam elastic training plugs into.
Enable through a trainer with RAY_TPU_TRAIN_V2_ENABLED=1 or use TrainController
directly.
"""
from __future__ import annotations

import dataclasses
import enum
import logging
import time
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.core.exceptions import ActorError, RayTpuError

from .backend_executor import BackendExecutor, TrainingFailedError, restart_backoff_s
from .checkpoint import Checkpoint
from .result import Result

logger = logging.getLogger(__name__)


def _failure_kind(e: Exception) -> str:
    """Classify a worker-group failure for policies/logs without parsing
    tracebacks: a TrainingFailedError carries the failed worker's exception
    type (e.g. "CollectiveAbortError" — a peer rank died mid-op and the
    group was poisoned); anything else classifies as its own type."""
    kind = getattr(e, "error_type", None)
    return kind or type(e).__name__


class TrainControllerState(enum.Enum):
    INITIALIZING = "INITIALIZING"
    SCHEDULING = "SCHEDULING"
    RUNNING = "RUNNING"
    RESTARTING = "RESTARTING"
    RESIZING = "RESIZING"
    ERRORED = "ERRORED"
    FINISHED = "FINISHED"


class FailureDecision(enum.Enum):
    RETRY = "RETRY"
    RAISE = "RAISE"


class FailurePolicy:
    """Decides what to do when the worker group fails (reference failure_policy.py:14)."""

    def make_decision(self, error: Exception, failure_count: int) -> FailureDecision:
        raise NotImplementedError


class DefaultFailurePolicy(FailurePolicy):
    """Retry up to max_failures times (-1 = unlimited), then raise."""

    def __init__(self, max_failures: int = 0):
        self.max_failures = max_failures

    def make_decision(self, error: Exception, failure_count: int) -> FailureDecision:
        if self.max_failures < 0 or failure_count <= self.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.RAISE


@dataclasses.dataclass
class ResizeDecision:
    num_workers: int


class NoopDecision:
    pass


class ScalingPolicy:
    """Sizes the worker group (reference scaling_policy.py:29)."""

    def make_decision_for_non_running_worker_group(self) -> ResizeDecision:
        raise NotImplementedError

    def monitor(self, executor: BackendExecutor):
        """Called each poll while RUNNING; return ResizeDecision to trigger a resize."""
        return NoopDecision()


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured size (reference fixed.py:13)."""

    def __init__(self, scaling_config):
        self.scaling_config = scaling_config

    def make_decision_for_non_running_worker_group(self) -> ResizeDecision:
        return ResizeDecision(self.scaling_config.num_workers)


class ElasticScalingPolicy(ScalingPolicy):
    """Size to available cluster resources inside [min_workers, max_workers].

    A minimal elastic policy: before each (re)start, fit the group to the CPUs
    (or TPUs) the cluster can currently grant — the shape the reference's v2
    elastic design targets."""

    def __init__(self, min_workers: int, max_workers: int, scaling_config=None):
        assert 1 <= min_workers <= max_workers
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.scaling_config = scaling_config

    def make_decision_for_non_running_worker_group(self) -> ResizeDecision:
        res = ray_tpu.available_resources()
        sc = self.scaling_config
        use_tpu = bool(sc and getattr(sc, "use_tpu", False))
        if use_tpu:
            per = float(getattr(sc, "chips_per_worker", 1.0)) or 1.0
        else:
            per = float(getattr(sc, "cpus_per_worker", 1.0)) or 1.0
        avail = res.get("TPU" if use_tpu else "CPU", 0.0)
        fit = int(avail // per)
        return ResizeDecision(max(self.min_workers, min(self.max_workers, fit)))


class TrainController:
    """Poll-driven state machine around a BackendExecutor (reference controller.py:94)."""

    def __init__(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        *,
        backend_config,
        scaling_config,
        run_config,
        checkpoint_manager=None,
        failure_policy: Optional[FailurePolicy] = None,
        scaling_policy: Optional[ScalingPolicy] = None,
        train_loop_config: Optional[Dict[str, Any]] = None,
        datasets: Optional[Dict[str, Any]] = None,
        experiment_name: str = "train_v2",
        resume_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_fn = train_fn
        self.train_loop_config = train_loop_config or {}
        self.datasets = datasets
        self.resume_checkpoint = resume_checkpoint
        self.backend_config = backend_config
        self.scaling_config = scaling_config
        self.run_config = run_config
        self.checkpoint_manager = checkpoint_manager
        self.failure_policy = failure_policy or DefaultFailurePolicy(
            run_config.failure_config.max_failures if run_config.failure_config else 0)
        self.scaling_policy = scaling_policy or FixedScalingPolicy(scaling_config)
        self.experiment_name = experiment_name
        self.state = TrainControllerState.INITIALIZING
        self.failure_count = 0
        self.executor: Optional[BackendExecutor] = None
        self._state_log: list = [self.state]
        # metric history survives executor replacement across restarts/resizes
        self._merged_history: list = []
        self._latest_metrics: Dict[str, Any] = {}  # {} matches the v1 no-reports shape

    def _transition(self, state: TrainControllerState) -> None:
        logger.info("TrainController: %s -> %s", self.state.value, state.value)
        self.state = state
        self._state_log.append(state)

    def _build_executor(self, num_workers: int) -> BackendExecutor:
        import copy as _copy

        sc = _copy.copy(self.scaling_config)
        sc.num_workers = num_workers
        return BackendExecutor(
            backend_config=self.backend_config,
            scaling_config=sc,
            checkpoint_manager=self.checkpoint_manager,
            failure_config=None,  # the controller owns failure handling in v2
            experiment_name=self.experiment_name,
        )

    def _retire_executor(self, graceful: bool) -> None:
        """Absorb the executor's metric history before replacing/dropping it."""
        if self.executor is None:
            return
        self._merged_history.extend(self.executor._history)
        if self.executor._latest_metrics:  # a crashed-before-report executor holds {}
            self._latest_metrics = self.executor._latest_metrics
        self.executor.shutdown(graceful=graceful)
        self.executor = None

    def run(self) -> Result:
        error: Optional[str] = None
        checkpoint: Optional[Checkpoint] = self.resume_checkpoint
        if self.checkpoint_manager is not None:
            checkpoint = self.checkpoint_manager.latest_checkpoint or checkpoint
        while self.state not in (TrainControllerState.ERRORED, TrainControllerState.FINISHED):
            if self.state in (TrainControllerState.INITIALIZING,
                              TrainControllerState.RESTARTING,
                              TrainControllerState.RESIZING):
                decision = self.scaling_policy.make_decision_for_non_running_worker_group()
                self._transition(TrainControllerState.SCHEDULING)
                # resume from whatever is durable NOW — the failure path's
                # salvage drain may have registered checkpoints after the
                # caller's last refresh
                if self.checkpoint_manager is not None:
                    checkpoint = self.checkpoint_manager.latest_checkpoint or checkpoint
                self.executor = self._build_executor(decision.num_workers)
                try:
                    self.executor.start()
                    self.executor.start_training(
                        self.train_fn, self.train_loop_config, self.datasets, checkpoint)
                except (TrainingFailedError, ActorError, RayTpuError) as e:
                    if not self._on_failure(e):
                        error = str(e)
                    continue
                self._transition(TrainControllerState.RUNNING)
            elif self.state == TrainControllerState.RUNNING:
                try:
                    poll = self.executor.poll()
                except (TrainingFailedError, ActorError, RayTpuError) as e:
                    if self.checkpoint_manager is not None:
                        checkpoint = self.checkpoint_manager.latest_checkpoint or checkpoint
                    if not self._on_failure(e):
                        error = str(e)
                    continue
                self._last_all_metrics = self.executor.all_metrics()
                if poll["finished"]:
                    self._transition(TrainControllerState.FINISHED)
                    continue
                resize = self.scaling_policy.monitor(self.executor)
                if isinstance(resize, ResizeDecision) and (
                        resize.num_workers != self.executor.scaling_config.num_workers):
                    if self.checkpoint_manager is not None:
                        checkpoint = self.checkpoint_manager.latest_checkpoint or checkpoint
                    self._retire_executor(graceful=True)
                    self._transition(TrainControllerState.RESIZING)
                    continue
                time.sleep(self.executor.poll_interval_s)
            else:  # SCHEDULING handled inline above
                break
        latest = self.checkpoint_manager.latest_checkpoint if self.checkpoint_manager else None
        best = self.checkpoint_manager.best_checkpoint if self.checkpoint_manager else None
        self._retire_executor(graceful=True)
        return Result(metrics=self._latest_metrics, checkpoint=latest, best_checkpoint=best,
                      error=error, metrics_dataframe=list(self._merged_history),
                      all_metrics=list(getattr(self, "_last_all_metrics", [])))

    def _on_failure(self, e: Exception) -> bool:
        """Returns True if retrying. Shuts the group down either way."""
        self.failure_count += 1
        decision = self.failure_policy.make_decision(e, self.failure_count)
        logger.warning("TrainController failure #%d (%s, %s): %s",
                       self.failure_count, decision.value, _failure_kind(e), e)
        if self.executor is not None:
            # Unblock survivors stuck in a collective (abort beats the op
            # timeout), then salvage their already-reported checkpoints
            # before the non-graceful teardown discards the workers.
            self.executor.salvage_after_failure(e)
        self._retire_executor(graceful=False)
        if decision == FailureDecision.RETRY:
            self._transition(TrainControllerState.RESTARTING)
            # bounded exponential backoff: a flapping node or bad checkpoint
            # must not hot-spin worker-group construction
            time.sleep(restart_backoff_s(self.failure_count))
            return True
        self._transition(TrainControllerState.ERRORED)
        return False
