"""DataParallelTrainer / JaxTrainer: the stock Trainer API.

Reference capability: python/ray/train/data_parallel_trainer.py:26 (SPMD: run
train_loop_per_worker on N workers) + base_trainer.py:651 (fit()). The reference routes
fit() through a 1-trial Tune run; here fit() drives the BackendExecutor directly and the
Tune integration wraps trainers the other way around (ray_tpu.tune can take a Trainer as a
trainable), which keeps the hot path free of trial bookkeeping.

JaxTrainer is the piece SURVEY.md §2.4 calls out as new work: the reference has no JAX
trainer; this one follows the Backend-plugin shape with jax.distributed bootstrap.
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Union

from ..air.config import RunConfig, ScalingConfig
from .backend import BackendConfig
from .backend_executor import BackendExecutor
from .checkpoint import Checkpoint
from .tensorflow_backend import TensorflowConfig
from .torch_backend import TorchConfig
from .checkpoint_manager import CheckpointManager
from .jax_backend import JaxConfig
from .result import Result

TrainLoop = Union[Callable[[], None], Callable[[Dict[str, Any]], None]]


def _default_storage_path() -> str:
    from ray_tpu.config import CONFIG

    return CONFIG.storage_path or os.path.join(os.path.expanduser("~"), "ray_tpu_results")


class DataParallelTrainer:
    _default_backend_config: Callable[[], BackendConfig] = BackendConfig

    def __init__(
        self,
        train_loop_per_worker: TrainLoop,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or type(self)._default_backend_config()
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        from ray_tpu.usage import record_library_usage

        record_library_usage("train")
        name = self.run_config.name or f"train_{time.strftime('%Y%m%d_%H%M%S')}"
        storage_path = self.run_config.storage_path or _default_storage_path()
        from . import storage as _storage

        run_dir = _storage.join_any(storage_path, name)
        ckpt_manager = CheckpointManager(run_dir, self.run_config.checkpoint_config)
        train_fn = _normalize_train_fn(self.train_loop_per_worker)
        from ray_tpu.config import CONFIG as _cfg

        if _cfg.train_v2_enabled:
            # v2 controller path (reference RAY_TRAIN_V2_ENABLED gate)
            from .v2 import TrainController

            controller = TrainController(
                train_fn,
                backend_config=self.backend_config,
                scaling_config=self.scaling_config,
                run_config=self.run_config,
                checkpoint_manager=ckpt_manager,
                train_loop_config=self.train_loop_config,
                datasets=self.datasets,
                experiment_name=name,
                resume_checkpoint=self.resume_from_checkpoint,
            )
            result = controller.run()
            result.path = run_dir
            return result
        executor = BackendExecutor(
            backend_config=self.backend_config,
            scaling_config=self.scaling_config,
            checkpoint_manager=ckpt_manager,
            failure_config=self.run_config.failure_config,
            experiment_name=name,
        )
        try:
            result = executor.run_until_complete(
                train_fn,
                self.train_loop_config,
                datasets=self.datasets,
                resume_checkpoint=self.resume_from_checkpoint,
            )
        finally:
            executor.shutdown()
        result.path = run_dir
        return result


def _normalize_train_fn(fn: TrainLoop) -> Callable[[Dict[str, Any]], None]:
    import inspect

    sig = inspect.signature(fn)
    if len(sig.parameters) == 0:
        return lambda config: fn()
    return fn  # type: ignore[return-value]


class JaxTrainer(DataParallelTrainer):
    """Train-shaped JAX trainer (north star: SURVEY.md §7 phase 3)."""

    _default_backend_config = JaxConfig


class TorchTrainer(DataParallelTrainer):
    """Torch trainer over a gloo process group (reference TorchTrainer,
    python/ray/train/torch/torch_trainer.py; CPU torch — the TPU path is
    JaxTrainer). DDP wrap via ray_tpu.train.torch.prepare_model."""

    _default_backend_config = TorchConfig


class TensorflowTrainer(DataParallelTrainer):
    """TF multi-worker trainer: workers get a TF_CONFIG cluster spec so
    MultiWorkerMirroredStrategy coordinates over the group (reference
    TensorflowTrainer, python/ray/train/tensorflow/tensorflow_trainer.py)."""

    _default_backend_config = TensorflowConfig
