"""PyTorch Lightning integration for TorchTrainer loops.

Capability parity: reference python/ray/train/lightning/_lightning_utils.py —
RayDDPStrategy (:57, DDP over the session's torch process group),
RayLightningEnvironment (:177, rank/world-size answered from the Train
context instead of SLURM/env detection), RayTrainReportCallback (:239,
per-epoch-end metric+checkpoint report), prepare_trainer (:209, validate the
strategy/environment combination).

Lightning is optional in this image; every entry point imports it lazily and
raises a clear error when absent. CPU torch is the supported device — the TPU
path is JaxTrainer.
"""
from __future__ import annotations

import os
import tempfile
from typing import Any


def _pl():
    try:
        import pytorch_lightning as pl
        return pl
    except ImportError:
        try:
            import lightning.pytorch as pl  # the renamed distribution
            return pl
        except ImportError as e:
            raise ImportError(
                "ray_tpu.train.lightning requires 'pytorch_lightning' (or "
                "'lightning'), which is not installed in this environment."
            ) from e


def RayDDPStrategy(**kwargs: Any):
    """DDPStrategy that trusts the session's already-initialized gloo group
    (reference RayDDPStrategy :57)."""
    pl = _pl()

    class _Impl(pl.strategies.DDPStrategy):
        def __init__(self):
            super().__init__(**kwargs)

        @property
        def root_device(self):
            import torch

            return torch.device("cpu")

        @property
        def distributed_sampler_kwargs(self):
            from . import session

            ctx = session.get_context()
            return dict(num_replicas=ctx.get_world_size(),
                        rank=ctx.get_world_rank())

    return _Impl()


def RayLightningEnvironment():
    """ClusterEnvironment answering rank/world-size from the Train session
    (reference RayLightningEnvironment :177)."""
    pl = _pl()
    from lightning_fabric.plugins.environments import LightningEnvironment  # type: ignore

    class _Impl(LightningEnvironment):
        def world_size(self) -> int:
            from . import session

            return session.get_context().get_world_size()

        def global_rank(self) -> int:
            from . import session

            return session.get_context().get_world_rank()

        def local_rank(self) -> int:
            from . import session

            return session.get_context().get_local_rank()

        def node_rank(self) -> int:
            from . import session

            return session.get_context().get_node_rank()

        def set_world_size(self, size: int) -> None:
            pass  # the worker group owns this

        def set_global_rank(self, rank: int) -> None:
            pass

        def teardown(self):
            pass

    del pl
    return _Impl()


def RayTrainReportCallback():
    """pl.Callback: on_train_epoch_end → session.report(metrics, checkpoint)
    (reference RayTrainReportCallback :239)."""
    pl = _pl()

    class _Impl(pl.callbacks.Callback):
        CHECKPOINT_NAME = "checkpoint.ckpt"

        def on_train_epoch_end(self, trainer, pl_module):
            from . import session
            from .checkpoint import Checkpoint

            metrics = {k: (v.item() if hasattr(v, "item") else v)
                       for k, v in trainer.callback_metrics.items()}
            metrics["epoch"] = trainer.current_epoch
            metrics["step"] = trainer.global_step
            ckpt = None
            tmpdir = None
            # rank 0 only: DDP ranks hold identical weights
            if session.get_context().get_world_rank() == 0:
                tmpdir = tempfile.mkdtemp(prefix="pl_ckpt_")
                trainer.save_checkpoint(
                    os.path.join(tmpdir, self.CHECKPOINT_NAME), weights_only=False)
                ckpt = Checkpoint.from_directory(tmpdir)
            session.report(metrics, checkpoint=ckpt)
            if tmpdir is not None:
                # report() stages the checkpoint before returning
                import shutil

                shutil.rmtree(tmpdir, ignore_errors=True)

    return _Impl()


def prepare_trainer(trainer):
    """Validate that the pl.Trainer uses the Ray strategy/environment pair
    (reference prepare_trainer :209)."""
    cls_name = type(trainer.strategy).__name__
    if cls_name not in ("_Impl", "SingleDeviceStrategy") and "DDP" in cls_name:
        raise RuntimeError(
            "pl.Trainer inside a TorchTrainer loop must use "
            "ray_tpu.train.lightning.RayDDPStrategy (got "
            f"{cls_name}) so DDP rides the session's process group.")
    return trainer
