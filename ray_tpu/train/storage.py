"""Pluggable checkpoint storage: URI-addressed run directories over fsspec.

Capability parity: reference python/ray/train/_internal/storage.py:358
(StorageContext over a pyarrow filesystem — workers UPLOAD checkpoints to
shared storage, the controller tracks URIs, restore DOWNLOADS on any host).
Here fsspec is the backend, so ``RunConfig(storage_path="gs://bucket/exp")``
works wherever an fsspec implementation for the scheme is installed.

A plain path (no ``scheme://``) keeps the zero-copy local behavior: staging
moves directories on one filesystem and never round-trips bytes.

The ``mock://`` scheme is a deliberately-indirect remote store for tests: it is
backed by the directory named in ``RAY_TPU_MOCK_FS_ROOT`` but reachable ONLY
through explicit upload/download calls — code that survives it never relied on
workers and controller sharing a filesystem.
"""
from __future__ import annotations

import os
import shutil
from typing import List, Tuple


def is_remote(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def normalize(path: str) -> str:
    """Strip the file:// scheme to a plain local path (remote URIs unchanged):
    every storage entry point must call this so "file:///mnt/nfs/exp" is never
    mistaken for a relative path named "file:"."""
    if path.startswith("file://"):
        return path[len("file://"):] or "/"
    return path


def join_any(base: str, *parts: str) -> str:
    """Remote-aware path join (THE helper for run-dir / checkpoint addressing)."""
    base = normalize(base)
    if is_remote(base):
        return join(base, *parts)
    return os.path.join(base, *parts)


def get_fs(uri: str) -> Tuple[object, str]:
    """(fsspec filesystem, path within it) for a URI."""
    import fsspec

    scheme, _, rest = uri.partition("://")
    if scheme == "mock":
        import tempfile

        root = (os.environ.get("RAY_TPU_MOCK_FS_ROOT")
                or os.path.join(tempfile.gettempdir(), "ray_tpu_mock_fs"))
        os.makedirs(root, exist_ok=True)
        fs = fsspec.filesystem("dir", path=root)
        return fs, rest
    fs, path = fsspec.core.url_to_fs(uri)
    return fs, path


def join(uri: str, *parts: str) -> str:
    return "/".join([uri.rstrip("/"), *parts])


def upload_dir(local_dir: str, uri: str) -> None:
    """Recursively copy a local directory's CONTENTS into uri."""
    fs, root = get_fs(uri)
    fs.makedirs(root, exist_ok=True)
    for dirpath, _, files in os.walk(local_dir):
        rel = os.path.relpath(dirpath, local_dir)
        target = root if rel == "." else f"{root}/{rel.replace(os.sep, '/')}"
        if rel != ".":
            fs.makedirs(target, exist_ok=True)
        for fn in files:
            fs.put_file(os.path.join(dirpath, fn), f"{target}/{fn}")


def download_dir(uri: str, local_dir: str) -> None:
    """Recursively copy uri's contents into a local directory (empty
    subdirectories included, so a checkpoint round-trips structurally intact)."""
    fs, root = get_fs(uri)
    os.makedirs(local_dir, exist_ok=True)
    base = root.rstrip("/")
    for f, info in fs.find(base, withdirs=True, detail=True).items():
        rel = f[len(base):].lstrip("/")
        if not rel:
            continue
        dst = os.path.join(local_dir, *rel.split("/"))
        if info.get("type") == "directory":
            os.makedirs(dst, exist_ok=True)
        else:
            os.makedirs(os.path.dirname(dst) or local_dir, exist_ok=True)
            fs.get_file(f, dst)


def exists(uri: str) -> bool:
    fs, root = get_fs(uri)
    return bool(fs.exists(root))


def listdir(uri: str) -> List[str]:
    """Child entry NAMES under uri ([] when absent)."""
    fs, root = get_fs(uri)
    if not fs.exists(root):
        return []
    return sorted(p.rstrip("/").rsplit("/", 1)[-1] for p in fs.ls(root, detail=False))


def delete(uri: str) -> None:
    """Best-effort recursive delete: pruning a stale checkpoint must never
    fail a training run (matches the local rmtree(ignore_errors=True))."""
    fs, root = get_fs(uri)
    try:
        fs.rm(root, recursive=True)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:  # noqa: BLE001 — transient object-store errors included
        pass


def move(src_uri: str, dst_uri: str) -> None:
    """Rename within one filesystem (both URIs must share a scheme/root)."""
    fs, src = get_fs(src_uri)
    _, dst = get_fs(dst_uri)
    fs.makedirs(dst.rsplit("/", 1)[0], exist_ok=True)
    fs.mv(src, dst, recursive=True)


def read_bytes(uri: str):
    fs, root = get_fs(uri)
    if not fs.exists(root):
        return None
    with fs.open(root, "rb") as f:
        return f.read()


def write_bytes(uri: str, data: bytes) -> None:
    fs, root = get_fs(uri)
    parent = root.rsplit("/", 1)[0]
    if parent:
        fs.makedirs(parent, exist_ok=True)
    with fs.open(root, "wb") as f:
        f.write(data)


def persist_dir(local_or_uri: str, dest_uri_or_dir: str) -> str:
    """Move a (possibly local) checkpoint into its durable location; returns
    the durable address. Local->local moves; anything else copies through the
    fs abstraction."""
    src_remote, dst_remote = is_remote(local_or_uri), is_remote(dest_uri_or_dir)
    if not src_remote and not dst_remote:
        if os.path.abspath(local_or_uri) != os.path.abspath(dest_uri_or_dir):
            try:
                shutil.move(local_or_uri, dest_uri_or_dir)
            except (OSError, shutil.Error):
                shutil.copytree(local_or_uri, dest_uri_or_dir, dirs_exist_ok=True)
        return dest_uri_or_dir
    if src_remote and dst_remote:
        move(local_or_uri, dest_uri_or_dir)
        return dest_uri_or_dir
    if not src_remote and dst_remote:
        upload_dir(local_or_uri, dest_uri_or_dir)
        shutil.rmtree(local_or_uri, ignore_errors=True)
        return dest_uri_or_dir
    # remote -> local
    download_dir(local_or_uri, dest_uri_or_dir)
    delete(local_or_uri)
    return dest_uri_or_dir
