"""Per-worker training session: the `ray_tpu.train.report()` plumbing.

Reference capability: python/ray/train/_internal/session.py — _TrainSession (:112),
report (:405), public ray.train.report (:672) and get_context
(python/ray/train/context.py:117). The user's train loop runs on a daemon thread inside
the worker actor; report() enqueues (metrics, checkpoint) for the driver-side executor to
drain. Checkpoints are staged into run storage *before* report() returns (worker-side
persistence, like Train v2's storage upload), so callers may delete their local snapshot
directory immediately after reporting.
"""
from __future__ import annotations

import contextlib
import os
import queue
import shutil
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ray_tpu.util import telemetry

from .checkpoint import Checkpoint

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


@dataclass
class TrainContext:
    """Reference: ray.train.get_context() — world/rank topology of the worker group."""

    world_size: int
    world_rank: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str = ""
    trial_name: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name


class _TrainSession:
    def __init__(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        config: Dict[str, Any],
        context: TrainContext,
        checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[Dict[str, Any]] = None,
        staging_dir: Optional[str] = None,
    ):
        self.train_fn = train_fn
        self.config = config
        self.context = context
        self.starting_checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.staging_dir = staging_dir
        self.results: "queue.Queue" = queue.Queue()
        self.error: Optional[BaseException] = None
        self.finished = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        def run():
            global _session
            try:
                self.train_fn(self.config)
            except BaseException as e:  # noqa: BLE001 — report worker crash faithfully
                self.error = e
            finally:
                self.finished.set()

        self._thread = threading.Thread(target=run, daemon=True, name="train_loop")
        self._thread.start()

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
        if checkpoint is not None and self.staging_dir is not None:
            # Stage into run storage now: the caller may delete its snapshot dir the
            # moment report() returns, long before the driver polls.
            from . import storage

            # remote staging UPLOADS from this worker's host (reference
            # _internal/storage.py persist_to_storage on the worker); local
            # staging keeps the zero-copy move
            if not storage.is_remote(self.staging_dir):
                os.makedirs(self.staging_dir, exist_ok=True)
            dest = storage.join_any(self.staging_dir,
                                    f"staged_{uuid.uuid4().hex[:12]}")
            storage.persist_dir(checkpoint.path, dest)
            checkpoint = Checkpoint(dest)
        self._record_report(metrics)
        self.results.put({"metrics": metrics, "checkpoint": checkpoint})

    def _record_report(self, metrics: Dict[str, Any]) -> None:
        """Train load signals: an MFU gauge whenever the loop reports one
        (bench.py's trainer path does), plus a timeline event per report."""
        try:
            tags = {"rank": str(self.context.world_rank)}
            mfu = metrics.get("mfu")
            if isinstance(mfu, (int, float)):
                telemetry.get_gauge(
                    "train_mfu", "model FLOPs utilization reported by the "
                    "training loop", tag_keys=("rank",)).set(float(mfu),
                                                             tags=tags)
            tps = metrics.get("tokens_per_sec")
            if isinstance(tps, (int, float)):
                telemetry.get_gauge(
                    "train_tokens_per_s", "training tokens/s reported by the "
                    "training loop", tag_keys=("rank",)).set(float(tps),
                                                             tags=tags)
            if telemetry.enabled():
                telemetry.event(
                    "train.report", "train", rank=self.context.world_rank,
                    **{k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, str, bool))})
        # graftlint: allow[swallowed-exception] telemetry emission is best-effort; a report must never fail on it
        except Exception:
            pass  # telemetry must never fail a report

    def drain(self, max_items: Optional[int] = None) -> list:
        out = []
        while max_items is None or len(out) < max_items:
            try:
                out.append(self.results.get_nowait())
            except queue.Empty:
                break
        return out


def _set_session(s: Optional[_TrainSession]) -> None:
    global _session
    with _session_lock:
        _session = s


def _get_session() -> Optional[_TrainSession]:
    with _session_lock:
        return _session


# -- public API (mirrors ray.train.*) --------------------------------------------------
def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    """Reference: ray.train.report (session.py:672)."""
    s = _get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a training worker")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.get_context() called outside a training worker")
    return s.context


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.get_checkpoint() called outside a training worker")
    return s.starting_checkpoint


@contextlib.contextmanager
def step_phase(name: str):
    """Time one phase of a training step — the step-composition breakdown
    (`data` / `forward_backward` / `allreduce` / `optimizer`) behind the
    train row of `ray-tpu status` and the chrome-trace timeline.

    Usage inside a train loop:
        with train.step_phase("forward_backward"):
            loss, grads = value_and_grad(...)

    Works outside a session too (bench scripts): rank then reports as -1."""
    s = _get_session()
    rank = s.context.world_rank if s is not None else -1
    t0 = time.perf_counter()
    with telemetry.span(f"train.phase.{name}", "train", rank=rank):
        yield
    telemetry.get_histogram(
        "train_step_phase_seconds", "per-phase training step time",
        tag_keys=("phase",)).observe(time.perf_counter() - t0,
                                     tags={"phase": name})


def get_dataset_shard(dataset_name: str = "train"):
    """Reference: ray.train.get_dataset_shard — this worker's split of a Dataset."""
    s = _get_session()
    if s is None:
        raise RuntimeError("get_dataset_shard() called outside a training worker")
    shard = s.dataset_shards.get(dataset_name)
    if shard is None:
        raise KeyError(
            f"no dataset shard named {dataset_name!r}; passed datasets: {list(s.dataset_shards)}"
        )
    return shard
