"""MPMD cross-process pipeline parallelism: per-stage compiled programs,
1F1B microbatch streaming over the zero-copy data plane.

"Scaling Deep Learning Training with MPMD Pipeline Parallelism" (arXiv
2412.14374): instead of GSPMD-tracing one giant program over a `pp` mesh axis
(`parallel/pipeline.py`), each pipeline stage is a *separate process* that
compiles its OWN three programs — forward, backward, optimizer-update — and
activations / activation-gradients stream stage-to-stage as fixed-shape
microbatch blocks over the collective data plane (PR 4's striped
`pull_into` transport; `resolve_stage_transport` in dag/accelerator_context
picks the device plane when both endpoints have it). Nothing ever moves
through the head: block keys are deterministic functions of
(step, microbatch, direction), so the blocking store read IS the
synchronization and zero control-plane round-trips ride the hot path.

Three layers, separable on purpose:

1. **Schedule core** — pure functions (`build_schedule`, `warmup_len`,
   `validate_schedule`, `bubble_fraction`): the 1F1B event order per stage
   and the timeline analysis, unit-testable with no processes involved.
2. **StageComm / StageRunner** — one process's slice of the pipeline: rides
   an existing collective group (PR 3), so stage death poisons the run and
   every blocked pull observes a typed `CollectiveAbortError` within one
   abort-poll interval instead of hanging. Runs equally inside a Train
   worker session (rank == stage; see `stage_runner_from_train_context`)
   or a standalone actor.
3. **MPMDPipeline** — driver facade: spawns one actor per stage, wires the
   group, streams steps. `parallel/mpmd.py` re-exports it.

Within-stage data parallelism reuses PR 10's bucketed grad sync: a stage
with >1 local device shards its microbatch over a local "dp" mesh and folds
`grad_sync._sync_bucketed` into its update program.

Gradient accumulation folds per-microbatch grads in REVERSE microbatch
order from a zero init — the exact float-addition chain `lax.scan`'s
transpose produces in the in-program pipeline — which is what makes the
cross-process runner bit-exact (f32) against `pipeline_spmd` (see
tests/test_mpmd_pipeline.py).
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util import telemetry
from ray_tpu.util.hot_path import hot_path

Event = Tuple[str, int]  # ("fwd" | "bwd", microbatch index)

PIPELINE_SPAN = "train.pipeline_stage"
BUBBLE_GAUGE = "train_pipeline_bubble_fraction"


# ---------------------------------------------------------------- schedule core
def warmup_len(stage: int, pp: int, num_microbatches: int) -> int:
    """Forward passes stage `stage` runs before its first backward (1F1B):
    the pipeline-fill depth below it, capped by the microbatch count."""
    return min(pp - 1 - stage, num_microbatches)


def build_1f1b_schedule(stage: int, pp: int, num_microbatches: int) -> List[Event]:
    """One stage's 1F1B event order: warmup fills, steady state alternates
    one-forward-one-backward, cooldown drains the in-flight microbatches."""
    m = num_microbatches
    w = warmup_len(stage, pp, m)
    events: List[Event] = [("fwd", i) for i in range(w)]
    for k in range(m - w):  # steady state: fwd(w+k) then bwd(k)
        events.append(("fwd", w + k))
        events.append(("bwd", k))
    events.extend(("bwd", i) for i in range(m - w, m))  # cooldown
    return events


def build_gpipe_schedule(stage: int, pp: int, num_microbatches: int) -> List[Event]:
    """All forwards, then all backwards — the unoverlapped baseline whose
    measured bubble the 1F1B row is gated against in bench.py --pipeline."""
    m = num_microbatches
    return [("fwd", i) for i in range(m)] + [("bwd", i) for i in range(m)]


def build_schedule(pp: int, num_microbatches: int,
                   schedule: str = "1f1b") -> List[List[Event]]:
    """Per-stage event lists for the whole pipeline. Raises on an invalid
    schedule name or a non-positive shape."""
    if pp < 1 or num_microbatches < 1:
        raise ValueError(f"need pp >= 1 and microbatches >= 1, got {pp}/{num_microbatches}")
    builder = {"1f1b": build_1f1b_schedule, "gpipe": build_gpipe_schedule}.get(schedule)
    if builder is None:
        raise ValueError(f"unknown pipeline schedule {schedule!r} (1f1b|gpipe)")
    out = [builder(s, pp, num_microbatches) for s in range(pp)]
    validate_schedule(out, pp, num_microbatches)
    return out


def validate_schedule(schedules: List[List[Event]], pp: int, m: int) -> None:
    """Prove the per-stage event lists deadlock-free by simulation.

    Dependencies: fwd(s, i) needs fwd(s-1, i); bwd(s, i) needs fwd(s, i) and
    bwd(s+1, i) (the last stage seeds its own cotangent). Greedy round-robin
    execution must retire every event — a cyclic wait or a missing/duplicate
    event fails loudly here rather than hanging live processes."""
    for s, evs in enumerate(schedules):
        fwds = [i for k, i in evs if k == "fwd"]
        bwds = [i for k, i in evs if k == "bwd"]
        if sorted(fwds) != list(range(m)) or sorted(bwds) != list(range(m)):
            raise ValueError(f"stage {s}: schedule must touch each microbatch "
                             f"exactly once per direction, got {evs}")
    done: set = set()
    cursor = [0] * pp
    progressed = True
    while progressed:
        progressed = False
        for s in range(pp):
            while cursor[s] < len(schedules[s]):
                kind, i = schedules[s][cursor[s]]
                if kind == "fwd":
                    ready = s == 0 or ("fwd", s - 1, i) in done
                else:
                    ready = ("fwd", s, i) in done and (
                        s == pp - 1 or ("bwd", s + 1, i) in done)
                if not ready:
                    break
                done.add((kind, s, i))
                cursor[s] += 1
                progressed = True
    stuck = [s for s in range(pp) if cursor[s] < len(schedules[s])]
    if stuck:
        raise ValueError(f"schedule deadlocks at stages {stuck}: "
                         f"{[schedules[s][cursor[s]] for s in stuck]}")


def bubble_fraction(events: List[Dict[str, Any]],
                    span_name: str = PIPELINE_SPAN) -> Dict[str, float]:
    """Per-stage bubble fraction from a (merged) telemetry timeline.

    For each stage, take its `span_name` spans (chrome-trace "X" events with a
    `stage` arg; ts/dur in microseconds), and compute the idle fraction of its
    own busy window [first span start, last span end]: 1 - busy/window.
    Overlapping spans are unioned so nested instrumentation can't push the
    fraction negative. Returns {"stage<i>": frac, ..., "mean": frac}; empty
    dict when no pipeline spans are present."""
    by_stage: Dict[int, List[Tuple[float, float]]] = {}
    for ev in events:
        if ev.get("name") != span_name or ev.get("ph", "X") != "X":
            continue
        args = ev.get("args", {})
        stage = args.get("stage")
        if stage is None:
            continue
        t0 = float(ev.get("ts", 0.0))
        by_stage.setdefault(int(stage), []).append((t0, t0 + float(ev.get("dur", 0.0))))
    out: Dict[str, float] = {}
    fracs = []
    for stage, spans in sorted(by_stage.items()):
        spans.sort()
        window = spans[-1][1] - spans[0][0] if spans else 0.0
        busy = 0.0
        cur_start, cur_end = spans[0]
        for s, e in spans[1:]:
            if s > cur_end:
                busy += cur_end - cur_start
                cur_start, cur_end = s, e
            else:
                cur_end = max(cur_end, e)
        busy += cur_end - cur_start
        frac = max(0.0, 1.0 - busy / window) if window > 0 else 0.0
        out[f"stage{stage}"] = frac
        fracs.append(frac)
    if fracs:
        out["mean"] = sum(fracs) / len(fracs)
    return out


def publish_bubble_gauge(fractions: Dict[str, float]) -> None:
    """Surface measured bubble fractions as the `train_pipeline_bubble_fraction`
    gauge (per stage + mean) — the `cluster_status()["train"]` / `ray-tpu
    status` hook."""
    g = telemetry.get_gauge(
        BUBBLE_GAUGE, "pipeline idle fraction per stage from the merged "
        "telemetry timeline (1 - busy/window over train.pipeline_stage spans)",
        tag_keys=("stage",))
    for stage, frac in fractions.items():
        g.set(float(frac), tags={"stage": stage})


# ---------------------------------------------------------------- configuration
@dataclass(frozen=True)
class MPMDPipelineConfig:
    """Shape of one MPMD pipeline run. Defaults come from the RAY_TPU_PIPELINE_*
    knobs (ray_tpu/knobs.py) via `from_env`."""

    num_microbatches: int = 4
    schedule: str = "1f1b"          # "1f1b" | "gpipe"
    prefetch: int = 2               # pull-ahead depth; 0 = unoverlapped transfers
    transfer_streams: int = 1       # concurrent stripes per block pull
    transport: str = "auto"         # "auto" | "host" | "device"
    group_name: str = "mpmd_pipeline"
    stage_dp: int = 1               # local data-parallel devices per stage
    learning_rate: float = 1e-2     # default SGD update when no update_fn given

    def __post_init__(self):
        if self.schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown schedule {self.schedule!r}")
        if self.transport not in ("auto", "host", "device"):
            raise ValueError(f"unknown transport {self.transport!r}")
        if self.num_microbatches < 1 or self.prefetch < 0 or self.transfer_streams < 1:
            raise ValueError("num_microbatches >= 1, prefetch >= 0, transfer_streams >= 1")

    @classmethod
    def from_env(cls, **overrides) -> "MPMDPipelineConfig":
        from ray_tpu.config import CONFIG

        base = dict(
            num_microbatches=int(CONFIG.pipeline_microbatches),
            schedule=str(CONFIG.pipeline_schedule),
            prefetch=int(CONFIG.pipeline_prefetch),
            transfer_streams=int(CONFIG.pipeline_streams),
            transport=str(CONFIG.pipeline_transport),
        )
        base.update(overrides)
        return cls(**base)


# ---------------------------------------------------------------- stage transport
class StageComm:
    """One stage's block transport: publish/pull fixed-shape microbatch blocks
    on the collective group's striped data plane, with abort-aware waits.

    Keys are deterministic — `mpmd:<dir>:<step>:<mb>` — so consumers need no
    per-block control round-trip: the peer's blocking store read is the
    synchronization, and a bounded-probe `pull_into` (one abort-poll interval
    per probe) keeps every wait interruptible by the PR 3 poison flag. Blocks
    publish with expected_read_bytes=nbytes: exactly one consumer reads each
    block once, after which the store auto-retracts it — a clean step leaves
    zero published buffers behind (the chaos test's leak check).

    transport="device" rides `core/device_plane` export/fetch with the handle
    handed off on the coordinator board (metadata only); "host" is the striped
    byte path; "auto" resolves per `dag.accelerator_context.resolve_stage_transport`.
    """

    def __init__(self, st, stage: int, pp: int, cfg: MPMDPipelineConfig):
        from ray_tpu.util.collective import ring

        self.st = st
        self.stage = stage
        self.pp = pp
        self.cfg = cfg
        self.plane = ring._ensure_plane(st)
        self._abort = ring._AbortCheck(st)
        self._published: set = set()
        self._inflight_pulls = 0
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._futures: Dict[Tuple[str, int, int], Any] = {}
        from ray_tpu.dag.accelerator_context import resolve_stage_transport

        self.transport = resolve_stage_transport(cfg.transport)
        # Rendezvous: every stage board-exchanges its plane address once per
        # epoch; pulls then dial peers directly (never the head).
        self.addrs = self._exchange_addrs()

    def _exchange_addrs(self) -> List[Tuple[str, int]]:
        from ray_tpu.util.collective import ring

        entries = ring._exchange(
            self.st, f"mpmd_addr:{self.st.epoch}:{self.cfg.schedule}",
            tuple(self.plane.addr))
        return [tuple(e) for e in entries]

    # -- key scheme --------------------------------------------------------------------
    @staticmethod
    def _key(direction: str, step: int, mb: int) -> str:
        return f"mpmd:{direction}:{step}:{mb}"

    # -- publish -----------------------------------------------------------------------
    def publish(self, direction: str, step: int, mb: int, arr: np.ndarray) -> None:
        key = self._key(direction, step, mb)
        if self.transport == "device":
            if self._publish_device(key, arr):
                return
        data = np.ascontiguousarray(arr)
        self.plane.publish(key, data.tobytes(), expected_read_bytes=data.nbytes)
        with self._lock:
            self._published.add(key)

    def _publish_device(self, key: str, arr) -> bool:
        """Device-plane path: export the block, hand the handle off on the
        coordinator board (metadata only). Falls back to the host path when
        the plane rejects the export."""
        from ray_tpu.core import device_plane

        dp = device_plane.plane()
        if not dp.available:
            return False
        try:
            handle = dp.export(arr)
        except device_plane.DevicePlaneError:
            return False
        self.st.coordinator.contribute.remote(
            f"{key}:h", self.st.rank, handle, self.st.epoch)
        return True

    # -- pull --------------------------------------------------------------------------
    def prefetch(self, direction: str, step: int, mb: int, src_stage: int,
                 shape: Tuple[int, ...], dtype) -> None:
        """Initiate an overlapped pull for a block the schedule needs soon."""
        if self.cfg.prefetch <= 0:
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=max(2, self.cfg.prefetch * self.cfg.transfer_streams),
                thread_name_prefix=f"mpmd-s{self.stage}")
        slot = (direction, step, mb)
        if slot not in self._futures:
            self._futures[slot] = self._pool.submit(
                self._pull_block, direction, step, mb, src_stage, shape, dtype)

    def take(self, direction: str, step: int, mb: int, src_stage: int,
             shape: Tuple[int, ...], dtype) -> np.ndarray:
        """The block for (direction, step, mb) — from a prefetched future when
        one is in flight, else pulled inline."""
        fut = self._futures.pop((direction, step, mb), None)
        if fut is not None:
            return fut.result()
        return self._pull_block(direction, step, mb, src_stage, shape, dtype)

    def _pull_block(self, direction: str, step: int, mb: int, src_stage: int,
                    shape: Tuple[int, ...], dtype) -> np.ndarray:
        with self._lock:
            self._inflight_pulls += 1
        try:
            if self.transport == "device":
                out = self._fetch_device(direction, step, mb, src_stage)
                if out is not None:
                    return out
            return self._pull_host(direction, step, mb, src_stage, shape, dtype)
        finally:
            with self._lock:
                self._inflight_pulls -= 1

    def _fetch_device(self, direction: str, step: int, mb: int,
                      src_stage: int) -> Optional[np.ndarray]:
        from ray_tpu.core import device_plane
        from ray_tpu.util.collective.coordinator import wait_poll_one

        dp = device_plane.plane()
        if not dp.available:
            return None
        key = f"{self._key(direction, step, mb)}:h"
        handle = wait_poll_one(self.st, key, src_stage, timeout_s=self._op_timeout())
        return np.asarray(dp.fetch(handle, release=True))

    def _op_timeout(self) -> float:
        from ray_tpu.config import CONFIG

        return CONFIG.collective_op_timeout_s

    def _pull_host(self, direction: str, step: int, mb: int, src_stage: int,
                   shape: Tuple[int, ...], dtype) -> np.ndarray:
        """Striped bounded-probe pull: probe stripe 0 until the block lands
        (checking the poison flag on every miss), then fan the remaining
        stripes out over `transfer_streams` concurrent ranged pulls."""
        addr = self.addrs[src_stage]
        key = self._key(direction, step, mb)
        out = np.empty(shape, dtype)
        mv = memoryview(out).cast("B")
        total = out.nbytes
        probe_s = self._abort.interval
        deadline = time.monotonic() + self._op_timeout()
        streams = min(self.cfg.transfer_streams, max(1, total // (64 << 10)) or 1)
        stripe = -(-total // streams)
        first = min(stripe, total)
        while True:  # stripe 0 carries the wait-for-publication probe loop
            try:
                n = self.plane.pull_into(addr, key, 0, first, mv[:first],
                                         timeout=probe_s)
            except (OSError, ConnectionError):
                # peer unreachable (killed or mid-restart): the abort probe
                # below turns this into the typed CollectiveAbortError as soon
                # as the coordinator's poison flag lands (one poll interval)
                n = None
                time.sleep(probe_s)
            if n is not None:
                break
            self._abort.check(force=True)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"stage {self.stage}: block {key} from stage {src_stage} "
                    f"not published within {self._op_timeout()}s")
        try:
            if streams > 1 and total > first:
                def pull_stripe(k: int) -> None:
                    off = k * stripe
                    ln = min(stripe, total - off)
                    self.plane.pull_into(addr, key, off, ln, mv[off:off + ln])

                with ThreadPoolExecutor(max_workers=streams - 1,
                                        thread_name_prefix="mpmd-stripe") as ex:
                    list(ex.map(pull_stripe, range(1, streams)))
            elif total > first:
                self.plane.pull_into(addr, key, first, total - first, mv[first:])
        except (OSError, ConnectionError):
            # producer died between stripe 0 and the fan-out: prefer the typed
            # abort when the group is poisoned, else surface the IO error
            self._abort.check(force=True)
            raise
        return out

    # -- accounting / teardown ---------------------------------------------------------
    def admission_counters(self) -> Dict[str, int]:
        """In-flight accounting for the leak gate: published-but-unconsumed
        mpmd blocks in this stage's store, plus pulls currently in flight.
        Both must read zero after a completed step AND after abort cleanup."""
        with self._lock:
            inflight = self._inflight_pulls
        with self.plane.store._cond:
            published = sum(1 for k in self.plane.store._bufs if k.startswith("mpmd:"))
        return {"published": published, "inflight_pulls": inflight}

    def abort_cleanup(self) -> None:
        """Retract every mpmd block this stage still serves and drop pending
        prefetch futures: survivors of a poisoned run must not pin activation
        buffers until the TTL sweep."""
        with self.plane.store._cond:
            stale = [k for k in self.plane.store._bufs if k.startswith("mpmd:")]
        for k in stale:
            self.plane.retract(k)
        for fut in self._futures.values():
            fut.cancel()
        self._futures.clear()
        with self._lock:
            self._published.clear()

    def close(self) -> None:
        self.abort_cleanup()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


# ---------------------------------------------------------------- stage runner
def _as_spec(spec) -> Tuple[Tuple[int, ...], Any]:
    """Normalize a jax.ShapeDtypeStruct / (shape, dtype) pair to (shape, dtype)."""
    if hasattr(spec, "shape") and hasattr(spec, "dtype"):
        return tuple(spec.shape), spec.dtype
    shape, dtype = spec
    return tuple(shape), np.dtype(dtype)


class StageRunner:
    """One pipeline stage's execution engine: compiles this stage's OWN three
    programs (forward, backward, update) and walks its 1F1B/GPipe event list,
    publishing/pulling fixed-shape microbatch blocks through `StageComm`.

    `stage_fn(params, x) -> y` must be batch-parallel along axis 0 of `x`
    (each sample independent) — required for stage_dp > 1 sharding and for
    microbatch semantics in general. `loss_fn(y) -> scalar` (last stage only)
    must be a mean over the microbatch. The update defaults to plain SGD at
    `cfg.learning_rate`; pass `update_fn(params, grads) -> params` to replace
    it.

    Bit-exactness contract (vs `parallel/pipeline.py`'s `pipeline_spmd`, f32):
    per-microbatch gradients are buffered and folded in REVERSE microbatch
    order from a zeros init — the float-addition chain `lax.scan`'s transpose
    emits — and the last stage seeds each microbatch cotangent with the exact
    scalar 1/num_microbatches (exact in f32 for power-of-two counts).
    """

    def __init__(self, st, stage: int, pp: int, stage_fn: Callable,
                 params: Any, cfg: MPMDPipelineConfig, *,
                 loss_fn: Optional[Callable] = None,
                 update_fn: Optional[Callable] = None,
                 in_spec=None, out_spec=None):
        import jax

        self.st = st
        self.stage = stage
        self.pp = pp
        self.cfg = cfg
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.update_fn = update_fn
        self.is_first = stage == 0
        self.is_last = stage == pp - 1
        if self.is_last and loss_fn is None:
            raise ValueError("last stage needs loss_fn")
        self.in_shape, self.in_dtype = _as_spec(in_spec)
        self.out_shape, self.out_dtype = _as_spec(out_spec)
        self.params = jax.device_put(params)
        self.events = build_schedule(pp, cfg.num_microbatches, cfg.schedule)[stage]
        self.comm = StageComm(st, stage, pp, cfg)
        self.last_grads: Any = None      # folded grads of the latest step (parity hook)
        self.last_losses: List[Any] = []  # per-microbatch losses (last stage)
        self.timeline: List[Dict[str, Any]] = []  # local chrome-trace span records
        self._dp_mesh = None
        if cfg.stage_dp > 1:
            self._dp_mesh = self._build_dp_mesh(cfg.stage_dp)
        self._programs_ready = False

    # -- program compilation ---------------------------------------------------------
    @staticmethod
    def _build_dp_mesh(dp: int):
        import jax
        from jax.sharding import Mesh

        devs = jax.local_devices()
        if len(devs) < dp:
            raise ValueError(f"stage_dp={dp} but only {len(devs)} local devices")
        return Mesh(np.array(devs[:dp]), ("dp",))

    def _build_programs(self) -> None:
        import jax
        import jax.numpy as jnp

        stage_fn, loss_fn = self.stage_fn, self.loss_fn
        m = self.cfg.num_microbatches
        # exact in f32 for power-of-two m: the same cotangent jnp.mean's
        # transpose distributes to each microbatch loss in the reference
        self._ct = jnp.float32(1.0 / m)
        self._stash = self._dp_mesh is None
        if self._dp_mesh is None:
            # Residual stashing: forward returns its vjp pullback (a
            # jax.tree_util.Partial — a pytree, so it crosses the jit
            # boundary with the residual arrays as leaves) and backward
            # applies it. One forward per microbatch total, where a
            # vjp-inside-bwd program would recompute it — that recompute is
            # exactly the edge the single-program scan baseline would keep.
            if self.is_last:
                def head(p_, x_):
                    return loss_fn(stage_fn(p_, x_))

                def fwd_last(p, x):
                    loss, pullback = jax.vjp(head, p, x)
                    return loss, pullback

                self._fwd = jax.jit(fwd_last)
            else:
                self._fwd = jax.jit(lambda p, x: jax.vjp(stage_fn, p, x))
            self._bwd = jax.jit(lambda pullback, ct: pullback(ct))  # (gp, gx)
        else:
            self._build_dp_programs()
        self._acc = jax.jit(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b))
        self._zeros = jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.zeros_like, p))
        upd = self.update_fn
        if upd is None:
            lr = jnp.float32(self.cfg.learning_rate)

            def upd(p, g):
                return jax.tree_util.tree_map(lambda pv, gv: pv - lr * gv, p, g)

        self._update = jax.jit(upd)
        self._programs_ready = True

    def _build_dp_programs(self) -> None:
        """stage_dp > 1: shard the microbatch over a local "dp" mesh and fold
        PR 10's bucketed grad sync into the backward program. Per-shard param
        grads are partial sums, so the group reduce is a SUM — expressed as
        dp * pmean to ride `grad_sync._sync_bucketed` unchanged."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ray_tpu.train import grad_sync

        stage_fn, loss_fn = self.stage_fn, self.loss_fn
        mesh = self._dp_mesh
        dp = jnp.float32(self.cfg.stage_dp)
        sync = grad_sync.GradSyncConfig(mode="bucketed")

        def scale(tree, s):
            return jax.tree_util.tree_map(lambda a: a * s, tree)

        self._fwd = jax.jit(grad_sync._shard_map(
            stage_fn, mesh, in_specs=(P(), P("dp")), out_specs=P("dp"),
            manual=("dp",)))
        if self.is_last:
            def bwd_last(p, x, ct):
                def head(p_, x_):
                    return loss_fn(stage_fn(p_, x_))
                loss, vjp = jax.vjp(head, p, x)
                # loss_fn is a microbatch mean: d(mb mean)/d(shard) is the
                # shard's local cotangent scaled by 1/dp
                gp, gx = vjp(ct / dp)
                gp = scale(grad_sync._sync_bucketed(gp, "dp", sync, None), dp)
                return jax.lax.pmean(loss, "dp"), gp, gx

            self._bwd = jax.jit(grad_sync._shard_map(
                bwd_last, mesh, in_specs=(P(), P("dp"), P()),
                out_specs=(P(), P(), P("dp")), manual=("dp",)))
        else:
            def bwd(p, x, gy):
                _, vjp = jax.vjp(stage_fn, p, x)
                gp, gx = vjp(gy)
                gp = scale(grad_sync._sync_bucketed(gp, "dp", sync, None), dp)
                return gp, gx

            self._bwd = jax.jit(grad_sync._shard_map(
                bwd, mesh, in_specs=(P(), P("dp"), P("dp")),
                out_specs=(P(), P("dp")), manual=("dp",)))

    # -- schedule execution ----------------------------------------------------------
    def _prefetch_ahead(self, step: int, idx: int) -> None:
        """Issue overlapped pulls for the next `prefetch` events' remote blocks."""
        for j in range(idx + 1, min(idx + 1 + self.cfg.prefetch, len(self.events))):
            kind, mb = self.events[j]
            if kind == "fwd" and not self.is_first:
                self.comm.prefetch("fwd", step, mb, self.stage - 1,
                                   self.in_shape, self.in_dtype)
            elif kind == "bwd" and not self.is_last:
                self.comm.prefetch("bwd", step, mb, self.stage + 1,
                                   self.out_shape, self.out_dtype)

    @hot_path(reason="per-microbatch schedule walk: transfers must overlap compute")
    def run_step(self, step: int, batch: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Walk this stage's event list for one optimizer step: forwards pull
        activations from upstream and publish downstream, backwards pull
        activation-grads from downstream and publish upstream; per-microbatch
        param grads fold (reverse order) into one update at the end.

        Raises `CollectiveAbortError` (typed, within one abort-poll interval)
        when any stage of the run dies; activation buffers are retracted on
        the way out so survivors leak nothing."""
        from ray_tpu.core.exceptions import CollectiveAbortError

        if not self._programs_ready:
            self._build_programs()
        m = self.cfg.num_microbatches
        if self.is_first:
            if batch is None:
                raise ValueError("stage 0 needs the step's batch")
            if batch.shape[0] % m:
                raise ValueError(
                    f"batch dim {batch.shape[0]} not divisible by {m} microbatches")
            batch = np.asarray(batch, self.in_dtype).reshape(  # graftlint: allow[host-sync-in-hot-path] stage-0 step input is already host memory; this is a dtype/shape normalize, not a device fetch
                (m, batch.shape[0] // m) + tuple(batch.shape[1:]))
        xs: Dict[int, Any] = {}       # microbatch -> primal input (dp path only)
        pbs: Dict[int, Any] = {}      # microbatch -> stashed vjp pullback
        grads: Dict[int, Any] = {}    # microbatch -> param-grad tree (device)
        losses: Dict[int, Any] = {}
        try:
            for idx, (kind, mb) in enumerate(self.events):
                self._prefetch_ahead(step, idx)
                if kind == "fwd":
                    x = batch[mb] if self.is_first else self.comm.take(
                        "fwd", step, mb, self.stage - 1, self.in_shape, self.in_dtype)
                    with telemetry.span(PIPELINE_SPAN, "train", stage=self.stage,
                                        kind="fwd", mb=mb, step=step):
                        t0 = time.perf_counter()
                        if self._stash:
                            y, pbs[mb] = self._fwd(self.params, x)
                        else:
                            y = self._fwd(self.params, x)
                            xs[mb] = x
                        if not self.is_last:
                            # designed sync point: the block must be host bytes
                            # before it can publish to the data plane
                            y = np.asarray(y)  # graftlint: allow[host-sync-in-hot-path] publish boundary
                        else:
                            import jax

                            y = jax.block_until_ready(y)  # graftlint: allow[host-sync-in-hot-path] span must cover compute, not async dispatch
                            if self._stash:
                                # stashed last-stage forward already folds
                                # loss_fn, so y IS the microbatch loss
                                losses[mb] = y
                        self._record(t0, "fwd", mb, step)
                    if not self.is_last:
                        self.comm.publish("fwd", step, mb, y)
                else:
                    gy = None if self.is_last else self.comm.take(
                        "bwd", step, mb, self.stage + 1, self.out_shape, self.out_dtype)
                    with telemetry.span(PIPELINE_SPAN, "train", stage=self.stage,
                                        kind="bwd", mb=mb, step=step):
                        t0 = time.perf_counter()
                        if self._stash:
                            gp, gx = self._bwd(
                                pbs.pop(mb), self._ct if self.is_last else gy)
                        elif self.is_last:
                            loss, gp, gx = self._bwd(self.params, xs[mb], self._ct)
                            losses[mb] = loss
                        else:
                            gp, gx = self._bwd(self.params, xs[mb], gy)
                        if not self.is_first:
                            # designed sync point: upstream needs host bytes
                            gx = np.asarray(gx)  # graftlint: allow[host-sync-in-hot-path] publish boundary
                        self._record(t0, "bwd", mb, step)
                    grads[mb] = gp
                    xs.pop(mb, None)
                    if not self.is_first:
                        self.comm.publish("bwd", step, mb, gx)
        except (CollectiveAbortError, TimeoutError):
            self.comm.abort_cleanup()
            raise
        # Fold per-microbatch grads in REVERSE order from zeros — the exact
        # chain lax.scan's transpose produces (float add is commutative but
        # not associative; arrival order would NOT be bit-exact).
        acc = self._zeros(self.params)
        for mb in range(m - 1, -1, -1):
            acc = self._acc(acc, grads[mb])
        self.last_grads = acc
        self.params = self._update(self.params, acc)
        self.last_losses = [losses[i] for i in range(m)] if self.is_last else []
        out: Dict[str, Any] = {"stage": self.stage, "step": step,
                               "admission": self.comm.admission_counters()}
        if self.is_last:
            import jax.numpy as jnp

            total = jnp.mean(jnp.stack(self.last_losses))
            out["loss"] = float(total)  # graftlint: allow[host-sync-in-hot-path] step boundary: metrics leave the device here
        return out

    def _record(self, t0: float, kind: str, mb: int, step: int) -> None:
        """Local chrome-trace record of the compute span: per-stage bubble
        fraction needs only the stage's own clock, so these are merged across
        stages without alignment (and work with telemetry disabled)."""
        t1 = time.perf_counter()
        self.timeline.append({
            "name": PIPELINE_SPAN, "ph": "X", "ts": t0 * 1e6,
            "dur": (t1 - t0) * 1e6,
            "args": {"stage": self.stage, "kind": kind, "mb": mb, "step": step},
        })

    # -- state hooks (checkpoint / parity) -------------------------------------------
    def params_host(self) -> Any:
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def grads_host(self) -> Any:
        import jax

        if self.last_grads is None:
            return None
        return jax.tree_util.tree_map(np.asarray, self.last_grads)

    def set_params(self, params: Any) -> None:
        import jax

        self.params = jax.device_put(params)

    def close(self) -> None:
        self.comm.close()


def stage_runner_from_train_context(stage_fn: Callable, params: Any,
                                    cfg: MPMDPipelineConfig, *,
                                    loss_fn: Optional[Callable] = None,
                                    update_fn: Optional[Callable] = None,
                                    in_spec=None, out_spec=None) -> StageRunner:
    """Build a StageRunner inside a Train worker session: the worker's rank IS
    its pipeline stage and the backend-created collective group (JaxConfig
    (collective_group=True); RAY_TPU_TRAIN_COLLECTIVE_GROUP) carries the
    blocks — so Train's failure policy (max_failures, salvage, restart from
    the latest checkpoint) applies to pipeline runs with no extra wiring."""
    import os

    from ray_tpu.util.collective import collective

    group = os.environ.get("RAY_TPU_TRAIN_COLLECTIVE_GROUP")
    if not group:
        raise RuntimeError(
            "no Train collective group in this session: construct the trainer "
            "with JaxConfig(collective_group=True)")
    st = collective._state(group)
    return StageRunner(st, st.rank, st.world_size, stage_fn, params, cfg,
                       loss_fn=loss_fn, update_fn=update_fn,
                       in_spec=in_spec, out_spec=out_spec)


# ---------------------------------------------------------------- driver facade
class _StageActor:
    """One pipeline stage as a standalone actor (the non-Train entry point:
    parity tests, bench). Joins the group via CollectiveActorMixin, then hosts
    a StageRunner."""

    def setup(self, stage: int, pp: int, stage_fn: Callable, params: Any,
              cfg: MPMDPipelineConfig, loss_fn, update_fn,
              in_spec, out_spec) -> int:
        self.runner = StageRunner(
            _collective_state(cfg.group_name), stage, pp, stage_fn, params,
            cfg, loss_fn=loss_fn, update_fn=update_fn,
            in_spec=in_spec, out_spec=out_spec)
        return stage

    def run_step(self, step: int, batch=None) -> Dict[str, Any]:
        return self.runner.run_step(step, batch)

    def params_host(self):
        return self.runner.params_host()

    def grads_host(self):
        return self.runner.grads_host()

    def admission(self) -> Dict[str, int]:
        return self.runner.comm.admission_counters()

    def timeline(self) -> List[Dict[str, Any]]:
        return list(self.runner.timeline)

    def reset_timeline(self) -> None:
        self.runner.timeline.clear()

    def close(self) -> None:
        runner = getattr(self, "runner", None)
        if runner is not None:
            runner.close()


def _collective_state(group_name: str):
    from ray_tpu.util.collective import collective

    return collective._state(group_name)


def _chain_specs(stage_fns: List[Callable], params: List[Any],
                 microbatch_spec) -> List[Tuple[Any, Any]]:
    """(in_spec, out_spec) per stage via an eval_shape chain from the
    microbatch input spec — no stage runs any real compute here."""
    import jax

    shape, dtype = _as_spec(microbatch_spec)
    spec = jax.ShapeDtypeStruct(shape, dtype)
    out = []
    for fn, p in zip(stage_fns, params):
        y = jax.eval_shape(fn, jax.eval_shape(lambda t: t, p), spec)
        out.append((spec, y))
        spec = y
    return out


class MPMDPipeline:
    """Driver facade: one actor per stage, a collective group underneath, and
    a step loop that streams microbatches through the 1F1B schedule. See the
    module docstring; `parallel/mpmd.py` re-exports this.

        pipe = MPMDPipeline(stage_fns, stage_params, loss_fn=loss,
                            microbatch_spec=((mb, d), jnp.float32),
                            cfg=MPMDPipelineConfig.from_env())
        for step, batch in enumerate(batches):
            metrics = pipe.step(step, batch)   # {"loss": ..., "admission": ...}
        fractions = pipe.bubble_fractions()    # also publishes the gauge
        pipe.shutdown()
    """

    def __init__(self, stage_fns: List[Callable], stage_params: List[Any],
                 *, loss_fn: Callable, microbatch_spec,
                 cfg: Optional[MPMDPipelineConfig] = None,
                 update_fn: Optional[Callable] = None):
        import ray_tpu
        from ray_tpu.util.collective.collective import (CollectiveActorMixin,
                                                        create_collective_group)

        self.cfg = cfg or MPMDPipelineConfig.from_env()
        self.pp = len(stage_fns)
        if self.pp < 2:
            raise ValueError("MPMD pipeline needs pp >= 2 stages")
        if len(stage_params) != self.pp:
            raise ValueError("one params tree per stage")
        specs = _chain_specs(stage_fns, stage_params, microbatch_spec)

        class _Actor(_StageActor, CollectiveActorMixin):
            pass

        actor_cls = ray_tpu.remote(_Actor)
        self.actors = [actor_cls.options(num_cpus=0).remote()
                       for _ in range(self.pp)]
        create_collective_group(self.actors, self.pp, list(range(self.pp)),
                                backend="shm", group_name=self.cfg.group_name)
        ray_tpu.get([
            a.setup.remote(s, self.pp, stage_fns[s], stage_params[s], self.cfg,
                           loss_fn if s == self.pp - 1 else None, update_fn,
                           specs[s][0], specs[s][1])
            for s, a in enumerate(self.actors)])

    def step(self, step: int, batch: np.ndarray) -> Dict[str, Any]:
        """Run one optimizer step; returns the last stage's metrics (loss,
        admission counters). A stage death surfaces as the survivors' typed
        `CollectiveAbortError`."""
        import ray_tpu

        refs = [a.run_step.remote(step, batch if s == 0 else None)
                for s, a in enumerate(self.actors)]
        results = ray_tpu.get(refs)
        return results[-1]

    def params_host(self) -> List[Any]:
        import ray_tpu

        return ray_tpu.get([a.params_host.remote() for a in self.actors])

    def grads_host(self) -> List[Any]:
        import ray_tpu

        return ray_tpu.get([a.grads_host.remote() for a in self.actors])

    def admission(self) -> List[Dict[str, int]]:
        import ray_tpu

        return ray_tpu.get([a.admission.remote() for a in self.actors])

    def merged_timeline(self) -> List[Dict[str, Any]]:
        import ray_tpu

        events: List[Dict[str, Any]] = []
        for tl in ray_tpu.get([a.timeline.remote() for a in self.actors]):
            events.extend(tl)
        return events

    def reset_timelines(self) -> None:
        """Drop span records so far (e.g. compile-step warmup) so
        `bubble_fractions()` reflects only steady-state steps."""
        import ray_tpu

        ray_tpu.get([a.reset_timeline.remote() for a in self.actors])

    def bubble_fractions(self) -> Dict[str, float]:
        """Per-stage bubble fractions from the merged stage timelines; also
        publishes the `train_pipeline_bubble_fraction` gauge so
        `cluster_status()["train"]` / `ray-tpu status` pick it up."""
        fractions = bubble_fraction(self.merged_timeline())
        if fractions:
            publish_bubble_gauge(fractions)
        return fractions

    def shutdown(self) -> None:
        import ray_tpu
        from ray_tpu.util.collective.collective import kill_coordinator

        for a in self.actors:
            try:
                ray_tpu.get(a.close.remote(), timeout=10)
            # graftlint: allow[swallowed-exception] teardown best-effort: a dead stage actor must not block shutdown
            except Exception:
                pass
        kill_coordinator(self.cfg.group_name)
        for a in self.actors:
            ray_tpu.kill(a)
        self.actors = []
