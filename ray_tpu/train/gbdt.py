"""XGBoost / LightGBM data-parallel trainers (GBDT family).

Capability parity:
- reference python/ray/train/xgboost/config.py — XGBoostConfig (:21) starts an
  ``xgboost.RabitTracker`` on the driver and hands every worker the DMLC env it
  needs to join the collective; the user's plain ``xgboost.train`` call inside
  the train loop becomes distributed under ``CommunicatorContext``.
- reference python/ray/train/lightgbm/config.py — LightGBMConfig (:58) has each
  worker bind a port, then broadcasts the ``machines`` list so user code merges
  ``get_network_params()`` into its LightGBM params.
- reference python/ray/train/xgboost/_xgboost_utils.py RayTrainReportCallback —
  per-round metric report + periodic Booster checkpointing through the session.

Both libraries are optional in this image: the modules import lazily and raise
a clear error at ``fit()`` time when absent (same contract as the reference's
optional integration deps).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Type

from .backend import Backend, BackendConfig
from .data_parallel_trainer import DataParallelTrainer
from .tensorflow_backend import _bind_free_port
from .worker_group import WorkerGroup


def _require(module: str, feature: str):
    import importlib

    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"{feature} requires the '{module}' package, which is not installed "
            f"in this environment. Install it to use this trainer."
        ) from e


# ------------------------------------------------------------------- xgboost

_rabit_args: Optional[Dict[str, Any]] = None
_rabit_lock = threading.Lock()


def get_rabit_args() -> Dict[str, Any]:
    """Args for ``xgboost.collective.CommunicatorContext(**args)`` on this
    worker (reference config.py _get_xgboost_args). Empty outside an
    XGBoostTrainer loop."""
    with _rabit_lock:
        return dict(_rabit_args) if _rabit_args else {}


def _set_rabit_args(args: Dict[str, Any], rank: int) -> None:
    global _rabit_args
    with _rabit_lock:
        # Rank alignment: the tracker sorts workers by task id
        # (reference config.py sortby="task" + dmlc_task_id).
        _rabit_args = dict(args, dmlc_task_id=f"[ray_tpu-rank={rank:08}]")


def _clear_rabit_args() -> None:
    global _rabit_args
    with _rabit_lock:
        _rabit_args = None


@dataclass
class XGBoostConfig(BackendConfig):
    """Rabit collective bootstrap (reference xgboost/config.py:21)."""

    xgboost_communicator: str = "rabit"

    @property
    def backend_cls(self) -> Type["XGBoostBackend"]:
        if self.xgboost_communicator != "rabit":
            raise NotImplementedError(
                f"unsupported xgboost communicator: {self.xgboost_communicator!r}")
        return XGBoostBackend


class XGBoostBackend(Backend):
    def __init__(self):
        self._tracker = None
        self._wait_thread: Optional[threading.Thread] = None

    def on_training_start(self, worker_group: WorkerGroup,
                          backend_config: XGBoostConfig) -> None:
        xgb = _require("xgboost", "XGBoostTrainer")
        # RabitTracker moved between xgboost versions (top level <-> .tracker)
        tracker_cls = getattr(xgb, "RabitTracker", None)
        if tracker_cls is None:
            from xgboost.tracker import RabitTracker as tracker_cls
        n = len(worker_group)
        self._tracker = tracker_cls(n_workers=n, host_ip="127.0.0.1",
                                    sortby="task")
        self._tracker.start()
        # wait_for holds the tracker open until every worker disconnects;
        # park it on a daemon thread like the reference does.
        self._wait_thread = threading.Thread(
            target=lambda: self._tracker.wait_for(), daemon=True,
            name="gbdt-tracker-wait")
        self._wait_thread.start()
        args = dict(self._tracker.worker_args())
        import ray_tpu

        ray_tpu.get([
            w.run_fn.remote(_set_rabit_args, args, rank)
            for rank, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: XGBoostConfig) -> None:
        try:
            worker_group.execute(_clear_rabit_args)
        # graftlint: allow[swallowed-exception] best-effort worker-env teardown (rabit args)
        except Exception:
            pass
        if self._wait_thread is not None:
            self._wait_thread.join(timeout=5)
            self._wait_thread = None
        self._tracker = None


class XGBoostTrainer(DataParallelTrainer):
    """Run ``train_loop_per_worker`` on N workers with a live rabit collective;
    plain ``xgboost.train(...)`` inside the loop (under
    ``xgboost.collective.CommunicatorContext()``) trains data-parallel
    (reference xgboost/v2.py:13)."""

    _default_backend_config = XGBoostConfig


class RayTrainReportCallback:
    """xgboost callback: per-iteration session.report of eval metrics, with the
    Booster checkpointed every ``frequency`` rounds (reference
    _xgboost_utils.py RayTrainReportCallback).

    Subclasses xgboost.callback.TrainingCallback dynamically so this module
    imports without xgboost present.
    """

    CHECKPOINT_NAME = "model.ubj"

    def __new__(cls, *args, **kwargs):
        xgb = _require("xgboost", "RayTrainReportCallback")

        class _Impl(xgb.callback.TrainingCallback):
            def __init__(self, metrics=None, frequency=0):
                self._metrics = metrics
                self._frequency = frequency

            def _flat_metrics(self, evals_log):
                out = {}
                for data_name, metric_log in evals_log.items():
                    for metric_name, values in metric_log.items():
                        key = f"{data_name}-{metric_name}"
                        if self._metrics and key not in self._metrics \
                                and metric_name not in self._metrics:
                            continue
                        out[key] = values[-1]
                return out

            def after_iteration(self, model, epoch, evals_log):
                import os
                import shutil
                import tempfile

                from . import session
                from .checkpoint import Checkpoint

                metrics = self._flat_metrics(evals_log)
                d = None
                ckpt = None
                # rank 0 only: the Booster is identical on every rank after
                # the allreduce; N copies are pure waste
                if self._frequency and (epoch + 1) % self._frequency == 0 \
                        and session.get_context().get_world_rank() == 0:
                    d = tempfile.mkdtemp(prefix="xgb_ckpt_")
                    model.save_model(os.path.join(d, cls.CHECKPOINT_NAME))
                    ckpt = Checkpoint.from_directory(d)
                session.report(metrics, checkpoint=ckpt)
                if d is not None:
                    # report() stages the checkpoint before returning
                    shutil.rmtree(d, ignore_errors=True)
                return False

        return _Impl(*args, **kwargs)


# ------------------------------------------------------------------ lightgbm

_lgbm_network_params: Optional[Dict[str, Any]] = None
_lgbm_lock = threading.Lock()


def get_network_params() -> Dict[str, Any]:
    """LightGBM network params for this worker's train() call (reference
    lightgbm/config.py:19). Empty outside a LightGBMTrainer loop."""
    with _lgbm_lock:
        return dict(_lgbm_network_params) if _lgbm_network_params else {}


def _set_lgbm_params(num_machines: int, local_listen_port: int, machines: str) -> None:
    global _lgbm_network_params
    with _lgbm_lock:
        _lgbm_network_params = {
            "num_machines": num_machines,
            "local_listen_port": local_listen_port,
            "machines": machines,
        }


def _clear_lgbm_params() -> None:
    global _lgbm_network_params
    with _lgbm_lock:
        _lgbm_network_params = None


@dataclass
class LightGBMConfig(BackendConfig):
    @property
    def backend_cls(self) -> Type["LightGBMBackend"]:
        return LightGBMBackend


class LightGBMBackend(Backend):
    def on_training_start(self, worker_group: WorkerGroup,
                          backend_config: LightGBMConfig) -> None:
        addrs = worker_group.execute(_bind_free_port)
        machines = ",".join(f"{ip}:{port}" for ip, port in addrs)
        import ray_tpu

        ray_tpu.get([
            w.run_fn.remote(_set_lgbm_params, len(worker_group), addrs[rank][1],
                            machines)
            for rank, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: LightGBMConfig) -> None:
        try:
            worker_group.execute(_clear_lgbm_params)
        # graftlint: allow[swallowed-exception] best-effort worker-env teardown (lgbm params)
        except Exception:
            pass


class LightGBMTrainer(DataParallelTrainer):
    """User loop merges ``get_network_params()`` into its lgbm params and calls
    plain ``lightgbm.train`` (reference lightgbm/v2.py)."""

    _default_backend_config = LightGBMConfig
