"""URI- or directory-addressed checkpoints.

Reference capability: python/ray/train/_checkpoint.py:56 (Checkpoint) — a
checkpoint is a URI/path-addressed directory; frameworks read/write inside it.
Remote URIs (``gs://``, ``s3://``, ``mock://`` …) resolve through
train/storage.py (reference _internal/storage.py:358 StorageContext): workers
upload on report, any host downloads on restore. Orbax handles the jax pytree
serialization (see train/orbax_utils.py); this class is format-agnostic.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

from . import storage


class Checkpoint:
    """A reference to a directory (local path or storage URI) holding a model
    snapshot."""

    _METADATA_FILE = ".metadata.json"

    def __init__(self, path: str):
        path = storage.normalize(path)
        self.path = path if storage.is_remote(path) else os.path.abspath(path)

    @property
    def is_remote(self) -> bool:
        return storage.is_remote(self.path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"Checkpoint.from_directory: {path} is not a directory")
        return cls(path)

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents. Local paths
        are yielded zero-copy; remote URIs download to a temp dir (removed
        afterwards) — the restore path works on ANY host, not just where the
        checkpoint was written."""
        if not self.is_remote:
            yield self.path
            return
        tmp = tempfile.mkdtemp(prefix="rt_ckpt_")
        try:
            storage.download_dir(self.path, tmp)
            yield tmp
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if self.is_remote:
            storage.download_dir(self.path, dest)
        elif os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    # -- metadata ----------------------------------------------------------------------
    def _meta_addr(self) -> str:
        return storage.join_any(self.path, self._METADATA_FILE)

    def get_metadata(self) -> Dict[str, Any]:
        if self.is_remote:
            raw = storage.read_bytes(self._meta_addr())
            return json.loads(raw) if raw else {}
        if os.path.exists(self._meta_addr()):
            with open(self._meta_addr()) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        if self.is_remote:
            storage.write_bytes(self._meta_addr(), json.dumps(metadata).encode())
            return
        with open(self._meta_addr(), "w") as f:
            json.dump(metadata, f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        merged = self.get_metadata()
        merged.update(metadata)
        self.set_metadata(merged)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
