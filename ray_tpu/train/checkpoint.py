"""Directory-based checkpoints.

Reference capability: python/ray/train/_checkpoint.py:56 (Checkpoint) — a checkpoint is a
URI/path-addressed directory; frameworks read/write inside it. Orbax handles the jax pytree
serialization (see train/orbax_utils.py); this class is deliberately format-agnostic.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    """A reference to a directory holding a model snapshot."""

    _METADATA_FILE = ".metadata.json"

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"Checkpoint.from_directory: {path} is not a directory")
        return cls(path)

    @contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents (zero-copy: local paths
        are yielded directly; a remote-fs implementation would download here)."""
        yield self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    # -- metadata ----------------------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.path, self._METADATA_FILE)

    def get_metadata(self) -> Dict[str, Any]:
        if os.path.exists(self._meta_path()):
            with open(self._meta_path()) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(self._meta_path(), "w") as f:
            json.dump(metadata, f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        merged = self.get_metadata()
        merged.update(metadata)
        self.set_metadata(merged)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
