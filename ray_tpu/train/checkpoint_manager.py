"""Checkpoint persistence + top-k retention.

Reference capability: python/ray/train/_internal/checkpoint_manager.py and
_internal/storage.py (StorageContext). Worker-reported checkpoints are moved into the run
storage directory as checkpoint_{:06d}; retention ordered by CheckpointConfig's score
attribute (ties/no-score: recency).
"""
from __future__ import annotations

import os
import shutil
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..air.config import CheckpointConfig
from . import storage
from .checkpoint import Checkpoint


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    index: int
    metrics: Dict[str, Any] = field(default_factory=dict)


class CheckpointManager:
    def __init__(self, storage_dir: str, config: Optional[CheckpointConfig] = None):
        storage_dir = storage.normalize(storage_dir)
        self._remote = storage.is_remote(storage_dir)
        self.storage_dir = storage_dir if self._remote else os.path.abspath(storage_dir)
        if not self._remote:
            os.makedirs(self.storage_dir, exist_ok=True)
        self.config = config or CheckpointConfig()
        self._tracked: List[_TrackedCheckpoint] = []
        self._next_index = 0
        # Rerunning with the same RunConfig.name must continue the index sequence, not
        # collide with (and nest inside) existing checkpoint_NNNNNN directories.
        for entry in sorted(storage.listdir(self.storage_dir) if self._remote
                            else os.listdir(self.storage_dir)):
            if not entry.startswith("checkpoint_"):
                continue
            path = self._join(entry)
            if not self._remote and not os.path.isdir(path):
                continue
            ckpt = Checkpoint(path)
            meta = ckpt.get_metadata()
            idx = meta.get("index", int(entry.split("_")[1]))
            self._tracked.append(_TrackedCheckpoint(ckpt, idx, meta.get("metrics", {})))
            self._next_index = max(self._next_index, idx + 1)

    def _join(self, *parts: str) -> str:
        return storage.join_any(self.storage_dir, *parts)

    @property
    def staging_dir(self) -> str:
        """Where worker sessions stage checkpoints before registration. Local
        runs: a dir on the run's filesystem (zero-copy move). Remote runs: a
        URI under the run — workers UPLOAD there (reference storage.py:358
        persist_to_storage), so no shared disk is ever assumed."""
        return self._join(".staging")

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a worker-reported checkpoint into run storage; returns the durable one."""
        idx = self._next_index
        self._next_index += 1
        dest = self._join(f"checkpoint_{idx:06d}")
        storage.persist_dir(checkpoint.path, dest)
        durable = Checkpoint(dest)
        durable.update_metadata({"index": idx, "metrics": {k: _jsonable(v) for k, v in metrics.items()}})
        self._tracked.append(_TrackedCheckpoint(durable, idx, metrics))
        self._enforce_retention()
        return durable

    def _score(self, t: _TrackedCheckpoint):
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return t.index
        v = t.metrics.get(attr)
        if v is None:
            return float("-inf") if self.config.checkpoint_score_order == "max" else float("inf")
        return v

    def _enforce_retention(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self._tracked) <= k:
            return
        reverse = self.config.checkpoint_score_order == "max"
        ranked = sorted(self._tracked, key=self._score, reverse=reverse)
        keep = set(id(t) for t in ranked[:k])
        # Never delete the most recent checkpoint — it's the resume point.
        latest = max(self._tracked, key=lambda t: t.index)
        keep.add(id(latest))
        survivors = []
        for t in self._tracked:
            if id(t) in keep:
                survivors.append(t)
            elif t.checkpoint.is_remote:
                storage.delete(t.checkpoint.path)
            else:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._tracked = survivors

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        reverse = self.config.checkpoint_score_order == "max"
        return sorted(self._tracked, key=self._score, reverse=reverse)[0].checkpoint

    def list(self) -> List[Checkpoint]:
        return [t.checkpoint for t in sorted(self._tracked, key=lambda t: t.index)]


def _jsonable(v):
    try:
        import json

        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)
