"""Checkpoint persistence + top-k retention.

Reference capability: python/ray/train/_internal/checkpoint_manager.py and
_internal/storage.py (StorageContext). Worker-reported checkpoints are moved into the run
storage directory as checkpoint_{:06d}; retention ordered by CheckpointConfig's score
attribute (ties/no-score: recency).
"""
from __future__ import annotations

import os
import shutil
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..air.config import CheckpointConfig
from .checkpoint import Checkpoint


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    index: int
    metrics: Dict[str, Any] = field(default_factory=dict)


class CheckpointManager:
    def __init__(self, storage_dir: str, config: Optional[CheckpointConfig] = None):
        self.storage_dir = os.path.abspath(storage_dir)
        os.makedirs(self.storage_dir, exist_ok=True)
        self.config = config or CheckpointConfig()
        self._tracked: List[_TrackedCheckpoint] = []
        self._next_index = 0
        # Rerunning with the same RunConfig.name must continue the index sequence, not
        # collide with (and nest inside) existing checkpoint_NNNNNN directories.
        for entry in sorted(os.listdir(self.storage_dir)):
            path = os.path.join(self.storage_dir, entry)
            if entry.startswith("checkpoint_") and os.path.isdir(path):
                ckpt = Checkpoint(path)
                meta = ckpt.get_metadata()
                idx = meta.get("index", int(entry.split("_")[1]))
                self._tracked.append(_TrackedCheckpoint(ckpt, idx, meta.get("metrics", {})))
                self._next_index = max(self._next_index, idx + 1)

    @property
    def staging_dir(self) -> str:
        """Where worker sessions stage checkpoints before registration (same fs)."""
        return os.path.join(self.storage_dir, ".staging")

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any]) -> Checkpoint:
        """Persist a worker-reported checkpoint into run storage; returns the durable one."""
        idx = self._next_index
        self._next_index += 1
        dest = os.path.join(self.storage_dir, f"checkpoint_{idx:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            # Move when possible (same filesystem) to avoid double disk usage.
            try:
                shutil.move(checkpoint.path, dest)
            except (OSError, shutil.Error):
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        durable = Checkpoint(dest)
        durable.update_metadata({"index": idx, "metrics": {k: _jsonable(v) for k, v in metrics.items()}})
        self._tracked.append(_TrackedCheckpoint(durable, idx, metrics))
        self._enforce_retention()
        return durable

    def _score(self, t: _TrackedCheckpoint):
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return t.index
        v = t.metrics.get(attr)
        if v is None:
            return float("-inf") if self.config.checkpoint_score_order == "max" else float("inf")
        return v

    def _enforce_retention(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self._tracked) <= k:
            return
        reverse = self.config.checkpoint_score_order == "max"
        ranked = sorted(self._tracked, key=self._score, reverse=reverse)
        keep = set(id(t) for t in ranked[:k])
        # Never delete the most recent checkpoint — it's the resume point.
        latest = max(self._tracked, key=lambda t: t.index)
        keep.add(id(latest))
        survivors = []
        for t in self._tracked:
            if id(t) in keep:
                survivors.append(t)
            else:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._tracked = survivors

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._tracked:
            return None
        reverse = self.config.checkpoint_score_order == "max"
        return sorted(self._tracked, key=self._score, reverse=reverse)[0].checkpoint

    def list(self) -> List[Checkpoint]:
        return [t.checkpoint for t in sorted(self._tracked, key=lambda t: t.index)]


def _jsonable(v):
    try:
        import json

        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)
