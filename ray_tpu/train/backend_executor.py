"""BackendExecutor: drives the worker group through a training run.

Reference capability: python/ray/train/_internal/backend_executor.py — BackendExecutor
(:73), start (:146), start_training (:460) — plus the v2 controller's failure handling
(v2/_internal/execution/controller/controller.py:94): on worker failure the whole group is
torn down and restarted from the latest checkpoint, up to FailureConfig.max_failures.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.exceptions import ActorError, RayTpuError

from ..air.config import FailureConfig, ScalingConfig
from .backend import BackendConfig
from .checkpoint import Checkpoint
from .checkpoint_manager import CheckpointManager
from .result import Result
from .session import TrainContext
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    """A training worker (or the whole group) failed.

    worker_rank / error_type carry the first failed rank and its exception's
    type name (e.g. "CollectiveAbortError" when a peer rank died mid-op) so
    failure policies can classify without parsing tracebacks."""

    worker_rank: Optional[int] = None
    error_type: Optional[str] = None


def restart_backoff_s(failure_count: int) -> float:
    """Bounded exponential backoff before worker-group restart N: a crash loop
    (bad checkpoint, flapping node) must not hot-spin group construction."""
    from ray_tpu.config import CONFIG

    base = CONFIG.train_restart_backoff_s
    if base <= 0:
        return 0.0
    return min(CONFIG.train_restart_backoff_max_s,
               base * (2 ** max(0, failure_count - 1)))


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        checkpoint_manager: Optional[CheckpointManager] = None,
        failure_config: Optional[FailureConfig] = None,
        experiment_name: str = "",
        poll_interval_s: float = 0.05,
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling_config = scaling_config
        self.checkpoint_manager = checkpoint_manager
        self.failure_config = failure_config or FailureConfig()
        self.experiment_name = experiment_name
        self.poll_interval_s = poll_interval_s
        self.worker_group: Optional[WorkerGroup] = None
        self._latest_metrics: Dict[str, Any] = {}
        self._history: List[Dict[str, Any]] = []
        self._per_worker: Dict[int, Dict[str, Any]] = {}  # rank -> last metrics + node

    # -- lifecycle ---------------------------------------------------------------------
    def start(self) -> None:
        self.worker_group = WorkerGroup(
            num_workers=self.scaling_config.num_workers,
            resources_per_worker=self.scaling_config.worker_resources(),
            placement_strategy=self.scaling_config.placement_strategy,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        train_loop_config: Dict[str, Any],
        datasets: Optional[Dict[str, Any]] = None,
        checkpoint: Optional[Checkpoint] = None,
    ) -> None:
        assert self.worker_group is not None, "call start() first"
        self.backend.on_training_start(self.worker_group, self.backend_config)
        node_ranks = self.worker_group.node_ranks()
        local_counts: Dict[int, int] = {}
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            nr = node_ranks[rank]
            local_rank = local_counts.get(nr, 0)
            local_counts[nr] = local_rank + 1
            ctx = TrainContext(
                world_size=len(self.worker_group),
                world_rank=rank,
                local_rank=local_rank,
                local_world_size=node_ranks.count(nr),
                node_rank=nr,
                experiment_name=self.experiment_name,
            )
            shards = _split_datasets(datasets, rank, len(self.worker_group))
            staging = (
                self.checkpoint_manager.staging_dir if self.checkpoint_manager else None
            )
            refs.append(
                w.start_session.remote(
                    train_fn, dict(train_loop_config), ctx, checkpoint, shards, staging
                )
            )
        ray_tpu.get(refs)

    def poll(self) -> Dict[str, Any]:
        """One poll cycle. Returns {"finished": bool}; raises on worker failure."""
        assert self.worker_group is not None
        polls = ray_tpu.get([w.poll_session.remote() for w in self.worker_group.workers])
        # Drain reports BEFORE surfacing errors: checkpoints reported ahead of a crash are
        # exactly what the restart resumes from. Metrics: rank 0 is canonical.
        self._register_rank0_reports(polls[0]["reports"])
        metas = self.worker_group.metadata
        for rank, p in enumerate(polls):
            if p["reports"]:
                # per-worker visibility (reference: per-worker metrics in
                # train result) — lets callers assert placement, e.g. one
                # worker per host under STRICT_SPREAD
                self._per_worker[rank] = {
                    **p["reports"][-1]["metrics"],
                    "rank": rank, "node": metas[rank].node_id}
        for rank, p in enumerate(polls):
            if p["error"]:
                e = TrainingFailedError(f"worker rank {rank} failed:\n{p['error']}")
                e.worker_rank = rank
                e.error_type = p.get("error_type")
                raise e
        return {"finished": all(p["finished"] for p in polls)}

    def all_metrics(self) -> List[Dict[str, Any]]:
        """Last reported metrics of every worker rank, each tagged with its
        node id."""
        return [self._per_worker[r] for r in sorted(self._per_worker)]

    def _register_rank0_reports(self, reports: List[Dict[str, Any]]) -> None:
        """Record rank 0's canonical reports (metrics history + durable
        checkpoints) — shared by poll() and the post-failure salvage drain so
        what a restart resumes from never diverges from what polling records."""
        for rep in reports:
            metrics = rep["metrics"]
            self._latest_metrics = metrics
            self._history.append(metrics)
            ckpt = rep["checkpoint"]
            if ckpt is not None and self.checkpoint_manager is not None:
                self.checkpoint_manager.register(ckpt, metrics)

    def drain_after_failure(self, grace_s: float = 2.0) -> None:
        """Salvage surviving ranks' last reports before tearing the group down.

        A worker failure races the other ranks' reporting: rank 0's checkpoint
        for step N may be staged (durable) but not yet polled when another
        rank's error surfaces — and losing it restarts the run from a much
        older step, or from nothing. Give surviving sessions a bounded grace
        period to settle (the backend's abort hook has already unblocked any
        rank stuck in a collective), drain their queues, and register what was
        reported. Best-effort: dead actors and still-hung sessions are skipped.
        """
        if self.worker_group is None:
            return
        deadline = time.monotonic() + grace_s
        while True:
            settled = True
            for rank, w in enumerate(self.worker_group.workers):
                try:
                    p = ray_tpu.get(w.poll_session.remote(),
                                    timeout=max(0.1, deadline - time.monotonic()))
                # graftlint: allow[swallowed-exception] dead/unreachable worker: nothing to salvage there, survivors carry on
                except Exception:
                    continue  # dead/unreachable: nothing to salvage there
                if rank == 0:
                    self._register_rank0_reports(p["reports"])
                if not p["finished"]:
                    settled = False
            if settled or time.monotonic() >= deadline:
                return
            time.sleep(self.poll_interval_s)

    def salvage_after_failure(self, error: BaseException) -> None:
        """The one failure-salvage sequence both the v1 run loop and the v2
        TrainController use: unblock survivors stuck in a collective (the
        backend's abort hook beats the op timeout), then drain their
        already-reported checkpoints before a non-graceful teardown discards
        them. Best-effort — the group is about to be torn down regardless."""
        try:
            if self.worker_group is not None:
                self.backend.on_failure(self.worker_group, self.backend_config, error)
            self.drain_after_failure()
        except Exception as e:
            logger.warning("failure-handling hook raised (%r): worker "
                           "checkpoint salvage may be incomplete for this "
                           "restart", e)

    def run_until_complete(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        train_loop_config: Dict[str, Any],
        datasets: Optional[Dict[str, Any]] = None,
        resume_checkpoint: Optional[Checkpoint] = None,
    ) -> Result:
        """Full run with group-restart failure policy."""
        failures_allowed = self.failure_config.max_failures
        checkpoint = resume_checkpoint
        if checkpoint is None and self.checkpoint_manager is not None:
            checkpoint = self.checkpoint_manager.latest_checkpoint
        error: Optional[str] = None
        failure_count = 0
        while True:
            try:
                if self.worker_group is None:
                    self.start()
                self.start_training(train_fn, train_loop_config, datasets, checkpoint)
                while True:
                    state = self.poll()
                    if state["finished"]:
                        break
                    time.sleep(self.poll_interval_s)
                break  # success
            except (TrainingFailedError, ActorError, RayTpuError) as e:
                logger.warning("training worker group failed: %s", e)
                failure_count += 1
                self.salvage_after_failure(e)
                self.shutdown(graceful=False)
                if failures_allowed == 0:
                    error = str(e)
                    break
                if failures_allowed > 0:
                    failures_allowed -= 1
                # Restart from the most recent durable checkpoint.
                if self.checkpoint_manager is not None:
                    checkpoint = self.checkpoint_manager.latest_checkpoint or resume_checkpoint
                time.sleep(restart_backoff_s(failure_count))
        latest_ckpt = (
            self.checkpoint_manager.latest_checkpoint if self.checkpoint_manager else None
        )
        best_ckpt = self.checkpoint_manager.best_checkpoint if self.checkpoint_manager else None
        return Result(
            metrics=self._latest_metrics,
            checkpoint=latest_ckpt,
            best_checkpoint=best_ckpt,
            error=error,
            metrics_dataframe=list(self._history),
            all_metrics=self.all_metrics(),
        )

    def shutdown(self, graceful: bool = True) -> None:
        if self.worker_group is None:
            return
        if graceful:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
                ray_tpu.get([w.end_session.remote() for w in self.worker_group.workers])
            # graftlint: allow[swallowed-exception] shutdown teardown: workers may already be gone
            except Exception:
                pass
        self.worker_group.shutdown()
        self.worker_group = None


def _split_datasets(datasets: Optional[Dict[str, Any]], rank: int, world: int):
    """Per-worker dataset shards (reference _internal/data_config.py). Datasets exposing
    split_at_indices/streaming_split get sharded; plain iterables pass through whole."""
    if not datasets:
        return {}
    out = {}
    for name, ds in datasets.items():
        if hasattr(ds, "split_for_workers"):
            out[name] = ds.split_for_workers(world)[rank]
        else:
            out[name] = ds
    return out
