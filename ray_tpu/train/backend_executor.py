"""BackendExecutor: drives the worker group through a training run.

Reference capability: python/ray/train/_internal/backend_executor.py — BackendExecutor
(:73), start (:146), start_training (:460) — plus the v2 controller's failure handling
(v2/_internal/execution/controller/controller.py:94): on worker failure the whole group is
torn down and restarted from the latest checkpoint, up to FailureConfig.max_failures.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.exceptions import ActorError, RayTpuError

from ..air.config import FailureConfig, ScalingConfig
from .backend import BackendConfig
from .checkpoint import Checkpoint
from .checkpoint_manager import CheckpointManager
from .result import Result
from .session import TrainContext
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    """Raised when training fails beyond the failure policy's budget."""


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        checkpoint_manager: Optional[CheckpointManager] = None,
        failure_config: Optional[FailureConfig] = None,
        experiment_name: str = "",
        poll_interval_s: float = 0.05,
    ):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling_config = scaling_config
        self.checkpoint_manager = checkpoint_manager
        self.failure_config = failure_config or FailureConfig()
        self.experiment_name = experiment_name
        self.poll_interval_s = poll_interval_s
        self.worker_group: Optional[WorkerGroup] = None
        self._latest_metrics: Dict[str, Any] = {}
        self._history: List[Dict[str, Any]] = []
        self._per_worker: Dict[int, Dict[str, Any]] = {}  # rank -> last metrics + node

    # -- lifecycle ---------------------------------------------------------------------
    def start(self) -> None:
        self.worker_group = WorkerGroup(
            num_workers=self.scaling_config.num_workers,
            resources_per_worker=self.scaling_config.worker_resources(),
            placement_strategy=self.scaling_config.placement_strategy,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        train_loop_config: Dict[str, Any],
        datasets: Optional[Dict[str, Any]] = None,
        checkpoint: Optional[Checkpoint] = None,
    ) -> None:
        assert self.worker_group is not None, "call start() first"
        self.backend.on_training_start(self.worker_group, self.backend_config)
        node_ranks = self.worker_group.node_ranks()
        local_counts: Dict[int, int] = {}
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            nr = node_ranks[rank]
            local_rank = local_counts.get(nr, 0)
            local_counts[nr] = local_rank + 1
            ctx = TrainContext(
                world_size=len(self.worker_group),
                world_rank=rank,
                local_rank=local_rank,
                local_world_size=node_ranks.count(nr),
                node_rank=nr,
                experiment_name=self.experiment_name,
            )
            shards = _split_datasets(datasets, rank, len(self.worker_group))
            staging = (
                self.checkpoint_manager.staging_dir if self.checkpoint_manager else None
            )
            refs.append(
                w.start_session.remote(
                    train_fn, dict(train_loop_config), ctx, checkpoint, shards, staging
                )
            )
        ray_tpu.get(refs)

    def poll(self) -> Dict[str, Any]:
        """One poll cycle. Returns {"finished": bool}; raises on worker failure."""
        assert self.worker_group is not None
        polls = ray_tpu.get([w.poll_session.remote() for w in self.worker_group.workers])
        # Drain reports BEFORE surfacing errors: checkpoints reported ahead of a crash are
        # exactly what the restart resumes from. Metrics: rank 0 is canonical.
        rank0_reports = polls[0]["reports"]
        for rep in rank0_reports:
            metrics = rep["metrics"]
            self._latest_metrics = metrics
            self._history.append(metrics)
            ckpt = rep["checkpoint"]
            if ckpt is not None and self.checkpoint_manager is not None:
                self.checkpoint_manager.register(ckpt, metrics)
        metas = self.worker_group.metadata
        for rank, p in enumerate(polls):
            if p["reports"]:
                # per-worker visibility (reference: per-worker metrics in
                # train result) — lets callers assert placement, e.g. one
                # worker per host under STRICT_SPREAD
                self._per_worker[rank] = {
                    **p["reports"][-1]["metrics"],
                    "rank": rank, "node": metas[rank].node_id}
        for rank, p in enumerate(polls):
            if p["error"]:
                raise TrainingFailedError(f"worker rank {rank} failed:\n{p['error']}")
        return {"finished": all(p["finished"] for p in polls)}

    def all_metrics(self) -> List[Dict[str, Any]]:
        """Last reported metrics of every worker rank, each tagged with its
        node id."""
        return [self._per_worker[r] for r in sorted(self._per_worker)]

    def run_until_complete(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        train_loop_config: Dict[str, Any],
        datasets: Optional[Dict[str, Any]] = None,
        resume_checkpoint: Optional[Checkpoint] = None,
    ) -> Result:
        """Full run with group-restart failure policy."""
        failures_allowed = self.failure_config.max_failures
        checkpoint = resume_checkpoint
        if checkpoint is None and self.checkpoint_manager is not None:
            checkpoint = self.checkpoint_manager.latest_checkpoint
        error: Optional[str] = None
        while True:
            try:
                if self.worker_group is None:
                    self.start()
                self.start_training(train_fn, train_loop_config, datasets, checkpoint)
                while True:
                    state = self.poll()
                    if state["finished"]:
                        break
                    time.sleep(self.poll_interval_s)
                break  # success
            except (TrainingFailedError, ActorError, RayTpuError) as e:
                logger.warning("training worker group failed: %s", e)
                self.shutdown(graceful=False)
                if failures_allowed == 0:
                    error = str(e)
                    break
                if failures_allowed > 0:
                    failures_allowed -= 1
                # Restart from the most recent durable checkpoint.
                if self.checkpoint_manager is not None:
                    checkpoint = self.checkpoint_manager.latest_checkpoint or resume_checkpoint
        latest_ckpt = (
            self.checkpoint_manager.latest_checkpoint if self.checkpoint_manager else None
        )
        best_ckpt = self.checkpoint_manager.best_checkpoint if self.checkpoint_manager else None
        return Result(
            metrics=self._latest_metrics,
            checkpoint=latest_ckpt,
            best_checkpoint=best_ckpt,
            error=error,
            metrics_dataframe=list(self._history),
            all_metrics=self.all_metrics(),
        )

    def shutdown(self, graceful: bool = True) -> None:
        if self.worker_group is None:
            return
        if graceful:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
                ray_tpu.get([w.end_session.remote() for w in self.worker_group.workers])
            except Exception:
                pass
        self.worker_group.shutdown()
        self.worker_group = None


def _split_datasets(datasets: Optional[Dict[str, Any]], rank: int, world: int):
    """Per-worker dataset shards (reference _internal/data_config.py). Datasets exposing
    split_at_indices/streaming_split get sharded; plain iterables pass through whole."""
    if not datasets:
        return {}
    out = {}
    for name, ds in datasets.items():
        if hasattr(ds, "split_for_workers"):
            out[name] = ds.split_for_workers(world)[rank]
        else:
            out[name] = ds
    return out
