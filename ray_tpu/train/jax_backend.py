"""JaxConfig / JaxBackend: the jax.distributed process-group bootstrap.

Reference shape: python/ray/train/torch/config.py — TorchConfig (:36), _TorchBackend
(:153), _setup_torch_process_group (:66). The reference rendezvouses a NCCL process group;
here the worker group forms ONE jax.distributed universe so workers can build a global
device Mesh spanning every chip of the pod slice, and gradient sync happens *inside* pjit
programs as XLA collectives over ICI — there is no NCCL analogue to configure.

SURVEY.md §2.4 notes JaxTrainer does not exist in the reference; this follows the Backend
plugin shape it prescribes.
"""
from __future__ import annotations

import logging
import os
import socket
from dataclasses import dataclass
from typing import Dict, Optional, Type

from .backend import Backend, BackendConfig
from .grad_sync import GradSyncConfig
from .worker_group import WorkerGroup

LOGGER = logging.getLogger(__name__)


@dataclass
class JaxConfig(BackendConfig):
    """Backend config for JAX workers.

    distributed: form a jax.distributed universe across workers (multi-host pods). Off by
      default for single-host/CPU test runs where each worker keeps a private runtime.
    platform: value for JAX_PLATFORMS in workers ("" = leave as-is / auto-detect TPU).
    collective_group: also create a host-plane shm collective group named "train" over the
      workers (out-of-jit weight broadcast / metric reduction; reference's gloo group).
    grad_sync: device-plane gradient-sync strategy (train/grad_sync.py: bucketed
      overlapped all-reduce, int8 reduction, cross-replica sharded optimizer update).
      Exported to the workers' env, so user loops that call `make_train_step()` /
      `init_state()` without an explicit `sync=` pick it up — the stock-Trainer-API
      config flag.
    """

    distributed: bool = False
    platform: str = ""
    coordinator_port: int = 0
    collective_group: bool = True
    # Unique per run unless pinned: two concurrent trainers must not share a coordinator.
    collective_group_name: str = ""
    grad_sync: Optional[GradSyncConfig] = None
    env: Optional[Dict[str, str]] = None  # extra env vars set in workers before jax import

    @property
    def backend_cls(self) -> Type["JaxBackend"]:
        return JaxBackend


# Rendezvous bound. jax's default initialization_timeout is 300s; the retry
# path below queues behind first-round tasks still blocked in connect (train
# workers execute serially), so a failed first round must release its workers
# well before the fresh coordinator of the retry gives up waiting for them.
_JAX_INIT_TIMEOUT_S = int(os.environ.get("RAY_TPU_TRAIN_JAX_INIT_TIMEOUT_S", "60"))


def _init_jax_distributed(coordinator_address: str, num_processes: int, process_id: int) -> None:
    import jax

    # Re-entrant for the coordinator-port retry: a worker whose first
    # rendezvous died mid-connect still holds the half-initialized client
    # (jax assigns global_state.client BEFORE connect()), and initialize()
    # refuses to run twice. Tear the remnant down first.
    try:
        from jax._src.distributed import global_state as _gs

        if getattr(_gs, "client", None) is not None:
            jax.distributed.shutdown()
    except Exception as e:  # noqa: BLE001 — half-dead client; proceed to init
        LOGGER.warning("jax.distributed pre-init cleanup failed: %r", e)

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        initialization_timeout=_JAX_INIT_TIMEOUT_S,
    )


def _pick_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _is_bind_failure(err: BaseException) -> bool:
    """Did jax.distributed.initialize lose the _pick_port bind->close->reuse
    race (another process grabbed the port between probe and coordinator
    startup)? Matched narrowly: worker errors arrive as TaskError whose str()
    embeds the WHOLE remote traceback, so a generic token like "bind" would
    match unrelated frames (e.g. a `sock.bind(...)` source line) and send an
    unrelated failure into a doomed retry that buries the real error."""
    import errno

    if isinstance(err, OSError) and err.errno == errno.EADDRINUSE:
        return True  # direct (non-wrapped) bind failure
    msg = str(err).lower()
    return any(tok in msg
               for tok in ("failed to bind", "bind failed",
                           "address already in use", "errno 98"))


class JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig) -> None:
        if backend_config.collective_group and not backend_config.collective_group_name:
            import uuid

            backend_config.collective_group_name = f"train_{uuid.uuid4().hex[:8]}"
        group_name = backend_config.collective_group_name
        envs = []
        for rank in range(len(worker_group)):
            env = {
                "RAY_TPU_TRAIN_WORLD_SIZE": str(len(worker_group)),
                "RAY_TPU_TRAIN_RANK": str(rank),
            }
            if backend_config.collective_group:
                env["RAY_TPU_TRAIN_COLLECTIVE_GROUP"] = group_name
            if backend_config.platform:
                env["JAX_PLATFORMS"] = backend_config.platform
            if backend_config.grad_sync is not None:
                env.update(backend_config.grad_sync.to_env())
            if backend_config.env:
                env.update(backend_config.env)
            envs.append(env)
        worker_group.set_env(envs)

        if backend_config.distributed and len(worker_group) > 1:
            host = worker_group.execute_single(0, socket.gethostname)
            import ray_tpu

            def _rendezvous(port: int) -> None:
                addr = f"{host}:{port}"
                refs = [
                    w.run_fn.remote(_init_jax_distributed, addr, len(worker_group), rank)
                    for rank, w in enumerate(worker_group.workers)
                ]
                ray_tpu.get(refs)

            # Pick the port ON worker 0's host — a driver-side free port proves nothing
            # about the machine that will actually bind it.
            port = backend_config.coordinator_port or worker_group.execute_single(0, _pick_port)
            try:
                _rendezvous(port)
            except Exception as e:
                # _pick_port's bind->close->probe leaves a TOCTOU window:
                # another process can claim the port before the coordinator
                # binds it. One retry with a fresh probe (only when the port
                # was OURS to re-pick) beats failing the whole run.
                if backend_config.coordinator_port or not _is_bind_failure(e):
                    raise
                port = worker_group.execute_single(0, _pick_port)
                LOGGER.warning(
                    "jax.distributed coordinator lost the port race (%s); "
                    "retrying once on fresh port %d", e, port)
                _rendezvous(port)

        if backend_config.collective_group:
            from ray_tpu.util import collective as col
            from ray_tpu.util import telemetry

            # Clear any stale coordinator (e.g. from a crashed prior generation of this
            # run) so the new generation's sequence numbers start on clean boards.
            with telemetry.span("train.collective_init", "train",
                                group=group_name, world=len(worker_group)):
                col.kill_coordinator(group_name)
                col.create_collective_group(
                    worker_group.workers,
                    len(worker_group),
                    list(range(len(worker_group))),
                    backend="shm",
                    group_name=group_name,
                )

    def on_failure(self, worker_group: WorkerGroup, backend_config: JaxConfig,
                   error: BaseException) -> None:
        """Poison the run's collective group before the non-graceful teardown.

        When one rank's session dies (an exception in the user loop — no
        process death, so core worker-death cleanup never fires), its peers
        may be blocked mid-allreduce with nobody left to arrive. The abort
        converts that wait into a fast CollectiveAbortError, so survivors
        finish their sessions in time for the executor's salvage drain and
        the group restart is not pinned behind collective_op_timeout_s."""
        if backend_config.collective_group and backend_config.collective_group_name:
            from ray_tpu.util import collective as col
            from ray_tpu.util import telemetry

            telemetry.get_counter(
                "train_group_failures_total",
                "training worker-group failures that poisoned the run's "
                "collective group").inc()
            telemetry.event("train.abort", "train",
                            group=backend_config.collective_group_name,
                            reason=str(error)[:200])
            # wait=False: on_failure must not block on the (possibly half-
            # dead) group — a wedged coordinator host would otherwise pin the
            # restart behind the op timeout, the exact stall this hook exists
            # to avoid
            col.abort_collective_group(
                backend_config.collective_group_name,
                reason=f"training worker group failed: {error}",
                wait=False)

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: JaxConfig) -> None:
        def _shutdown():
            import jax

            try:
                if jax.process_count() > 1:
                    jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                _warn_shutdown_failure("jax.distributed.shutdown", e)

        try:
            worker_group.execute(_shutdown)
        except Exception as e:  # noqa: BLE001 — workers may already be dead
            _warn_shutdown_failure("worker group shutdown broadcast", e)
        if backend_config.collective_group and backend_config.collective_group_name:
            from ray_tpu.util import collective as col

            col.kill_coordinator(backend_config.collective_group_name)


_shutdown_warn_interval_s = 30.0
_last_shutdown_warning = [0.0]  # monotonic stamp (same convention as tracing._maybe_flush)


def _warn_shutdown_failure(what: str, err: BaseException) -> None:
    """Teardown is best-effort, but a swallowed error is undiagnosable — log it
    (throttled, the repo convention since PR 8's tracing._maybe_flush fix)."""
    import time

    now = time.monotonic()
    if now - _last_shutdown_warning[0] >= _shutdown_warn_interval_s:
        _last_shutdown_warning[0] = now
        LOGGER.warning("JaxBackend.on_shutdown: %s failed: %r (continuing "
                       "teardown; further failures muted for %.0fs)",
                       what, err, _shutdown_warn_interval_s)
