"""JaxConfig / JaxBackend: the jax.distributed process-group bootstrap.

Reference shape: python/ray/train/torch/config.py — TorchConfig (:36), _TorchBackend
(:153), _setup_torch_process_group (:66). The reference rendezvouses a NCCL process group;
here the worker group forms ONE jax.distributed universe so workers can build a global
device Mesh spanning every chip of the pod slice, and gradient sync happens *inside* pjit
programs as XLA collectives over ICI — there is no NCCL analogue to configure.

SURVEY.md §2.4 notes JaxTrainer does not exist in the reference; this follows the Backend
plugin shape it prescribes.
"""
from __future__ import annotations

import socket
from dataclasses import dataclass
from typing import Dict, Optional, Type

from .backend import Backend, BackendConfig
from .worker_group import WorkerGroup


@dataclass
class JaxConfig(BackendConfig):
    """Backend config for JAX workers.

    distributed: form a jax.distributed universe across workers (multi-host pods). Off by
      default for single-host/CPU test runs where each worker keeps a private runtime.
    platform: value for JAX_PLATFORMS in workers ("" = leave as-is / auto-detect TPU).
    collective_group: also create a host-plane shm collective group named "train" over the
      workers (out-of-jit weight broadcast / metric reduction; reference's gloo group).
    """

    distributed: bool = False
    platform: str = ""
    coordinator_port: int = 0
    collective_group: bool = True
    # Unique per run unless pinned: two concurrent trainers must not share a coordinator.
    collective_group_name: str = ""
    env: Optional[Dict[str, str]] = None  # extra env vars set in workers before jax import

    @property
    def backend_cls(self) -> Type["JaxBackend"]:
        return JaxBackend


def _init_jax_distributed(coordinator_address: str, num_processes: int, process_id: int) -> None:
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def _pick_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig) -> None:
        if backend_config.collective_group and not backend_config.collective_group_name:
            import uuid

            backend_config.collective_group_name = f"train_{uuid.uuid4().hex[:8]}"
        group_name = backend_config.collective_group_name
        envs = []
        for rank in range(len(worker_group)):
            env = {
                "RAY_TPU_TRAIN_WORLD_SIZE": str(len(worker_group)),
                "RAY_TPU_TRAIN_RANK": str(rank),
            }
            if backend_config.collective_group:
                env["RAY_TPU_TRAIN_COLLECTIVE_GROUP"] = group_name
            if backend_config.platform:
                env["JAX_PLATFORMS"] = backend_config.platform
            if backend_config.env:
                env.update(backend_config.env)
            envs.append(env)
        worker_group.set_env(envs)

        if backend_config.distributed and len(worker_group) > 1:
            host = worker_group.execute_single(0, socket.gethostname)
            # Pick the port ON worker 0's host — a driver-side free port proves nothing
            # about the machine that will actually bind it.
            port = backend_config.coordinator_port or worker_group.execute_single(0, _pick_port)
            addr = f"{host}:{port}"
            import ray_tpu

            refs = [
                w.run_fn.remote(_init_jax_distributed, addr, len(worker_group), rank)
                for rank, w in enumerate(worker_group.workers)
            ]
            ray_tpu.get(refs)

        if backend_config.collective_group:
            from ray_tpu.util import collective as col
            from ray_tpu.util import telemetry

            # Clear any stale coordinator (e.g. from a crashed prior generation of this
            # run) so the new generation's sequence numbers start on clean boards.
            with telemetry.span("train.collective_init", "train",
                                group=group_name, world=len(worker_group)):
                col.kill_coordinator(group_name)
                col.create_collective_group(
                    worker_group.workers,
                    len(worker_group),
                    list(range(len(worker_group))),
                    backend="shm",
                    group_name=group_name,
                )

    def on_failure(self, worker_group: WorkerGroup, backend_config: JaxConfig,
                   error: BaseException) -> None:
        """Poison the run's collective group before the non-graceful teardown.

        When one rank's session dies (an exception in the user loop — no
        process death, so core worker-death cleanup never fires), its peers
        may be blocked mid-allreduce with nobody left to arrive. The abort
        converts that wait into a fast CollectiveAbortError, so survivors
        finish their sessions in time for the executor's salvage drain and
        the group restart is not pinned behind collective_op_timeout_s."""
        if backend_config.collective_group and backend_config.collective_group_name:
            from ray_tpu.util import collective as col
            from ray_tpu.util import telemetry

            telemetry.get_counter(
                "train_group_failures_total",
                "training worker-group failures that poisoned the run's "
                "collective group").inc()
            telemetry.event("train.abort", "train",
                            group=backend_config.collective_group_name,
                            reason=str(error)[:200])
            # wait=False: on_failure must not block on the (possibly half-
            # dead) group — a wedged coordinator host would otherwise pin the
            # restart behind the op timeout, the exact stall this hook exists
            # to avoid
            col.abort_collective_group(
                backend_config.collective_group_name,
                reason=f"training worker group failed: {error}",
                wait=False)

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: JaxConfig) -> None:
        def _shutdown():
            import jax

            try:
                if jax.process_count() > 1:
                    jax.distributed.shutdown()
            except Exception:
                pass

        try:
            worker_group.execute(_shutdown)
        except Exception:
            pass
        if backend_config.collective_group and backend_config.collective_group_name:
            from ray_tpu.util import collective as col

            col.kill_coordinator(backend_config.collective_group_name)
