"""TensorflowConfig / TensorflowBackend: TF_CONFIG multi-worker bootstrap.

Capability parity: reference python/ray/train/tensorflow/config.py —
_setup_tensorflow_environment (:24) assembles the ``TF_CONFIG`` cluster spec
(one "worker" URL per rank, task index = rank) that
``tf.distribute.MultiWorkerMirroredStrategy`` reads at construction time.

On TPU hosts the supported device for TF user code is CPU — the TPU compute
path is JaxTrainer — so this backend exists for parity with TF data pipelines
and Keras models users bring along, not as a TPU training path.
"""
from __future__ import annotations

import json
import socket
from dataclasses import dataclass
from typing import List, Type

from .backend import Backend, BackendConfig
from .worker_group import WorkerGroup


def _bind_free_port() -> tuple:
    """Return (ip, port) for this worker; port is free at call time (the same
    pick-then-release rendezvous the reference's get_address_and_port does)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return "127.0.0.1", port


def _apply_tf_config(worker_urls: List[str], index: int) -> None:
    import os

    tf_config = {
        "cluster": {"worker": worker_urls},
        "task": {"type": "worker", "index": index},
    }
    os.environ["TF_CONFIG"] = json.dumps(tf_config)


def _clear_tf_config() -> None:
    import os

    os.environ.pop("TF_CONFIG", None)


@dataclass
class TensorflowConfig(BackendConfig):
    @property
    def backend_cls(self) -> Type["TensorflowBackend"]:
        return TensorflowBackend


class TensorflowBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: TensorflowConfig) -> None:
        addrs = worker_group.execute(_bind_free_port)
        urls = [f"{ip}:{port}" for ip, port in addrs]
        import ray_tpu

        ray_tpu.get([
            w.run_fn.remote(_apply_tf_config, urls, rank)
            for rank, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: TensorflowConfig) -> None:
        try:
            worker_group.execute(_clear_tf_config)
        # graftlint: allow[swallowed-exception] best-effort worker-env teardown (TF_CONFIG)
        except Exception:
            pass


def prepare_dataset_shard(tf_dataset_shard):
    """Disable TF autosharding on an already-sharded dataset (reference
    ray.train.tensorflow.prepare_dataset_shard, train/tensorflow/train_loop_utils.py)."""
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = (
        tf.data.experimental.AutoShardPolicy.OFF
    )
    return tf_dataset_shard.with_options(options)
