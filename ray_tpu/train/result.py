"""Result of a training run (reference: python/ray/train/result.py / air Result)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    best_checkpoint: Optional[Checkpoint] = None
    path: Optional[str] = None
    error: Optional[str] = None
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None  # metric history (list of dicts)
    # last reported metrics per worker rank, tagged with the worker's node id
    all_metrics: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def best_checkpoints(self) -> List[Checkpoint]:
        return [c for c in [self.best_checkpoint] if c is not None]
