"""ray_tpu.train: Train-API-shaped distributed training on TPU.

Reference capability: python/ray/train/ (SURVEY.md §2.4). The `JaxTrainer` here is the
north-star API the reference lacks (no JaxTrainer exists upstream — SURVEY.md §2.4 note).

Public surface mirrors ray.train: report/get_context/get_checkpoint/get_dataset_shard
inside the worker loop; JaxTrainer(...).fit() on the driver; ScalingConfig/RunConfig etc.
re-exported from ray_tpu.air.
"""
from ..air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from .backend import Backend, BackendConfig  # noqa: F401
from .checkpoint import Checkpoint  # noqa: F401
from .data_parallel_trainer import (  # noqa: F401
    DataParallelTrainer,
    JaxTrainer,
    TensorflowTrainer,
    TorchTrainer,
)
from .jax_backend import JaxBackend, JaxConfig  # noqa: F401
from .torch_backend import TorchBackend, TorchConfig  # noqa: F401
from .tensorflow_backend import TensorflowBackend, TensorflowConfig  # noqa: F401
from .gbdt import (  # noqa: F401  (optional-dep GBDT family)
    LightGBMConfig,
    LightGBMTrainer,
    XGBoostConfig,
    XGBoostTrainer,
)
from . import gbdt as xgboost  # noqa: F401
from . import gbdt as lightgbm  # noqa: F401
from . import huggingface  # noqa: F401
from . import lightning  # noqa: F401
from . import torch_backend as torch  # noqa: F401  (ray_tpu.train.torch.prepare_model)

# reference import shapes: `from ray_tpu.train.torch import prepare_model`,
# `from ray_tpu.train.xgboost import get_rabit_args`, ...
import sys as _sys

_sys.modules[__name__ + ".torch"] = torch
_sys.modules[__name__ + ".xgboost"] = xgboost
_sys.modules[__name__ + ".lightgbm"] = lightgbm
from .result import Result  # noqa: F401
from .session import (  # noqa: F401
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
    step_phase,
)
from .step import TrainState, init_state, make_optimizer, make_train_step  # noqa: F401
from . import grad_sync  # noqa: F401
from .grad_sync import GradSyncConfig  # noqa: F401
from . import mpmd_pipeline  # noqa: F401
from .mpmd_pipeline import (  # noqa: F401  (cross-process MPMD pipeline runner)
    MPMDPipeline,
    MPMDPipelineConfig,
    StageRunner,
    stage_runner_from_train_context,
)
from .v2 import (  # noqa: F401  (Train v2: controller + policies, SURVEY §2.4)
    DefaultFailurePolicy,
    ElasticScalingPolicy,
    FailureDecision,
    FailurePolicy,
    FixedScalingPolicy,
    ResizeDecision,
    ScalingPolicy,
    TrainController,
    TrainControllerState,
)
