"""ray_tpu.train: Train-API-shaped distributed training on TPU.

Reference capability: python/ray/train/ (SURVEY.md §2.4). The `JaxTrainer` here is the
north-star API the reference lacks (no JaxTrainer exists upstream — SURVEY.md §2.4 note).
"""
from .step import TrainState, init_state, make_optimizer, make_train_step  # noqa: F401
