"""WorkerGroup: N training-worker actors, optionally inside a placement group.

Reference capability: python/ray/train/_internal/worker_group.py:102 (WorkerGroup,
RayTrainWorker). Worker actors host a _TrainSession on a daemon thread; the executor
polls them for reports.
"""
from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.task_spec import PlacementGroupSchedulingStrategy
from ray_tpu.util import placement_group_api as pg_api

from .session import TrainContext, _TrainSession, _set_session


class RayTrainWorker:
    """The per-worker actor (reference worker_group.py RayTrainWorker)."""

    def __init__(self):
        self._session: Optional[_TrainSession] = None

    def get_metadata(self) -> Dict[str, Any]:
        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": ctx.get_node_id(),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
        }

    def set_env(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def run_fn(self, fn: Callable, *args, **kwargs):
        """Execute an arbitrary function in the worker (backend hooks use this)."""
        return fn(*args, **kwargs)

    def start_session(
        self,
        train_fn: Callable[[Dict[str, Any]], None],
        config: Dict[str, Any],
        context: TrainContext,
        checkpoint=None,
        dataset_shards: Optional[Dict[str, Any]] = None,
        staging_dir: Optional[str] = None,
    ) -> None:
        if self._session is not None and not self._session.finished.is_set():
            raise RuntimeError("a training session is already running in this worker")
        self._session = _TrainSession(
            train_fn, config, context, checkpoint, dataset_shards, staging_dir
        )
        _set_session(self._session)
        self._session.start()

    def poll_session(self) -> Dict[str, Any]:
        s = self._session
        if s is None:
            return {"reports": [], "finished": True, "error": None,
                    "error_type": None}
        reports = s.drain()
        err = err_type = None
        if s.finished.is_set() and s.error is not None:
            import traceback

            err = "".join(traceback.format_exception(s.error)).strip()
            # the exception's type name rides alongside the formatted traceback
            # so the executor can classify the failure (e.g. CollectiveAbortError
            # = a peer rank died mid-op) without parsing text
            err_type = type(s.error).__name__
        return {"reports": reports, "finished": s.finished.is_set(), "error": err,
                "error_type": err_type}

    def end_session(self) -> None:
        self._session = None
        _set_session(None)

    def _ray_tpu_collective_init(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend, group_name)


@dataclass
class WorkerMetadata:
    node_id: str
    hostname: str
    pid: int


class WorkerGroup:
    """Creates and addresses the N RayTrainWorker actors."""

    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        use_placement_group: bool = True,
        worker_cls: type = RayTrainWorker,
    ):
        self.num_workers = num_workers
        self._pg = None
        actor_cls = ray_tpu.remote(worker_cls)
        num_cpus = resources_per_worker.get("CPU", 1.0)
        num_tpus = resources_per_worker.get("TPU", 0.0)
        extra = {k: v for k, v in resources_per_worker.items() if k not in ("CPU", "TPU")}
        opts: Dict[str, Any] = dict(num_cpus=num_cpus, num_tpus=num_tpus)
        if extra:
            opts["resources"] = extra
        if use_placement_group and num_workers > 1:
            bundle = dict(resources_per_worker)
            bundle.setdefault("CPU", num_cpus)
            self._pg = pg_api.placement_group(
                [dict(bundle) for _ in range(num_workers)], strategy=placement_strategy
            )
            ray_tpu.get(self._pg.ready())
        self.workers = []
        try:
            for i in range(num_workers):
                o = dict(opts)
                if self._pg is not None:
                    o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                        placement_group=self._pg, placement_group_bundle_index=i
                    )
                self.workers.append(actor_cls.options(**o).remote())
            metas = ray_tpu.get([w.get_metadata.remote() for w in self.workers])
        except BaseException:
            # a node dying mid-construction must not leak the PG/actors created
            # so far: the caller retries with a fresh group, and an orphaned PG
            # would pin resources forever (deadlocking the retry's placement)
            self.shutdown()
            raise
        self.metadata: List[WorkerMetadata] = [WorkerMetadata(**m) for m in metas]

    def __len__(self) -> int:
        return self.num_workers

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return results in rank order."""
        return ray_tpu.get([w.run_fn.remote(fn, *args, **kwargs) for w in self.workers])

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(self.workers[rank].run_fn.remote(fn, *args, **kwargs))

    def set_env(self, envs: List[Dict[str, str]]) -> None:
        ray_tpu.get([w.set_env.remote(e) for w, e in zip(self.workers, envs)])

    def node_ranks(self) -> List[int]:
        """Map each worker to a dense node index (for local_rank computation)."""
        node_order: List[str] = []
        ranks = []
        for m in self.metadata:
            if m.node_id not in node_order:
                node_order.append(m.node_id)
            ranks.append(node_order.index(m.node_id))
        return ranks

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            try:
                pg_api.remove_placement_group(self._pg)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
            self._pg = None
