"""Orbax-backed pytree (de)serialization into directory Checkpoints.

Reference capability: checkpoint payload handling that python/ray/train delegates to
torch.save / framework code; here orbax-checkpoint is the JAX-native format (sharded
arrays restore onto the current mesh layout). Falls back to a pickle of host numpy arrays
if orbax is unavailable.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Optional

from .checkpoint import Checkpoint

_STATE_DIR = "state"
_PICKLE_FILE = "state.pkl"


def save_pytree(tree: Any, directory: str) -> Checkpoint:
    """Write a jax pytree into `directory` and return a Checkpoint pointing at it."""
    os.makedirs(directory, exist_ok=True)
    try:
        import orbax.checkpoint as ocp

        path = os.path.join(os.path.abspath(directory), _STATE_DIR)
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(path, tree, force=True)
    except ImportError:
        import jax

        host_tree = jax.tree_util.tree_map(lambda x: _to_numpy(x), tree)
        with open(os.path.join(directory, _PICKLE_FILE), "wb") as f:
            pickle.dump(host_tree, f)
    return Checkpoint(directory)


def load_pytree(checkpoint: Checkpoint, target: Optional[Any] = None) -> Any:
    """Restore a pytree from a Checkpoint. `target` (a pytree of like-structured arrays,
    possibly sharded) guides structure and placement when given."""
    with checkpoint.as_directory() as d:
        orbax_path = os.path.join(d, _STATE_DIR)
        pickle_path = os.path.join(d, _PICKLE_FILE)
        if os.path.isdir(orbax_path):
            import orbax.checkpoint as ocp

            with ocp.PyTreeCheckpointer() as ckptr:
                if target is not None:
                    import jax

                    abstract = jax.tree_util.tree_map(_abstractify, target)
                    return ckptr.restore(orbax_path, item=abstract)
                return ckptr.restore(orbax_path)
        if os.path.exists(pickle_path):
            with open(pickle_path, "rb") as f:
                tree = pickle.load(f)
            if target is not None:
                import jax

                # Re-place host arrays to match the target's sharding.
                return jax.tree_util.tree_map(
                    lambda t, x: jax.device_put(x, t.sharding) if hasattr(t, "sharding") else x,
                    target,
                    tree,
                )
            return tree
    raise FileNotFoundError(f"no pytree state found in {checkpoint.path}")


def _to_numpy(x):
    import numpy as np

    try:
        return np.asarray(x)
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return x) by design
    except Exception:
        return x


def _abstractify(x):
    import jax

    if hasattr(x, "shape") and hasattr(x, "dtype"):
        try:
            import orbax.checkpoint as ocp

            return ocp.utils.to_shape_dtype_struct(x)
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return jax.ShapeDtypeStruct(x.shape, x.dtype)) by design
        except Exception:
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x
