"""Canonical jitted train step: loss -> grads -> optax update, GSPMD-sharded.

This is the compute core `JaxTrainer` drives; it is also what `__graft_entry__` and
`bench.py` exercise. One function builds the whole step so XLA fuses grad + update and
the optimizer state inherits the parameter shardings (ZeRO-for-free under fsdp).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import ModelConfig, llama
from ray_tpu.parallel import build_mesh, MeshSpec, use_mesh
from ray_tpu.parallel.sharding import AxisRules, TRAIN_RULES, named_sharding, shard_pytree

from . import grad_sync


class TrainState(NamedTuple):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10000,
    mu_dtype=None,
) -> optax.GradientTransformation:
    """mu_dtype: dtype of Adam's first moment (e.g. jnp.bfloat16 halves that
    third of optimizer HBM; the second moment stays f32 — its dynamic range is
    the one that cannot survive bf16). Used with the sharded optimizer update
    on HBM-tight pod budgets (__graft_entry__.hbm_budget_sharded_opt)."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def init_state(
    rng: jax.Array,
    cfg: ModelConfig,
    tx: optax.GradientTransformation,
    mesh=None,
    rules: AxisRules = TRAIN_RULES,
    checkpoint_dir: Optional[str] = None,
    param_dtype=None,
    sync: Optional["grad_sync.GradSyncConfig"] = None,
) -> TrainState:
    """Fresh (or checkpoint-warm-started) sharded TrainState.

    checkpoint_dir: HF-layout safetensors dir (models/checkpoint.py) — streams
    real weights into the sharded pytree instead of random init, so fine-tuning
    starts from a released model (reference: model loading is the engine/trainer
    contract, vllm_engine.py:180).

    sync: with `sharded_update=True` the optimizer state materializes sharded
    over the update axes from the start (train/grad_sync.py) instead of being
    re-laid-out on the first step."""
    if checkpoint_dir is not None:
        from ray_tpu.models import checkpoint as ckpt_io

        params = ckpt_io.load_llama_params(
            checkpoint_dir, cfg, mesh, rules=rules,
            param_dtype=param_dtype or jnp.float32)
    else:
        params = llama.init(rng, cfg)
        if mesh is not None:
            params = shard_pytree(params, llama.param_axes(cfg), mesh, rules)
    sync = sync or grad_sync.GradSyncConfig.from_env()
    if mesh is not None:
        with use_mesh(mesh):
            opt_state = jax.jit(tx.init)(params)
            if sync.sharded_update:
                opt_state = grad_sync.shard_opt_state(
                    tx, params, opt_state, sync, mesh)
    else:
        opt_state = tx.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def make_train_step(
    cfg: ModelConfig,
    tx: optax.GradientTransformation,
    loss_fn: Optional[Callable] = None,
    donate: bool = True,
    sync: Optional["grad_sync.GradSyncConfig"] = None,
) -> Callable[[TrainState, Dict[str, jax.Array]], Tuple[TrainState, Dict[str, jax.Array]]]:
    """sync=None reads GradSyncConfig.from_env() — how a JaxTrainer backend
    config (`JaxConfig(grad_sync=...)`) reaches user train loops that build
    their own step. The default (env unset) is the stock fused jit below,
    byte-identical to the historical behavior; non-default configs delegate to
    train/grad_sync.py (bucketed overlapped all-reduce, int8 reduction,
    cross-replica sharded optimizer update)."""
    loss_fn = loss_fn or llama.loss_fn
    sync = sync or grad_sync.GradSyncConfig.from_env()
    if not sync.is_default:
        return grad_sync.make_step(cfg, tx, loss_fn, sync, donate)

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, cfg
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(aux)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(state.step + 1, new_params, new_opt), metrics

    return jax.jit(step, donate_argnums=(0,) if donate else ())
