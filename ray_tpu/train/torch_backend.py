"""TorchConfig / TorchBackend: torch.distributed process-group bootstrap.

Capability parity: reference python/ray/train/torch/config.py — TorchConfig
(:36), _TorchBackend (:153), _setup_torch_process_group (:66, dist.init_process_
group :115 with a TCP store on the rank-0 worker). CPU-torch is the supported
device here (the TPU compute path is JaxTrainer); the gloo group gives reference-
faithful DDP semantics for torch user code.
"""
from __future__ import annotations

import datetime
import socket
from dataclasses import dataclass
from typing import Dict, Optional, Type

from .backend import Backend, BackendConfig
from .worker_group import WorkerGroup


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"  # NCCL has no place on TPU hosts (SURVEY.md §2.3)
    timeout_s: int = 1800
    env: Optional[Dict[str, str]] = None

    @property
    def backend_cls(self) -> Type["TorchBackend"]:
        return TorchBackend


from .jax_backend import _pick_port  # same rank-0 port-pick as the jax backend


def _setup_torch_process_group(backend: str, init_method: str, world_size: int,
                               rank: int, timeout_s: int) -> None:
    import torch.distributed as dist

    if dist.is_initialized():
        return
    dist.init_process_group(
        backend=backend,
        init_method=init_method,
        world_size=world_size,
        rank=rank,
        timeout=datetime.timedelta(seconds=timeout_s),
    )


def _teardown_torch_process_group() -> None:
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: TorchConfig) -> None:
        envs = []
        for rank in range(len(worker_group)):
            env = {
                "RAY_TPU_TRAIN_WORLD_SIZE": str(len(worker_group)),
                "RAY_TPU_TRAIN_RANK": str(rank),
                "GLOO_SOCKET_IFNAME": "lo",
            }
            if backend_config.env:
                env.update(backend_config.env)
            envs.append(env)
        worker_group.set_env(envs)

        # TCP rendezvous on the rank-0 worker's host (reference: TCP store there).
        # Single-host deployment: loopback avoids gloo interface-selection hangs in
        # sandboxed/multi-homed environments; GLOO_SOCKET_IFNAME pins the transport.
        port = worker_group.execute_single(0, _pick_port)
        url = f"tcp://127.0.0.1:{port}"
        import ray_tpu

        refs = [
            w.run_fn.remote(_setup_torch_process_group, backend_config.backend, url,
                            len(worker_group), rank, backend_config.timeout_s)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs)

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: TorchConfig) -> None:
        try:
            worker_group.execute(_teardown_torch_process_group)
        # graftlint: allow[swallowed-exception] best-effort worker-env teardown (torch process group)
        except Exception:
            pass


# ------------------------------------------------------------------ user-loop API

def get_device():
    """Reference ray.train.torch.get_device — CPU on TPU hosts."""
    import torch

    return torch.device("cpu")


def prepare_model(model, *, wrap_ddp: Optional[bool] = None):
    """Wrap the model in DDP when the process group spans >1 worker
    (reference ray.train.torch.prepare_model)."""
    import torch.distributed as dist

    if wrap_ddp is None:
        wrap_ddp = dist.is_initialized() and dist.get_world_size() > 1
    if not wrap_ddp:
        return model
    from torch.nn.parallel import DistributedDataParallel

    return DistributedDataParallel(model)


def prepare_data_loader(data_loader):
    """Re-build the DataLoader with a DistributedSampler so each worker sees its
    shard (reference ray.train.torch.prepare_data_loader)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, SequentialSampler, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return data_loader
    if data_loader.batch_size is None:
        # custom batch_sampler: we cannot infer how to re-shard batched sampling
        raise NotImplementedError(
            "prepare_data_loader does not support DataLoaders built with "
            "batch_sampler; construct the DistributedSampler yourself")
    if not isinstance(data_loader.sampler, (SequentialSampler, RandomSampler,
                                            DistributedSampler)):
        raise NotImplementedError(
            "prepare_data_loader would discard the DataLoader's custom sampler "
            f"({type(data_loader.sampler).__name__}); shard it explicitly instead")
    sampler = DistributedSampler(
        data_loader.dataset,
        shuffle=isinstance(data_loader.sampler, RandomSampler),
    )
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        timeout=data_loader.timeout,
        worker_init_fn=data_loader.worker_init_fn,
    )
