"""HuggingFace Transformers integration for TorchTrainer loops.

Capability parity: reference python/ray/train/huggingface/transformers/
_transformers_utils.py — RayTrainReportCallback (:30, on_save → aggregate
``state.log_history`` + wrap the HF checkpoint dir as a Train Checkpoint),
RayTorchIterableDataset (:92), prepare_trainer (:104, reroute the HF Trainer's
dataloaders through the worker's Data shard when one was passed).

Usage inside a TorchTrainer loop::

    def loop(config):
        trainer = transformers.Trainer(..., train_dataset=get_dataset_shard())
        trainer = ray_tpu.train.huggingface.prepare_trainer(trainer)
        trainer.add_callback(ray_tpu.train.huggingface.RayTrainReportCallback())
        trainer.train()
"""
from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, Optional

from ..data.iterator import DataIterator
from .checkpoint import Checkpoint


def _is_shard(ds) -> bool:
    """A Data shard in either spelling: a DataIterator or a whole Dataset
    (single-worker groups pass datasets through unsplit)."""
    return ds is not None and hasattr(ds, "iter_torch_batches")


def _transformers():
    try:
        import transformers
        return transformers
    except ImportError as e:
        raise ImportError(
            "ray_tpu.train.huggingface requires the 'transformers' package"
        ) from e


class RayTrainReportCallback:
    """transformers.TrainerCallback: after each HF checkpoint save, report the
    aggregated log_history metrics plus the checkpoint to the Train session."""

    CHECKPOINT_NAME = "checkpoint"

    def __new__(cls):
        transformers = _transformers()

        class _Impl(transformers.TrainerCallback):
            def on_save(self, args, state, control, **kwargs):
                from . import session

                metrics = {}
                for log in state.log_history:
                    metrics.update(log)
                ckpt = None
                tmpdir = None
                source = transformers.trainer_utils.get_last_checkpoint(args.output_dir)
                # rank 0 only: with DDP all ranks save identical weights
                if source is not None and session.get_context().get_world_rank() == 0:
                    tmpdir = tempfile.mkdtemp(prefix="hf_ckpt_")
                    shutil.copytree(source,
                                    os.path.join(tmpdir, cls.CHECKPOINT_NAME))
                    ckpt = Checkpoint.from_directory(tmpdir)
                session.report(metrics, checkpoint=ckpt)
                if tmpdir is not None:
                    # report() stages the checkpoint before returning
                    shutil.rmtree(tmpdir, ignore_errors=True)

        return _Impl()


class RayTorchIterableDataset:
    """torch IterableDataset over a Data shard's row iterator."""

    def __new__(cls, data_iterator: DataIterator, batch_size: Optional[int]):
        from torch.utils.data import IterableDataset

        class _Impl(IterableDataset):
            def __iter__(self) -> Iterator:
                if batch_size is None:
                    return data_iterator.iter_rows()
                return data_iterator.iter_torch_batches(batch_size=batch_size)

        return _Impl()


def prepare_trainer(trainer):
    """Reroute ``get_train_dataloader`` / ``get_eval_dataloader`` through the
    Data shard when ``train_dataset`` / ``eval_dataset`` is a DataIterator
    (reference prepare_trainer :104 — subclass-swap so user Trainer subclasses
    keep their own overrides)."""
    from torch.utils.data import DataLoader

    base = trainer.__class__

    def _loader(it: DataIterator, batch_size) -> "DataLoader":
        ds = RayTorchIterableDataset(it, batch_size)
        # the shard iterator already batches; DataLoader is a pass-through
        return DataLoader(ds, batch_size=1, collate_fn=lambda x: x[0])

    class RayTransformersTrainer(base):
        def get_train_dataloader(self):
            if _is_shard(self.train_dataset):
                return _loader(self.train_dataset, self.args.per_device_train_batch_size)
            return super().get_train_dataloader()

        def get_eval_dataloader(self, eval_dataset=None):
            ds = eval_dataset if eval_dataset is not None else self.eval_dataset
            if _is_shard(ds):
                return _loader(ds, self.args.per_device_eval_batch_size)
            return super().get_eval_dataloader(eval_dataset)

    trainer.__class__ = RayTransformersTrainer
    return trainer
