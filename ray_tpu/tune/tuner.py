"""Tuner: the public entrypoint (reference python/ray/tune/tuner.py:43, tune.py:267)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from .result_grid import ResultGrid
from .schedulers import TrialScheduler
from .search import Searcher
from .tune_controller import TuneController


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    seed: Optional[int] = None


class Tuner:
    def __init__(
        self,
        trainable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config=None,  # air.RunConfig
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        from ray_tpu.usage import record_library_usage

        record_library_usage("tune")
        stop = None
        max_failures = 0
        checkpoint_freq = 1
        if self.run_config is not None:
            stop = getattr(self.run_config, "stop", None)
            fc = getattr(self.run_config, "failure_config", None)
            if fc is not None:
                max_failures = max(0, getattr(fc, "max_failures", 0))
            cc = getattr(self.run_config, "checkpoint_config", None)
            if cc is not None:
                checkpoint_freq = getattr(cc, "checkpoint_frequency", 1)
        controller = TuneController(
            self.trainable,
            param_space=self.param_space,
            searcher=self.tune_config.search_alg,
            scheduler=self.tune_config.scheduler,
            num_samples=self.tune_config.num_samples,
            max_concurrent_trials=self.tune_config.max_concurrent_trials,
            max_failures=max_failures,
            stop=stop,
            checkpoint_frequency=checkpoint_freq,
            seed=self.tune_config.seed,
        )
        return ResultGrid(controller.run())


def run(
    trainable,
    *,
    config: Optional[Dict[str, Any]] = None,
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    stop: Optional[Dict[str, Any]] = None,
    max_concurrent_trials: int = 4,
    **_compat,
) -> ResultGrid:
    """tune.run (reference tune.py:267)."""
    controller = TuneController(
        trainable,
        param_space=config,
        scheduler=scheduler,
        num_samples=num_samples,
        max_concurrent_trials=max_concurrent_trials,
        stop=stop,
    )
    return ResultGrid(controller.run())
