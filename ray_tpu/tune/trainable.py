"""Trainable: the unit of execution Tune schedules.

Capability parity: reference python/ray/tune/trainable/trainable.py (class API:
setup/step/save_checkpoint/load_checkpoint) and function_trainable.py (user function +
session.report stream). The actor hosting a trainable exposes step()/save()/restore()
to the TuneController.
"""
from __future__ import annotations

import queue as _queue
import threading
from typing import Any, Callable, Dict, Optional

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class API: subclass and implement setup/step (+ optional save/load checkpoint)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self._iteration = 0
        self.setup(self.config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return None

    def load_checkpoint(self, checkpoint: Any) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """PBT exploit hook; return True if in-place reset is supported."""
        return False

    def cleanup(self) -> None:
        pass

    # -- controller-facing ----------------------------------------------------
    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Any:
        return {"state": self.save_checkpoint(), "iteration": self._iteration}

    def restore(self, payload: Any) -> None:
        self._iteration = payload.get("iteration", 0)
        self.load_checkpoint(payload.get("state"))

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = dict(new_config)
        return ok

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wrap `def train_fn(config)` calling tune.report(...) into the step() protocol.

    The function runs on a daemon thread; each report() becomes one step() result
    (reference function_trainable.py queue handoff).
    """

    _fn: Callable[[Dict[str, Any]], None] = None  # bound by make_function_trainable

    def setup(self, config: Dict[str, Any]) -> None:
        # maxsize=1 -> report() blocks until the controller consumes the result, pacing
        # the function with the scheduler (reference function_trainable.py semantics;
        # a free-running function would make early stopping save zero compute and
        # desynchronize checkpoints from iterations).
        self._results: _queue.Queue = _queue.Queue(maxsize=1)
        self._error: Optional[BaseException] = None
        self._restore_payload = None

        def runner():
            from . import session

            session._set_reporter(self._results.put, lambda: self._restore_payload)
            try:
                self._fn(self.config)
                self._results.put({DONE: True})
            except BaseException as e:  # noqa: BLE001
                self._error = e
                self._results.put({DONE: True, "_error": repr(e)})

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="tune-fn-runner")
        self._started = False

    def step(self) -> Dict[str, Any]:
        if not self._started:
            self._thread.start()
            self._started = True
        result = self._results.get()
        # only surface the failure on its terminal sentinel; queued valid results first
        if result.get("_error") and self._error is not None:
            raise self._error
        return result

    def save_checkpoint(self) -> Any:
        from . import session

        return session._last_checkpoint()

    def load_checkpoint(self, checkpoint: Any) -> None:
        self._restore_payload = checkpoint


def make_function_trainable(fn: Callable[[Dict[str, Any]], None]) -> type:
    return type(f"func_{getattr(fn, '__name__', 'trainable')}", (FunctionTrainable,), {"_fn": staticmethod(fn)})


def wrap_trainable(trainable) -> type:
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        return make_function_trainable(trainable)
    raise TypeError(f"not a trainable: {trainable!r}")
