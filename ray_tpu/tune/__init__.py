"""ray_tpu.tune: hyperparameter search over trial actors.

Capability parity: reference python/ray/tune/ — Tuner (tuner.py:43), tune.run
(tune.py:267), Trainable, schedulers (ASHA/PBT/median-stopping), search spaces
(basic variant generator), ResultGrid.
"""
from .result_grid import Result, ResultGrid  # noqa: F401
from .schedulers import (  # noqa: F401
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (  # noqa: F401
    BasicVariantGenerator,
    HyperOptSearch,
    OptunaSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from .session import get_checkpoint, report  # noqa: F401
from .trainable import Trainable  # noqa: F401
from .tune_controller import Trial, TuneController  # noqa: F401
from .tuner import TuneConfig, Tuner, run  # noqa: F401

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "Tuner",
    "TuneConfig",
    "run",
    "Trainable",
    "report",
    "get_checkpoint",
    "ResultGrid",
    "Result",
    "Trial",
    "TuneController",
    "TrialScheduler",
    "FIFOScheduler",
    "AsyncHyperBandScheduler",
    "ASHAScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "BasicVariantGenerator",
    "Searcher",
    "grid_search",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "sample_from",
]
