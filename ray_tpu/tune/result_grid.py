"""ResultGrid / Result (reference python/ray/tune/result_grid.py)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from .tune_controller import ERROR, Trial


@dataclasses.dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    config: Dict[str, Any]
    error: Optional[str] = None
    checkpoint: Any = None
    metrics_dataframe: Any = None
    trial_id: str = ""


class ResultGrid:
    def __init__(self, trials: List[Trial]):
        self._trials = trials
        self._results = []
        for t in trials:
            ckpt = None
            if t.checkpoint is not None:
                import ray_tpu
                from ray_tpu import ObjectRef

                if isinstance(t.checkpoint, ObjectRef):
                    try:
                        ckpt = ray_tpu.get(t.checkpoint)
                    # graftlint: allow[swallowed-exception] degrades to the coded fallback (ckpt = None) by design
                    except Exception:
                        ckpt = None
                else:
                    ckpt = t.checkpoint
            df = None
            try:
                df = t.metrics_dataframe
            # graftlint: allow[swallowed-exception] metrics dataframe is optional (pandas may be absent)
            except Exception:
                pass
            self._results.append(
                Result(metrics=t.last_result, config=t.config, error=t.error,
                       checkpoint=ckpt, metrics_dataframe=df, trial_id=t.trial_id)
            )

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str, mode: str = "min") -> Result:
        assert mode in ("min", "max")
        candidates = [r for r in self._results if r.metrics and metric in r.metrics]
        if not candidates:
            raise ValueError(f"no trial reported metric {metric!r}")
        keyfn = lambda r: r.metrics[metric]  # noqa: E731
        return min(candidates, key=keyfn) if mode == "min" else max(candidates, key=keyfn)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return pd.DataFrame(rows)
