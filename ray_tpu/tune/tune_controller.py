"""TuneController: drives trial actors to completion.

Capability parity: reference python/ray/tune/execution/tune_controller.py:68 — creates
trial actors, steps them, routes results through the scheduler, handles failures
(FailureConfig.max_failures restarts from last checkpoint), performs PBT exploits.
"""
from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

from .schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from .search import BasicVariantGenerator, Searcher
from .trainable import DONE, wrap_trainable

PENDING, RUNNING, TERMINATED, ERROR = "PENDING", "RUNNING", "TERMINATED", "ERROR"


def _graceful_stop(actor, timeout: float = 10.0) -> None:
    """Run Trainable.stop() (cleanup of nested actors, e.g. rllib groups) before kill."""
    try:
        ref = actor.stop.remote()
        ray_tpu.wait([ref], num_returns=1, timeout=timeout)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass
    try:
        ray_tpu.kill(actor)
    # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
    except Exception:
        pass


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Optional[Dict[str, Any]] = None
    results: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    num_failures: int = 0
    checkpoint: Any = None  # ObjectRef of last saved payload
    _actor: Any = None
    _pending: Any = None  # in-flight step() ref
    _pbt_exploit: Optional[Dict[str, Any]] = None

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.results)


class TuneController:
    def __init__(
        self,
        trainable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        searcher: Optional[Searcher] = None,
        scheduler: Optional[TrialScheduler] = None,
        num_samples: int = 1,
        max_concurrent_trials: int = 4,
        max_failures: int = 0,
        stop: Optional[Dict[str, Any]] = None,
        checkpoint_frequency: int = 1,
        resources_per_trial: Optional[Dict[str, float]] = None,
        seed: Optional[int] = None,
    ):
        self.trainable_cls = wrap_trainable(trainable)
        # model-based searchers (TPE, ...) suggest forever; num_samples is the cap.
        # Self-limiting searchers (BasicVariantGenerator's grid x num_samples
        # expansion) are exempt — they return None from suggest when exhausted.
        self._suggest_cap = (
            None if searcher is None or isinstance(searcher, BasicVariantGenerator)
            else max(1, num_samples)
        )
        self.searcher = searcher or BasicVariantGenerator(param_space or {}, num_samples, seed)
        self.scheduler = scheduler or FIFOScheduler()
        self.max_concurrent = max_concurrent_trials
        self.max_failures = max_failures
        self.stop_criteria = stop or {}
        self.checkpoint_frequency = checkpoint_frequency
        res = dict(resources_per_trial or {"CPU": 1})
        self._actor_cls = ray_tpu.remote(
            num_cpus=res.get("CPU", 1), num_tpus=res.get("TPU", 0)
        )(self.trainable_cls)
        self.trials: List[Trial] = []

    # -- lifecycle -------------------------------------------------------------
    def _next_trial(self) -> Optional[Trial]:
        if self._suggest_cap is not None and len(self.trials) >= self._suggest_cap:
            return None
        tid = uuid.uuid4().hex[:8]
        cfg = self.searcher.suggest(tid)
        if cfg is None:
            return None
        t = Trial(trial_id=tid, config=cfg)
        self.trials.append(t)
        return t

    def _start(self, trial: Trial, restore_from: Any = None) -> None:
        trial._actor = self._actor_cls.remote(trial.config)
        if restore_from is not None:
            ray_tpu.get(trial._actor.restore.remote(restore_from))
        trial.status = RUNNING
        trial._pending = trial._actor.train.remote()

    def _stop_trial(self, trial: Trial, status: str, error: Optional[str] = None) -> None:
        trial.status = status
        trial.error = error
        if status == TERMINATED and trial.checkpoint is not None:
            # resolve the in-flight save before killing the actor, else the kill races it
            try:
                trial.checkpoint = ray_tpu.get(trial.checkpoint)
            # graftlint: allow[swallowed-exception] degrades to the coded fallback (trial.checkpoint = None) by design
            except Exception:
                trial.checkpoint = None
        if trial._actor is not None:
            _graceful_stop(trial._actor)
            trial._actor = None
        trial._pending = None
        self.scheduler.on_trial_complete(trial, trial.last_result)
        self.searcher.on_trial_complete(trial.trial_id, trial.last_result)

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        if result.get(DONE):
            return True
        for k, v in self.stop_criteria.items():
            if k in result and result[k] >= v:
                return True
        return False

    def _maybe_checkpoint(self, trial: Trial, result: Dict[str, Any]) -> None:
        it = result.get("training_iteration", 0)
        if self.checkpoint_frequency and it % self.checkpoint_frequency == 0 and trial._actor is not None:
            trial.checkpoint = trial._actor.save.remote()

    def _handle_failure(self, trial: Trial, err: Exception) -> None:
        trial.num_failures += 1
        if trial.num_failures <= self.max_failures:
            restore = trial.checkpoint
            try:
                ray_tpu.kill(trial._actor)
            # graftlint: allow[swallowed-exception] best-effort cleanup of a target that may already be dead/gone
            except Exception:
                pass
            try:
                self._start(trial, restore_from=restore)
            # graftlint: allow[swallowed-exception] checkpoint-restore failure falls back to starting the trial fresh
            except Exception:
                # checkpoint ref itself failed (e.g. save raced the crash): fresh start
                trial.checkpoint = None
                try:
                    self._start(trial, restore_from=None)
                except Exception as e2:  # noqa: BLE001
                    self._stop_trial(trial, ERROR, error=repr(e2))
        else:
            self._stop_trial(trial, ERROR, error=repr(err))

    def _apply_pbt_exploit(self, trial: Trial) -> None:
        info = trial._pbt_exploit
        trial._pbt_exploit = None
        donor = next((t for t in self.trials if t.trial_id == info["donor"]), None)
        if donor is None or donor._actor is None:
            # donor already finished — keep training without exploiting
            trial._pending = trial._actor.train.remote()
            return
        donor_ckpt = ray_tpu.get(donor._actor.save.remote())
        new_config = info["perturb"](donor.config)
        # Try in-place reset; otherwise restart the actor with the new config.
        ok = ray_tpu.get(trial._actor.reset.remote(new_config))
        if not ok:
            _graceful_stop(trial._actor)
            trial._actor = self._actor_cls.remote(new_config)
        trial.config = new_config
        ray_tpu.get(trial._actor.restore.remote(donor_ckpt))
        trial._pending = trial._actor.train.remote()

    # -- main loop -------------------------------------------------------------
    def run(self) -> List[Trial]:
        active: List[Trial] = []
        while True:
            while len(active) < self.max_concurrent:
                t = self._next_trial()
                if t is None:
                    break
                self._start(t)
                active.append(t)
            if not active:
                break
            for t in active:  # safety: a RUNNING trial must always have a step in flight
                if t._pending is None and t._actor is not None:
                    t._pending = t._actor.train.remote()
            pending = {t._pending: t for t in active if t._pending is not None}
            if not pending:
                break
            done, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=30.0)
            for ref in done:
                trial = pending[ref]
                try:
                    result = ray_tpu.get(ref)
                except Exception as e:  # noqa: BLE001 - actor/task failure
                    self._handle_failure(trial, e)
                    if trial.status == ERROR:
                        active.remove(trial)
                    continue
                bare_completion = result.get(DONE) and not (
                    set(result) - {DONE, "_error", "training_iteration"}
                )
                if bare_completion and trial.last_result is not None:
                    # function finished: keep the last metrics, just mark terminal
                    trial.last_result = {**trial.last_result, DONE: True}
                else:
                    trial.last_result = result
                    trial.results.append(result)
                self._maybe_checkpoint(trial, result)
                decision = self.scheduler.on_trial_result(trial, result)
                if self._should_stop(result) or decision == STOP:
                    self._stop_trial(trial, TERMINATED)
                    active.remove(trial)
                elif trial._pbt_exploit is not None:
                    self._apply_pbt_exploit(trial)
                else:
                    trial._pending = trial._actor.train.remote()
        return self.trials
