"""Search spaces + searchers.

Capability parity: reference python/ray/tune/search/ — sample.py domains
(uniform/loguniform/randint/choice/grid_search), basic_variant.py
(BasicVariantGenerator grid expansion × num_samples), searcher ABC (searcher.py).
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[Dict], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories: List[Any]) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


class Searcher:
    """ABC (reference search/searcher.py). suggest() -> config or None when exhausted."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion crossed with num_samples random draws (reference basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1, seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items() if isinstance(v, GridSearch)]
        grids = [self.param_space[k].values for k in grid_keys]
        variants = []
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                variants.append(cfg)
        return variants

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg
