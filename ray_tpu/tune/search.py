"""Search spaces + searchers.

Capability parity: reference python/ray/tune/search/ — sample.py domains
(uniform/loguniform/randint/choice/grid_search), basic_variant.py
(BasicVariantGenerator grid expansion × num_samples), searcher ABC (searcher.py).
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.low, self.high = low, high
        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[Dict], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values: List[Any]):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories: List[Any]) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


class Searcher:
    """ABC (reference search/searcher.py). suggest() -> config or None when exhausted."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion crossed with num_samples random draws (reference basic_variant.py)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1, seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items() if isinstance(v, GridSearch)]
        grids = [self.param_space[k].values for k in grid_keys]
        variants = []
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                variants.append(cfg)
        return variants

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator searcher (the model behind the reference's
    TuneBOHB/HyperOptSearch integrations — python/ray/tune/search/bohb/, hyperopt/ —
    implemented natively on numpy so no ConfigSpace/hyperopt dependency is needed).

    Observations are split at the gamma-quantile; per-dimension KDEs l(x) (good) and
    g(x) (bad) are fit and candidates sampled from l are ranked by l(x)/g(x).
    """

    def __init__(self, param_space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        assert mode in ("min", "max")
        for k, dom in param_space.items():
            if isinstance(dom, GridSearch):
                raise ValueError(
                    f"TPESearcher does not support grid_search (key {k!r}); "
                    "use tune.choice(...) or BasicVariantGenerator for grids")
        self.space = dict(param_space)
        self.metric, self.mode = metric, mode
        self.n_startup, self.gamma, self.n_candidates = n_startup, gamma, n_candidates
        self.rng = random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._obs: List[tuple] = []  # (config, signed_score)

    def _random_config(self) -> Dict[str, Any]:
        out = {}
        for k, dom in self.space.items():
            out[k] = dom.sample(self.rng) if isinstance(dom, Domain) else dom
        return out

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._obs) < self.n_startup:
            cfg = self._random_config()
        else:
            cfg = self._tpe_suggest()
        self._configs[trial_id] = cfg
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None) -> None:
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or not result or result.get(self.metric) is None:
            return
        v = float(result[self.metric])
        self._obs.append((cfg, -v if self.mode == "min" else v))

    # -- TPE internals ---------------------------------------------------------
    def _tpe_suggest(self) -> Dict[str, Any]:
        import math as _m

        obs = sorted(self._obs, key=lambda o: o[1], reverse=True)
        n_good = max(2, int(self.gamma * len(obs)))
        good, bad = [o[0] for o in obs[:n_good]], [o[0] for o in obs[n_good:]] or [o[0] for o in obs]

        def kde_logp(x: float, pts: List[float], lo: float, hi: float) -> float:
            bw = max((hi - lo) / max(len(pts), 1) * 1.5, 1e-9)
            s = sum(_m.exp(-0.5 * ((x - p) / bw) ** 2) for p in pts)
            return _m.log(max(s, 1e-12))

        best_cfg, best_score = None, -_m.inf
        for _ in range(self.n_candidates):
            cand: Dict[str, Any] = {}
            score = 0.0
            for k, dom in self.space.items():
                if isinstance(dom, Choice):
                    # categorical TPE: sample by good-frequency, score by ratio
                    counts_g = {c: 1.0 for c in dom.categories}
                    for g in good:
                        counts_g[g[k]] = counts_g.get(g[k], 1.0) + 1.0
                    total = sum(counts_g.values())
                    r = self.rng.random() * total
                    acc = 0.0
                    pick = dom.categories[-1]
                    for c, w in counts_g.items():
                        acc += w
                        if r <= acc:
                            pick = c
                            break
                    counts_b = {c: 1.0 for c in dom.categories}
                    for b in bad:
                        counts_b[b[k]] = counts_b.get(b[k], 1.0) + 1.0
                    cand[k] = pick
                    score += _m.log(counts_g[pick] / sum(counts_g.values())) - _m.log(
                        counts_b.get(pick, 1.0) / sum(counts_b.values()))
                    continue
                if isinstance(dom, (Uniform, RandInt)):
                    lo, hi = float(dom.low), float(dom.high)
                    to_x = lambda v: float(v)  # noqa: E731
                    if isinstance(dom, RandInt):
                        # randrange upper bound is exclusive; never round onto it
                        from_x = lambda v, d=dom: min(int(round(v)), d.high - 1)  # noqa: E731
                    else:
                        from_x = lambda v: v  # noqa: E731
                elif isinstance(dom, LogUniform):
                    lo, hi = dom.lo, dom.hi
                    to_x = lambda v: _m.log(v)  # noqa: E731
                    # clamp the exp against float error (exp(log(b)) can undershoot b)
                    from_x = lambda v, d=dom: min(max(_m.exp(v), d.low), d.high)  # noqa: E731
                else:
                    cand[k] = dom.sample(self.rng) if isinstance(dom, Domain) else dom
                    continue
                pts_g = [to_x(g[k]) for g in good if k in g]
                pts_b = [to_x(b[k]) for b in bad if k in b]
                # sample from the good KDE: pick a center, jitter by bandwidth
                bw = max((hi - lo) / max(len(pts_g), 1) * 1.5, 1e-9)
                center = self.rng.choice(pts_g) if pts_g else self.rng.uniform(lo, hi)
                x = min(max(self.rng.gauss(center, bw), lo), hi)
                cand[k] = from_x(x)
                score += kde_logp(x, pts_g, lo, hi) - kde_logp(x, pts_b, lo, hi)
            if score > best_score:
                best_cfg, best_score = cand, score
        return best_cfg or self._random_config()


# BOHB = a TPE-style model paired with HyperBand brackets (reference
# search/bohb/bohb_search.py): compose TPESearcher with
# schedulers.HyperBandScheduler for that behavior. There is deliberately no
# TuneBOHB name here — an alias would promise an algorithm that isn't one.


class OptunaSearch(Searcher):
    """Adapter onto an Optuna study — the external-searcher seam the reference
    exposes (python/ray/tune/search/optuna/optuna_search.py: OptunaSearch maps
    Tune spaces onto optuna distributions via study.ask()/tell()). The native
    search-space Domains translate directly; `optuna` is an OPTIONAL dependency
    and importing this class without it raises with an install hint.

    Usage: Tuner(trainable, param_space=space,
                 tune_config=TuneConfig(search_alg=OptunaSearch(space))).fit()
    """

    def __init__(self, param_space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", seed: Optional[int] = None,
                 sampler: Any = None, study: Any = None):
        try:
            import optuna
        except ImportError as e:  # pragma: no cover - exercised when installed
            raise ImportError(
                "OptunaSearch requires the optional 'optuna' package "
                "(pip install optuna); the native TPESearcher needs no extra "
                "dependency and covers the same algorithm family") from e
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        for k, dom in param_space.items():
            if isinstance(dom, GridSearch):
                raise ValueError(
                    f"OptunaSearch does not support grid_search (key {k!r}); "
                    "use BasicVariantGenerator for grids")
        self.space = dict(param_space)
        self.metric, self.mode = metric, mode
        optuna.logging.set_verbosity(optuna.logging.WARNING)
        self.study = study or optuna.create_study(
            direction="minimize" if mode == "min" else "maximize",
            sampler=sampler or optuna.samplers.TPESampler(seed=seed))
        self._rng = random.Random(seed)
        self._live: Dict[str, Any] = {}  # trial_id -> optuna trial

    def _suggest_param(self, trial, key: str, dom: Any):
        if isinstance(dom, LogUniform):
            return trial.suggest_float(key, dom.low, dom.high, log=True)
        if isinstance(dom, Uniform):
            return trial.suggest_float(key, dom.low, dom.high)
        if isinstance(dom, RandInt):
            return trial.suggest_int(key, dom.low, dom.high - 1)  # high exclusive
        if isinstance(dom, Choice):
            return trial.suggest_categorical(key, dom.categories)
        if isinstance(dom, Function):
            return dom.sample(self._rng)  # opaque to the optuna model
        return dom  # constant

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        trial = self.study.ask()
        self._live[trial_id] = trial
        return {k: self._suggest_param(trial, k, dom)
                for k, dom in self.space.items()}

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None) -> None:
        trial = self._live.pop(trial_id, None)
        if trial is None:
            return
        import optuna

        value = (result or {}).get(self.metric)
        if value is None:  # errored/early-stopped with no metric: tell FAIL
            self.study.tell(trial, state=optuna.trial.TrialState.FAIL)
        else:
            self.study.tell(trial, float(value))


class HyperOptSearch(Searcher):
    """Adapter onto hyperopt's TPE — the second external-searcher seam the
    reference exposes (python/ray/tune/search/hyperopt/hyperopt_search.py:
    HyperOptSearch drives hyperopt.tpe.suggest over a Trials object). Native
    Domains map onto hp.* distributions; `hyperopt` is an OPTIONAL dependency
    (>= 0.2.4 for 3-arg hp.randint; declared in the tune-searchers extra) and
    importing this class without it raises with an install hint. The e2e test
    (test_tune_extras.py) importorskips, so environments without hyperopt
    never execute this adapter — install the extra before relying on it.

    Usage: Tuner(trainable, param_space=space,
                 tune_config=TuneConfig(search_alg=HyperOptSearch(space))).fit()
    """

    def __init__(self, param_space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", seed: Optional[int] = None,
                 n_initial_points: int = 20, gamma: float = 0.25):
        try:
            import hyperopt as hpo
        except ImportError as e:  # pragma: no cover - exercised when installed
            raise ImportError(
                "HyperOptSearch requires the optional 'hyperopt' package "
                "(pip install hyperopt); the native TPESearcher needs no extra "
                "dependency and covers the same algorithm family") from e
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self._hpo = hpo
        self.metric, self.mode = metric, mode
        self.space = dict(param_space)
        self._choices: Dict[str, List[Any]] = {}  # hp.choice returns indices
        self._functions: Dict[str, Function] = {}  # opaque to the model
        hp_space: Dict[str, Any] = {}
        for k, dom in param_space.items():
            if isinstance(dom, GridSearch):
                raise ValueError(
                    f"HyperOptSearch does not support grid_search (key {k!r}); "
                    "use BasicVariantGenerator for grids")
            hp_dom = self._to_hp(k, dom)
            if hp_dom is not None:
                hp_space[k] = hp_dom
        # Domain wants the objective; suggestions never call it (ask/tell use)
        self.domain = hpo.Domain(lambda spc: 0.0, hp_space)
        self.trials = hpo.Trials()
        import functools

        self._suggest_fn = functools.partial(
            hpo.tpe.suggest, n_startup_jobs=n_initial_points, gamma=gamma)
        self._rng = random.Random(seed)
        self._live: Dict[str, int] = {}  # trial_id -> hyperopt tid

    def _to_hp(self, key: str, dom: Any):
        hp = self._hpo.hp
        import math as _m

        if isinstance(dom, LogUniform):
            return hp.loguniform(key, _m.log(dom.low), _m.log(dom.high))
        if isinstance(dom, Uniform):
            return hp.uniform(key, dom.low, dom.high)
        if isinstance(dom, RandInt):
            return hp.randint(key, dom.low, dom.high)  # high exclusive, as ours
        if isinstance(dom, Choice):
            self._choices[key] = dom.categories
            return hp.choice(key, list(range(len(dom.categories))))
        if isinstance(dom, Function):
            self._functions[key] = dom
            return None
        return None  # constant: carried through verbatim in suggest()

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        hpo = self._hpo
        new_ids = self.trials.new_trial_ids(1)
        self.trials.refresh()
        docs = self._suggest_fn(new_ids, self.domain, self.trials,
                                self._rng.randrange(2 ** 31 - 1))
        self.trials.insert_trial_docs(docs)
        self.trials.refresh()
        tid = docs[0]["tid"]
        self._live[trial_id] = tid
        vals = hpo.base.spec_from_misc(docs[0]["misc"])
        cfg: Dict[str, Any] = {}
        for k, dom in self.space.items():
            if k in self._choices:
                cfg[k] = self._choices[k][int(vals[k])]
            elif k in self._functions:
                cfg[k] = self._functions[k].sample(self._rng)
            elif k in vals:
                v = vals[k]
                cfg[k] = int(v) if isinstance(dom, RandInt) else float(v)
            else:
                cfg[k] = dom  # constant
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None) -> None:
        hpo = self._hpo
        tid = self._live.pop(trial_id, None)
        if tid is None:
            return
        value = (result or {}).get(self.metric)
        for trial in self.trials._dynamic_trials:
            if trial["tid"] != tid:
                continue
            if value is None:  # errored/early-stopped with no metric
                trial["state"] = hpo.JOB_STATE_ERROR
                trial["result"] = {"status": hpo.STATUS_FAIL}
            else:
                loss = float(value) if self.mode == "min" else -float(value)
                trial["state"] = hpo.JOB_STATE_DONE
                trial["result"] = {"loss": loss, "status": hpo.STATUS_OK}
            break
        self.trials.refresh()
