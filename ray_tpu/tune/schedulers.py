"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Capability parity: reference python/ray/tune/schedulers/ — trial_scheduler.py decisions,
async_hyperband.py (ASHA brackets with halving rungs), median_stopping_rule.py, pbt.py
(exploit bottom quantile from top quantile + perturb).
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference async_hyperband.py): asynchronous successive halving.

    At each rung (time_attr = grace_period * reduction_factor^k), a trial stops unless
    its metric is in the top 1/reduction_factor of completed rung entries.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        max_t: int = 100,
        reduction_factor: float = 3.0,
    ):
        assert mode in ("min", "max")
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.grace_period, self.max_t, self.rf = grace_period, max_t, reduction_factor
        self._rungs: Dict[int, List[float]] = {}
        self._recorded: Dict[int, set] = {}
        rung, t = 0, grace_period
        self._milestones = []
        while t < max_t:
            self._milestones.append(t)
            rung += 1
            t = int(grace_period * reduction_factor**rung)

    def _sign(self, v: float) -> float:
        return -v if self.mode == "min" else v

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        for milestone in self._milestones:
            seen = self._recorded.setdefault(milestone, set())
            if t >= milestone and trial.trial_id not in seen:
                seen.add(trial.trial_id)
                rung = self._rungs.setdefault(milestone, [])
                rung.append(self._sign(metric))
                k = max(1, int(len(rung) / self.rf))
                cutoff = sorted(rung, reverse=True)[k - 1]
                if self._sign(metric) < cutoff:
                    return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running mean is worse than the median of completed means."""

    def __init__(self, metric: str = "loss", mode: str = "min", grace_period: int = 3):
        self.metric, self.mode, self.grace = metric, mode, grace_period
        self._histories: Dict[str, List[float]] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        h = self._histories.setdefault(trial.trial_id, [])
        h.append(float(v))
        if result.get("training_iteration", 0) < self.grace or len(self._histories) < 3:
            return CONTINUE
        means = {tid: sum(hh) / len(hh) for tid, hh in self._histories.items() if hh}
        med = sorted(means.values())[len(means) // 2]
        mine = means[trial.trial_id]
        worse = mine > med if self.mode == "min" else mine < med
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): periodically clone top-quantile state into bottom quantile
    and perturb hyperparameters. The controller performs the actual exploit via the
    decisions this scheduler returns in `trial._pbt_exploit`.
    """

    def __init__(
        self,
        metric: str = "reward",
        mode: str = "max",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    def _sign(self, v):
        return v if self.mode == "max" else -v

    def _perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_p or key not in out:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    out[key] = self.rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self.rng.choice([0.8, 1.2])
                if isinstance(out[key], (int, float)) and not isinstance(out[key], bool):
                    out[key] = type(out[key])(out[key] * factor)
        return out

    def on_trial_complete(self, trial, result) -> None:
        # finished trials can't donate state; drop them from the exploit pool
        self._scores.pop(trial.trial_id, None)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is not None:
            self._scores[trial.trial_id] = self._sign(float(v))
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        if len(self._scores) < 2:
            return CONTINUE
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        k = max(1, int(len(ranked) * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom:
            donor = self.rng.choice(top)
            trial._pbt_exploit = {"donor": donor, "perturb": self._perturb}
        return CONTINUE
