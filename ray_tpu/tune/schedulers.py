"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Capability parity: reference python/ray/tune/schedulers/ — trial_scheduler.py decisions,
async_hyperband.py (ASHA brackets with halving rungs), median_stopping_rule.py, pbt.py
(exploit bottom quantile from top quantile + perturb).
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference async_hyperband.py): asynchronous successive halving.

    At each rung (time_attr = grace_period * reduction_factor^k), a trial stops unless
    its metric is in the top 1/reduction_factor of completed rung entries.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        max_t: int = 100,
        reduction_factor: float = 3.0,
    ):
        assert mode in ("min", "max")
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.grace_period, self.max_t, self.rf = grace_period, max_t, reduction_factor
        self._rungs: Dict[int, List[float]] = {}
        self._recorded: Dict[int, set] = {}
        rung, t = 0, grace_period
        self._milestones = []
        while t < max_t:
            self._milestones.append(t)
            rung += 1
            t = int(grace_period * reduction_factor**rung)

    def _sign(self, v: float) -> float:
        return -v if self.mode == "min" else v

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        for milestone in self._milestones:
            seen = self._recorded.setdefault(milestone, set())
            if t >= milestone and trial.trial_id not in seen:
                seen.add(trial.trial_id)
                rung = self._rungs.setdefault(milestone, [])
                rung.append(self._sign(metric))
                k = max(1, int(len(rung) / self.rf))
                cutoff = sorted(rung, reverse=True)[k - 1]
                if self._sign(metric) < cutoff:
                    return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running mean is worse than the median of completed means."""

    def __init__(self, metric: str = "loss", mode: str = "min", grace_period: int = 3):
        self.metric, self.mode, self.grace = metric, mode, grace_period
        self._histories: Dict[str, List[float]] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        h = self._histories.setdefault(trial.trial_id, [])
        h.append(float(v))
        if result.get("training_iteration", 0) < self.grace or len(self._histories) < 3:
            return CONTINUE
        means = {tid: sum(hh) / len(hh) for tid, hh in self._histories.items() if hh}
        med = sorted(means.values())[len(means) // 2]
        mine = means[trial.trial_id]
        worse = mine > med if self.mode == "min" else mine < med
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): periodically clone top-quantile state into bottom quantile
    and perturb hyperparameters. The controller performs the actual exploit via the
    decisions this scheduler returns in `trial._pbt_exploit`.
    """

    def __init__(
        self,
        metric: str = "reward",
        mode: str = "max",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}

    def _sign(self, v):
        return v if self.mode == "max" else -v

    def _perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_p or key not in out:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    out[key] = self.rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self.rng.choice([0.8, 1.2])
                if isinstance(out[key], (int, float)) and not isinstance(out[key], bool):
                    out[key] = type(out[key])(out[key] * factor)
        return out

    def on_trial_complete(self, trial, result) -> None:
        # finished trials can't donate state; drop them from the exploit pool
        self._scores.pop(trial.trial_id, None)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is not None:
            self._scores[trial.trial_id] = self._sign(float(v))
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        if len(self._scores) < 2:
            return CONTINUE
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        k = max(1, int(len(ranked) * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom:
            donor = self.rng.choice(top)
            trial._pbt_exploit = {"donor": donor, "perturb": self._perturb}
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference hyperband.py): bracketed successive halving.

    Trials are assigned round-robin to brackets; each bracket halves at
    milestones r, r*eta, r*eta^2, ... keeping the top 1/eta of its members.
    Unlike ASHA the cutoff waits for the whole rung (bracket cohort) to report.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration", max_t: int = 81,
                 reduction_factor: float = 3.0):
        assert mode in ("min", "max")
        self.metric, self.mode, self.time_attr = metric, mode, time_attr
        self.max_t, self.eta = max_t, reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        # bracket s starts halving at r = max_t * eta^-s
        self._brackets: List[Dict[str, Any]] = [
            {"r0": max(1, int(max_t * reduction_factor ** -s)), "members": {}, "rungs": {}}
            for s in range(s_max, -1, -1)
        ]
        self._next_bracket = 0
        self._assignment: Dict[str, int] = {}
        self._to_stop: set = set()  # below-cutoff trials from completed rungs

    def _sign(self, v: float) -> float:
        return -v if self.mode == "min" else v

    def _milestones(self, bracket) -> List[int]:
        out, t = [], bracket["r0"]
        while t < self.max_t:
            out.append(int(t))
            t *= self.eta
        return out

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        tid = trial.trial_id
        if tid in self._to_stop:
            self._to_stop.discard(tid)
            return STOP
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        bi = self._assignment.get(tid)
        if bi is None:
            bi = self._next_bracket
            self._assignment[tid] = bi
            self._next_bracket = (self._next_bracket + 1) % len(self._brackets)
        bracket = self._brackets[bi]
        bracket["members"][tid] = self._sign(float(v))
        for milestone in self._milestones(bracket):
            rung = bracket["rungs"].setdefault(milestone, {})
            if t >= milestone and tid not in rung:
                rung[tid] = self._sign(float(v))
                # synchronous halving: once every live bracket member reached the
                # rung, stop the whole bottom (1 - 1/eta) fraction
                live = set(bracket["members"])
                if set(rung) >= live and len(rung) > 1:
                    k = max(1, int(len(rung) / self.eta))
                    cutoff = sorted(rung.values(), reverse=True)[k - 1]
                    losers = {r for r, s in rung.items() if s < cutoff and r in live}
                    for loser in losers:
                        bracket["members"].pop(loser, None)
                    self._to_stop |= losers
                    if tid in self._to_stop:
                        self._to_stop.discard(tid)
                        return STOP
        return CONTINUE

    def on_trial_complete(self, trial, result) -> None:
        bi = self._assignment.get(trial.trial_id)
        if bi is not None:
            self._brackets[bi]["members"].pop(trial.trial_id, None)
        self._to_stop.discard(trial.trial_id)


class PB2(PopulationBasedTraining):
    """PB2 (reference pb2.py): PBT where the perturbation is replaced by a
    GP-bandit suggestion (Parker-Holder et al. 2020). A small numpy GP with an
    RBF kernel is fit on (hyperparam vector -> reward improvement) pairs and the
    exploit picks the UCB argmax inside `hyperparam_bounds` — no sklearn/GPy
    dependency (the reference requires GPy here).
    """

    def __init__(self, metric: str = "reward", mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, List[float]]] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None,
                 ucb_kappa: float = 2.0):
        super().__init__(metric=metric, mode=mode, time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations=None,
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = {k: (float(lo), float(hi)) for k, (lo, hi) in (hyperparam_bounds or {}).items()}
        self.kappa = ucb_kappa
        self._last_metric: Dict[str, float] = {}
        self._X: List[List[float]] = []  # normalized hyperparam vectors
        self._y: List[float] = []  # reward deltas over the interval

    def _vec(self, config: Dict[str, Any]) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        v = result.get(self.metric)
        if v is not None:
            signed = self._sign(float(v))
            prev = self._last_metric.get(trial.trial_id)
            if prev is not None:
                self._X.append(self._vec(trial.config))
                self._y.append(signed - prev)
            self._last_metric[trial.trial_id] = signed
        return super().on_trial_result(trial, result)

    def _gp_ucb(self) -> Optional[Dict[str, float]]:
        import numpy as np

        if len(self._y) < 2 or not self.bounds:
            return None
        X = np.asarray(self._X[-64:], dtype=np.float64)
        y = np.asarray(self._y[-64:], dtype=np.float64)
        y = (y - y.mean()) / (y.std() + 1e-9)
        ls, noise = 0.3, 1e-2
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-d2 / (2 * ls * ls)) + noise * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return None
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
        # UCB argmax over random candidates in the unit box
        cand = np.asarray([[self.rng.random() for _ in self.bounds] for _ in range(256)])
        d2c = ((cand[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        Kc = np.exp(-d2c / (2 * ls * ls))
        mu = Kc @ alpha
        v = np.linalg.solve(L, Kc.T)
        var = np.clip(1.0 - (v * v).sum(0), 1e-9, None)
        best = cand[int(np.argmax(mu + self.kappa * np.sqrt(var)))]
        out = {}
        for (k, (lo, hi)), u in zip(self.bounds.items(), best):
            out[k] = lo + float(u) * (hi - lo)
        return out

    def _perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        suggestion = self._gp_ucb()
        if suggestion is None:
            # cold start: uniform resample inside bounds
            suggestion = {k: lo + self.rng.random() * (hi - lo)
                          for k, (lo, hi) in self.bounds.items()}
        out.update(suggestion)
        return out
