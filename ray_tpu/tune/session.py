"""tune.report / tune.get_checkpoint from inside a function trainable.

Capability parity: reference ray.tune session API (ray/tune/trainable/session shims).
Per-worker (actor process) globals; a trainable actor hosts exactly one trial.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_reporter: Optional[Callable[[Dict[str, Any]], None]] = None
_restore_getter: Optional[Callable[[], Any]] = None
_checkpoint: Any = None


def _set_reporter(reporter, restore_getter) -> None:
    global _reporter, _restore_getter, _checkpoint
    with _lock:
        _reporter = reporter
        _restore_getter = restore_getter
        _checkpoint = None


def _last_checkpoint() -> Any:
    with _lock:
        return _checkpoint


def report(metrics: Dict[str, Any], *, checkpoint: Any = None) -> None:
    global _checkpoint
    with _lock:
        rep = _reporter
        if checkpoint is not None:
            _checkpoint = checkpoint
    if rep is None:
        raise RuntimeError("tune.report() called outside a Tune function trainable")
    rep(dict(metrics))


def get_checkpoint() -> Any:
    with _lock:
        return _restore_getter() if _restore_getter else None
