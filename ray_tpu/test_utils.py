"""Chaos / fault-injection utilities for tests and resilience drills.

Capability parity: reference ray._private.test_utils kill primitives —
`RayletKiller`, `WorkerKillerActor`, `EC2InstanceTerminator(WithGracePeriod)`
(imported by release/nightly_tests/setup_chaos.py:6-13) and the chaos suites in
python/ray/tests/chaos/. These are product-adjacent tools: resilience tests and
game-day drills script them directly.
"""
from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

from ray_tpu.core import global_state


def _cluster():
    c = global_state.try_cluster()
    if c is None:
        raise RuntimeError("ray_tpu is not initialized")
    return c


class WorkerKiller:
    """Kill worker processes (SIGKILL) — the reference WorkerKillerActor.

    Targets busy workers first (that's where interesting recovery paths live).
    """

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 5):
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.kills_done = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _pick(self):
        c = _cluster()
        with c._lock:
            workers = [w for n in c._nodes.values() for w in n.workers.values()
                       if w.state in ("busy", "blocked", "idle")]
        busy = [w for w in workers if w.state in ("busy", "blocked")]
        pool = busy or workers
        return random.choice(pool) if pool else None

    def kill_one(self) -> bool:
        w = self._pick()
        if w is None:
            return False
        try:
            w.process.kill()
            self.kills_done += 1
            return True
        # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
        except Exception:
            return False

    def run_policy(self) -> None:
        """Background kill loop until max_kills (reference chaos setup)."""
        def loop():
            while not self._stop.wait(self.kill_interval_s):
                if self.kills_done >= self.max_kills:
                    return
                self.kill_one()

        self._thread = threading.Thread(target=loop, daemon=True, name="worker-killer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class NodeKiller:
    """Remove whole nodes (the reference RayletKiller / instance terminator).

    Never touches the head node, matching the reference's choice to keep the GCS
    alive during chaos runs.
    """

    def __init__(self):
        self.killed: List[str] = []

    def kill_node(self, node_id=None) -> Optional[str]:
        c = _cluster()
        candidates = [n for n in c.nodes() if n is not c.head_node]
        if node_id is not None:
            candidates = [n for n in candidates if n.node_id == node_id]
        if not candidates:
            return None
        node = random.choice(candidates)
        c.remove_node(node.node_id)
        self.killed.append(node.node_id.hex())
        return node.node_id.hex()


class CollectiveRankKiller:
    """Kill the worker process holding a specific rank of a collective group
    (SIGKILL, mid-op by design) — the chaos injection for the collective
    abort path, alongside WorkerKiller (any busy worker) and NodeKiller
    (whole nodes).

    Compatibility shim: the logic moved to
    ray_tpu.util.fault_injection.ChaosController (the unified chaos API,
    which also kills serve replicas and arms fail points); this wrapper
    preserves the original call shape for existing drills.
    """

    def __init__(self, group_name: str = "default", rank: int = 0):
        from ray_tpu.util.fault_injection import ChaosController

        self.group_name = group_name
        self.rank = rank
        self._chaos = ChaosController()

    def registered(self) -> bool:
        """True once the target rank has joined (the kill can land)."""
        return self._chaos.collective_rank_registered(self.group_name, self.rank)

    def kill(self) -> bool:
        return self._chaos.kill_collective_rank(self.group_name, self.rank)

    def kill_when_registered(self, timeout: float = 10.0) -> bool:
        """Block until the rank joins its group, then kill it."""
        return self._chaos.kill_collective_rank_when_registered(
            self.group_name, self.rank, timeout)


def kill_worker_running(task_name: str) -> bool:
    """Kill the worker currently executing a dispatched task with this name
    (deterministic chaos: reference WorkerKillerActor targets by task)."""
    c = _cluster()
    with c._lock:
        for ts in c.tasks.values():
            if ts.spec.name == task_name and ts.worker is not None:
                try:
                    ts.worker.process.kill()
                    return True
                # graftlint: allow[swallowed-exception] degrades to the coded fallback (return False) by design
                except Exception:
                    return False
    return False


def wait_for_condition(predicate, timeout: float = 10.0, interval: float = 0.05,
                       message: str = "condition not met") -> None:
    """Reference ray._private.test_utils.wait_for_condition."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise TimeoutError(message)


def get_actor_state(actor_handle) -> Optional[str]:
    c = _cluster()
    st = c.actors.get(actor_handle._actor_id)
    return st.state if st is not None else None
