"""HF-format checkpoint IO: safetensors ⇄ the ray_tpu llama parameter pytree.

Loading real weights is table stakes of the serving-engine contract (reference:
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:180 — the
engine constructor is handed a model id and must materialize it). The reference
delegates to vLLM/HF loaders; here the loader is native:

- reads HF transformers Llama layout (config.json + *.safetensors, sharded
  index supported), torch ``Linear`` weight convention (out_features, in_features);
- streams ONE target leaf at a time: gather the per-layer tensors, transform
  (transpose/reshape/stack for the scanned layout), cast, and ``jax.device_put``
  with the leaf's NamedSharding before touching the next leaf — peak host memory
  is one stacked leaf, not the whole model;
- the writer emits the same layout so checkpoints round-trip (and tests can
  fabricate tiny "HF" checkpoints without the hub).
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.parallel.sharding import INFER_RULES, AxisRules, named_sharding

from .config import ModelConfig

Params = Dict[str, Any]


# ----------------------------------------------------------------- config.json

def config_from_hf(source_dir: str, **overrides) -> ModelConfig:
    """Map an HF transformers LlamaConfig (config.json) onto ModelConfig."""
    with open(os.path.join(source_dir, "config.json")) as f:
        hf = json.load(f)
    fields = dict(
        name=hf.get("_name_or_path") or os.path.basename(os.path.normpath(source_dir)),
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        d_ff=hf["intermediate_size"],
        # missing keys take the HF transformers LlamaConfig defaults, NOT ours —
        # a Llama-2-era config.json omits rope_theta and means 10000.0
        max_seq_len=hf.get("max_position_embeddings", 2048),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        norm_eps=float(hf.get("rms_norm_eps", 1e-6)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    # Mixtral-style sparse MoE (num_local_experts in MixtralConfig). Our top-k
    # gating (softmax over all E, keep top-k, renormalize to sum 1) is
    # mathematically identical to Mixtral's softmax-over-the-top-k-logits: the
    # full-softmax normalizer cancels in the renormalization; top-1 needs the
    # explicit renorm flag (Switch convention differs there). Mixtral has no
    # capacity concept (dropless), so the faithful default is capacity_factor
    # = E/k, which makes expert capacity cover the worst-case routing (every
    # token to one expert) — moe.py then drops nothing. Our own round-tripped
    # checkpoints carry the trained factor in config.json instead.
    if hf.get("num_local_experts", 0):
        # Only Mixtral's layout/gating is wired: other HF MoE families that
        # also carry num_local_experts (e.g. Phi-MoE) have different tensor
        # layouts and routing conventions — accepting them here would fail
        # much later at weight load with an opaque missing-tensor error.
        model_type = hf.get("model_type", "")
        if model_type != "mixtral":
            raise ValueError(
                f"unsupported MoE checkpoint: model_type {model_type!r} with "
                f"num_local_experts={hf['num_local_experts']}; only "
                "Mixtral-style sparse MoE (model_type 'mixtral') is supported"
            )
        e = int(hf["num_local_experts"])
        k = int(hf.get("num_experts_per_tok", 2))
        fields["n_experts"] = e
        fields["moe_top_k"] = k
        fields["moe_top1_renorm"] = bool(hf.get("moe_top1_renorm", True))
        fields["moe_capacity_factor"] = float(
            hf.get("moe_capacity_factor", e / k))
    fields.update(overrides)
    return ModelConfig(**fields)


def config_to_hf(cfg: ModelConfig) -> Dict[str, Any]:
    moe = cfg.n_experts > 0
    extra = (
        # moe_capacity_factor/moe_top1_renorm are our extension keys (ignored by
        # HF): they persist the trained dispatch semantics through a round-trip
        # instead of resetting to the dropless Mixtral defaults on reload.
        {"num_local_experts": cfg.n_experts, "num_experts_per_tok": cfg.moe_top_k,
         "moe_capacity_factor": cfg.moe_capacity_factor,
         "moe_top1_renorm": cfg.moe_top1_renorm}
        if moe else {}
    )
    return {
        "architectures": ["MixtralForCausalLM" if moe else "LlamaForCausalLM"],
        "model_type": "mixtral" if moe else "llama",
        **extra,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.d_ff,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
    }


# ---------------------------------------------------------------- tensor index

class _ShardedReader:
    """name -> tensor across one or many .safetensors files (lazy handles)."""

    def __init__(self, source_dir: str):
        from safetensors import safe_open

        self._safe_open = safe_open
        index_path = os.path.join(source_dir, "model.safetensors.index.json")
        self._key_to_file: Dict[str, str] = {}
        if os.path.exists(index_path):
            with open(index_path) as f:
                weight_map = json.load(f)["weight_map"]
            for key, fname in weight_map.items():
                self._key_to_file[key] = os.path.join(source_dir, fname)
        else:
            files = sorted(
                os.path.join(source_dir, f) for f in os.listdir(source_dir)
                if f.endswith(".safetensors"))
            if not files:
                raise FileNotFoundError(f"no .safetensors files in {source_dir}")
            for path in files:
                with safe_open(path, framework="numpy") as h:
                    for key in h.keys():
                        self._key_to_file[key] = path
        self._handles: Dict[str, Any] = {}

    def keys(self):
        return self._key_to_file.keys()

    def get(self, name: str) -> np.ndarray:
        path = self._key_to_file[name]
        h = self._handles.get(path)
        if h is None:
            h = self._handles[path] = self._safe_open(path, framework="numpy")
        return h.get_tensor(name)


# -------------------------------------------------------------------- mapping
# HF torch Linear stores weight as (out_features, in_features); ours contract
# inputs on the leading axis, so every projection transposes.

def _leaf_readers(cfg: ModelConfig, rd: _ShardedReader) -> Dict[str, Any]:
    d, hd = cfg.d_model, cfg.head_dim
    nh, nkv, ff = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff

    def layer_leaf(field: str) -> Callable[[int], np.ndarray]:
        pre = "model.layers.{}."

        def q(i):
            return rd.get(f"model.layers.{i}.self_attn.q_proj.weight").T.reshape(d, nh, hd)

        def k(i):
            return rd.get(f"model.layers.{i}.self_attn.k_proj.weight").T.reshape(d, nkv, hd)

        def v(i):
            return rd.get(f"model.layers.{i}.self_attn.v_proj.weight").T.reshape(d, nkv, hd)

        def o(i):
            return rd.get(f"model.layers.{i}.self_attn.o_proj.weight").T.reshape(nh, hd, d)

        if cfg.n_experts > 0:
            # Mixtral layout: block_sparse_moe.gate (router, [E, D]) +
            # experts.{e}.{w1,w3,w2} (gate/up/down, torch Linear orientation).
            e_ = cfg.n_experts
            moe_pre = "model.layers.{}.block_sparse_moe."

            def expert_stack(i: int, w: str) -> np.ndarray:
                return np.stack([
                    rd.get(moe_pre.format(i) + f"experts.{j}.{w}.weight").T
                    for j in range(e_)
                ])

            mlp_readers = {
                "router": lambda i: rd.get(moe_pre.format(i) + "gate.weight").T,
                "w_gate": lambda i: expert_stack(i, "w1"),  # [E, D, F]
                "w_up": lambda i: expert_stack(i, "w3"),    # [E, D, F]
                "w_down": lambda i: expert_stack(i, "w2"),  # [E, F, D]
            }
        else:
            mlp_readers = {
                "w_gate": lambda i: rd.get(pre.format(i) + "mlp.gate_proj.weight").T,
                "w_up": lambda i: rd.get(pre.format(i) + "mlp.up_proj.weight").T,
                "w_down": lambda i: rd.get(pre.format(i) + "mlp.down_proj.weight").T,
            }
        return {
            "attn_norm": lambda i: rd.get(pre.format(i) + "input_layernorm.weight"),
            "mlp_norm": lambda i: rd.get(pre.format(i) + "post_attention_layernorm.weight"),
            "wq": q, "wk": k, "wv": v, "wo": o,
            **mlp_readers,
        }[field]

    return {
        "embed": lambda: rd.get("model.embed_tokens.weight"),
        "final_norm": lambda: rd.get("model.norm.weight"),
        "lm_head": lambda: rd.get("lm_head.weight").T,
        "layer": layer_leaf,
    }


_LAYER_FIELDS = ("attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
                 "w_gate", "w_up", "w_down")
_MOE_LAYER_FIELDS = ("attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
                     "router", "w_gate", "w_up", "w_down")


def _layer_fields(cfg: ModelConfig):
    return _MOE_LAYER_FIELDS if cfg.n_experts > 0 else _LAYER_FIELDS


def load_llama_params(
    source_dir: str,
    cfg: Optional[ModelConfig] = None,
    mesh=None,
    rules: AxisRules = INFER_RULES,
    param_dtype=jnp.bfloat16,
) -> Params:
    """Stream an HF Llama safetensors checkpoint into a (sharded) pytree.

    cfg defaults to config.json in source_dir. With a mesh, every leaf is
    device_put with its NamedSharding as soon as it is assembled (reference
    engine contract: vllm_engine.py:180). Without a mesh, leaves stay host-local
    jnp arrays (single-process tests / single chip)."""
    if cfg is None:
        cfg = config_from_hf(source_dir)
    from . import llama

    rd = _ShardedReader(source_dir)
    readers = _leaf_readers(cfg, rd)
    axes = llama.param_axes(cfg)

    def put(arr: np.ndarray, leaf_axes) -> jax.Array:
        arr = arr.astype(param_dtype) if param_dtype is not None else arr
        if mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, named_sharding(mesh, *leaf_axes, rules=rules))

    params: Params = {
        "embed": put(readers["embed"](), axes["embed"]),
        "final_norm": put(readers["final_norm"](), axes["final_norm"]),
    }
    fields = _layer_fields(cfg)
    if cfg.scan_layers:
        layers = {}
        for field in fields:
            read = readers["layer"](field)
            stacked = np.stack([np.asarray(read(i)) for i in range(cfg.n_layers)])
            layers[field] = put(stacked, axes["layers"][field])
            del stacked  # one leaf resident at a time
        params["layers"] = layers
    else:
        params["layers"] = [
            {field: put(np.asarray(readers["layer"](field)(i)),
                        axes["layers"][i][field])
             for field in fields}
            for i in range(cfg.n_layers)
        ]
    if not cfg.tie_embeddings:
        params["lm_head"] = put(readers["lm_head"](), axes["lm_head"])
    return params


def save_llama_params(params: Params, cfg: ModelConfig, out_dir: str) -> str:
    """Write the pytree as an HF-layout safetensors checkpoint + config.json."""
    from safetensors.numpy import save_file

    if cfg.n_experts > 0 and not cfg.moe_top1_renorm and cfg.moe_top_k == 1:
        import warnings

        # HF ignores our extension keys: MixtralForCausalLM renormalizes the
        # single gate to 1.0 while this model was trained gating by the raw
        # top-1 prob — a transformers consumer of this export gets different
        # forward math. Our own loader reads the keys back faithfully.
        warnings.warn(
            "exporting a Switch-gated MoE (moe_top_k=1, moe_top1_renorm=False) "
            "in Mixtral layout: transformers will renormalize the gate to 1.0 "
            "and produce different logits; only ray_tpu's loader reproduces "
            "the trained semantics", stacklevel=2)
    os.makedirs(out_dir, exist_ok=True)
    d = cfg.d_model

    def host(x) -> np.ndarray:
        arr = np.asarray(jax.device_get(x))
        # numpy can't persist ml_dtypes bfloat16 through every consumer; f32 is
        # the interchange dtype for these (typically tiny/test) exports
        return arr.astype(np.float32) if arr.dtype not in (np.float32, np.float16) else arr

    def layer(i):
        if cfg.scan_layers:
            return {f: jax.tree.map(lambda x: x[i], params["layers"][f])
                    for f in _layer_fields(cfg)}
        return params["layers"][i]

    tensors: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host(params["embed"]),
        "model.norm.weight": host(params["final_norm"]),
    }
    if not cfg.tie_embeddings:
        tensors["lm_head.weight"] = host(params["lm_head"]).T
    for i in range(cfg.n_layers):
        ly = layer(i)
        pre = f"model.layers.{i}."
        tensors[pre + "input_layernorm.weight"] = host(ly["attn_norm"])
        tensors[pre + "post_attention_layernorm.weight"] = host(ly["mlp_norm"])
        tensors[pre + "self_attn.q_proj.weight"] = host(ly["wq"]).reshape(d, -1).T
        tensors[pre + "self_attn.k_proj.weight"] = host(ly["wk"]).reshape(d, -1).T
        tensors[pre + "self_attn.v_proj.weight"] = host(ly["wv"]).reshape(d, -1).T
        tensors[pre + "self_attn.o_proj.weight"] = host(ly["wo"]).reshape(-1, d).T
        if cfg.n_experts > 0:
            moe_pre = pre + "block_sparse_moe."
            tensors[moe_pre + "gate.weight"] = host(ly["router"]).T
            wg, wu, wd = host(ly["w_gate"]), host(ly["w_up"]), host(ly["w_down"])
            for j in range(cfg.n_experts):
                ex = moe_pre + f"experts.{j}."
                tensors[ex + "w1.weight"] = wg[j].T
                tensors[ex + "w3.weight"] = wu[j].T
                tensors[ex + "w2.weight"] = wd[j].T
        else:
            tensors[pre + "mlp.gate_proj.weight"] = host(ly["w_gate"]).T
            tensors[pre + "mlp.up_proj.weight"] = host(ly["w_up"]).T
            tensors[pre + "mlp.down_proj.weight"] = host(ly["w_down"]).T
    tensors = {k: np.ascontiguousarray(v) for k, v in tensors.items()}
    save_file(tensors, os.path.join(out_dir, "model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(config_to_hf(cfg), f, indent=2)
    return out_dir


def looks_like_checkpoint_dir(path: Any) -> bool:
    return (isinstance(path, str) and os.path.isdir(path)
            and os.path.exists(os.path.join(path, "config.json")))
