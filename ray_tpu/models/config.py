"""Transformer model configs + registry.

Sizes follow the public Llama-2/-3 architecture descriptions (RMSNorm, RoPE, GQA,
SwiGLU, untied or tied embeddings).
"""
from __future__ import annotations

import dataclasses
from typing import Dict

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: str = "bfloat16"  # activation dtype; params kept f32, cast in forward
    remat: bool = True  # jax.checkpoint each layer (HBM <-> FLOPs trade)
    # full: recompute everything in backward (min HBM). dots: save matmul outputs
    # and recompute only cheap elementwise ops (more HBM, fewer recomputed FLOPs —
    # higher MFU when activations fit). none == remat=False.
    remat_policy: str = "full"  # full | dots | dots_no_batch | none
    scan_layers: bool = True  # stack layer params + lax.scan (fast compile)
    # Attention backend: auto|pallas|reference|ring|ulysses. ring/ulysses are the
    # sequence-parallel collectives (ops/ring_attention.py) — use with an sp>1 mesh.
    attention_impl: str = "auto"
    # Pipeline parallelism: >1 splits the layer stack into this many stages over the
    # "pp" mesh axis (parallel/pipeline.py); requires n_layers % pipeline_stages == 0.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0  # 0 -> = pipeline_stages
    # Mixture-of-experts (0 = dense). Experts shard over the "ep" mesh axis; dispatch
    # is static capacity-based einsum (models/moe.py) so shapes stay XLA-friendly.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coef: float = 0.01
    # top-1 gate convention: False = raw top-1 softmax prob (Switch; keeps the
    # router differentiable through the task loss), True = renormalize to 1.0
    # (Mixtral inference semantics — what HF MixtralForCausalLM computes for
    # num_experts_per_tok=1). checkpoint.config_from_hf sets True for
    # model_type=mixtral; irrelevant when moe_top_k > 1 (both renormalize).
    moe_top1_renorm: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def n_params(self) -> int:
        """Approximate parameter count (embeddings + blocks + norms)."""
        emb = self.vocab_size * self.d_model * (1 if self.tie_embeddings else 2)
        attn = self.d_model * self.head_dim * (2 * self.n_heads + 2 * self.n_kv_heads)
        mlp = 3 * self.d_model * self.d_ff
        norms = 2 * self.d_model
        return emb + self.n_layers * (attn + mlp + norms) + self.d_model


_REGISTRY: Dict[str, ModelConfig] = {}


def register_config(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


def get_config(name: str, **overrides) -> ModelConfig:
    cfg = _REGISTRY[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


register_config(
    ModelConfig(
        name="test-tiny",
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=128,
        dtype="float32",
        scan_layers=True,
    )
)
register_config(
    # Tiny serving-test model whose vocab covers the byte-level tokenizer (259 ids).
    ModelConfig(
        name="byte-tiny",
        vocab_size=512,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=256,
        dtype="float32",
        scan_layers=True,
    )
)
register_config(
    # Single-chip bench model (~0.4B): same architecture family as llama3, sized so that
    # f32 params + Adam state + remat activations fit one v5e chip's 16 GiB HBM.
    ModelConfig(
        name="llama-500m",
        vocab_size=32000,
        d_model=1536,
        n_layers=12,
        n_heads=12,
        n_kv_heads=6,
        d_ff=4096,
        max_seq_len=2048,
        rope_theta=500000.0,
    )
)
register_config(
    ModelConfig(
        name="llama-1b",
        vocab_size=32000,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        d_ff=5632,
        max_seq_len=2048,
        rope_theta=500000.0,
    )
)
register_config(
    # llama3-8b LAYER GEOMETRY at single-chip depth: the realistic
    # arithmetic-intensity regime (d_model 4096, GQA 32/8, d_ff 14336) for
    # one-chip MFU benchmarking without 8B-scale optimizer state. 2 layers +
    # the 32k vocab keep f32 Adam + remat activations inside one v5e's HBM.
    ModelConfig(
        name="llama8b-geom2",
        vocab_size=32000,
        d_model=4096,
        n_layers=2,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=2048,
        rope_theta=500000.0,
    )
)
register_config(
    ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=8192,
        rope_theta=500000.0,
    )
)
register_config(
    ModelConfig(
        name="llama3-70b",
        vocab_size=128256,
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        d_ff=28672,
        max_seq_len=8192,
    )
)
register_config(
    ModelConfig(
        name="moe-tiny",
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=96,
        max_seq_len=128,
        dtype="float32",
        scan_layers=True,
        n_experts=4,
        moe_top_k=2,
    )
)
register_config(
    # Mixtral-8x7B architecture description (public): 8 experts, top-2 routing.
    ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=32768,
        rope_theta=1e6,
        n_experts=8,
        moe_top_k=2,
    )
)
register_config(
    ModelConfig(
        name="llama2-7b",
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=32,
        d_ff=11008,
        max_seq_len=4096,
        rope_theta=10000.0,
    )
)
