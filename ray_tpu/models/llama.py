"""Llama-family decoder-only transformer, TPU-first.

Pure-JAX (pytree params, no Module framework) so every transform — pjit, scan, remat,
shard_map — composes without adapters. Architecture: RMSNorm, RoPE (rotate-half / HF
convention), GQA, SwiGLU. Layers are stacked on a leading axis and iterated with
`lax.scan` (+ optional `jax.checkpoint`) so compile time is O(1) in depth and XLA tiles
every matmul onto the MXU with static shapes.

The reference framework has no model code (models come from torch/vLLM; SURVEY.md §2.7);
this is the flagship model its Train/Serve equivalents here exercise.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops import attention
from ray_tpu.ops.quant import as_weight as _w
from ray_tpu.parallel.sharding import with_sharding_constraint as wsc

from .config import ModelConfig

Params = Dict[str, Any]


# ---------------------------------------------------------------------------- init

def param_axes(cfg: ModelConfig) -> Params:
    """Logical-axis tree mirroring init() output (leading 'layer' axis when scanned)."""
    lyr = ("layer",) if cfg.scan_layers else ()

    def L(*axes):
        return lyr + axes

    layers = {
        "attn_norm": L("embed"),
        "wq": L("embed", "heads", "head_dim"),
        "wk": L("embed", "kv_heads", "head_dim"),
        "wv": L("embed", "kv_heads", "head_dim"),
        "wo": L("heads", "head_dim", "embed"),
        "mlp_norm": L("embed"),
    }
    if cfg.n_experts > 0:
        from . import moe as _moe

        layers.update({k: L(*axes) for k, axes in _moe.EXPERT_AXES.items()})
    else:
        layers.update({
            "w_gate": L("embed", "mlp"),
            "w_up": L("embed", "mlp"),
            "w_down": L("mlp", "embed"),
        })
    axes = {
        "embed": ("vocab", "embed"),
        "layers": layers if cfg.scan_layers else [dict(layers) for _ in range(cfg.n_layers)],
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Initialize parameters (f32). Scaled-normal init, wo/w_down scaled by depth."""
    k_emb, k_head, k_layers = jax.random.split(rng, 3)
    d, hd, nh, nkv, ff = cfg.d_model, cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.d_ff

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(jnp.float32)

    def layer_init(key):
        ks = jax.random.split(key, 7)
        s_in = d**-0.5
        s_out = (2 * cfg.n_layers * d) ** -0.5
        out = {
            "attn_norm": jnp.ones((d,), jnp.float32),
            "wq": norm(ks[0], (d, nh, hd), s_in),
            "wk": norm(ks[1], (d, nkv, hd), s_in),
            "wv": norm(ks[2], (d, nkv, hd), s_in),
            "wo": norm(ks[3], (nh, hd, d), s_out),
            "mlp_norm": jnp.ones((d,), jnp.float32),
        }
        if cfg.n_experts > 0:
            from . import moe as _moe

            out.update(_moe.init_expert_weights(ks[4], cfg))
        else:
            out.update({
                "w_gate": norm(ks[4], (d, ff), s_in),
                "w_up": norm(ks[5], (d, ff), s_in),
                "w_down": norm(ks[6], (ff, d), (2 * cfg.n_layers * ff) ** -0.5),
            })
        return out

    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    if cfg.scan_layers:
        layers = jax.vmap(layer_init)(layer_keys)
    else:
        layers = [layer_init(k) for k in layer_keys]

    params: Params = {
        "embed": norm(k_emb, (cfg.vocab_size, d), 1.0),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(k_head, (d, cfg.vocab_size), d**-0.5)
    return params


# ------------------------------------------------------------------------- kernels

def _maybe_remat(body, cfg: ModelConfig):
    """Per-layer rematerialization with a selectable policy (cfg.remat_policy):
    'full' recomputes everything; 'dots' saves matmul outputs so only cheap
    elementwise ops replay in the backward pass (XLA's usual MFU sweet spot)."""
    policy = getattr(cfg, "remat_policy", "full")
    if not cfg.remat or policy == "none":
        return body
    if policy == "dots":
        return jax.checkpoint(body, policy=jax.checkpoint_policies.checkpoint_dots)
    if policy == "dots_no_batch":
        return jax.checkpoint(
            body, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if policy != "full":
        raise ValueError(
            f"unknown remat_policy {policy!r} (expected full | dots | dots_no_batch | none)")
    return jax.checkpoint(body)


def _embed_lookup(table: jax.Array, tokens: jax.Array) -> jax.Array:
    """Token embedding lookup, sharding-aware.

    When the vocab dim is sharded (tp>1) a plain gather carries a transposed-
    device-order output sharding that GSPMD can only reconcile with the batch-sharded
    activation constraint via involuntary full rematerialization (replicate +
    repartition, wasted HBM/ICI every step). A one-hot matmul instead contracts over
    the vocab shard — GSPMD turns that into a local dot + psum over tp, the
    embed/fsdp dim flows through, and the op lands on the MXU. With vocab unsharded
    (tp=1, incl. single device) the cheaper gather is kept: embed-dim (fsdp) sharding
    flows through a gather cleanly. Single-token decode (S==1) also keeps the gather
    — one row per sequence is too small for the resharding cost to matter and the
    matmul would add vocab*d FLOPs per token. (Sharding-in-types can't see Auto-axis
    specs, so the gate is the mesh's tp extent, not the table's actual spec.)
    Semantics note: out-of-range token ids clamp under gather but embed to zeros
    under the one-hot path; valid inputs (< vocab_size) are identical.
    """
    try:
        mesh = jax.sharding.get_abstract_mesh()
        sharded = mesh is not None and not mesh.empty and mesh.shape.get("tp", 1) > 1
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (sharded = False) by design
    except Exception:
        sharded = False
    if not sharded or tokens.shape[-1] == 1:
        return table[tokens]
    onehot = jax.nn.one_hot(tokens, table.shape[0], dtype=table.dtype)
    return jnp.einsum("bsv,vd->bsd", onehot, table)


def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale).astype(dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotate-half RoPE (HF Llama convention). x: [B, S, H, D], positions: [B, S]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# ------------------------------------------------------------------------- forward

class KVCache(NamedTuple):
    """Stacked-per-layer KV cache for autoregressive decode.

    k/v: [L, B, max_len, n_kv_heads, head_dim]; length: current fill (same per batch
    row — the paged engine in serve/ handles ragged batches above this level).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array  # scalar int32


def init_kv_cache(cfg: ModelConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    dtype = dtype or cfg.activation_dtype
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype), length=jnp.zeros((), jnp.int32)
    )


def _block(
    x: jax.Array,
    lp: Params,
    cfg: ModelConfig,
    positions: jax.Array,
    segment_ids: Optional[jax.Array],
    cache_kv: Optional[Tuple[jax.Array, jax.Array]] = None,
    cache_len: Optional[jax.Array] = None,
    token_mask: Optional[jax.Array] = None,
):
    """One decoder block. Returns (x, updated (k,v) if caching, moe aux loss)."""
    dt = x.dtype
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsd,dhk->bshk", h, _w(lp["wq"], dt))
    k = jnp.einsum("bsd,dhk->bshk", h, _w(lp["wk"], dt))
    v = jnp.einsum("bsd,dhk->bshk", h, _w(lp["wv"], dt))
    q = wsc(rope(q, positions, cfg.rope_theta), "batch", "seq", "act_heads", "head_dim")
    k = rope(k, positions, cfg.rope_theta)

    new_kv = None
    if cache_kv is not None:
        ck, cv = cache_kv
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k.astype(ck.dtype), cache_len, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v.astype(cv.dtype), cache_len, axis=1)
        new_kv = (ck, cv)
        attn = attention(
            q, ck, cv, causal=True, q_offset=cache_len, kv_valid_len=cache_len + q.shape[1]
        )
    elif cfg.attention_impl in ("ring", "ulysses"):
        # Sequence-parallel attention: activations stay seq-sharded over "sp"; KV chunks
        # ride the ICI ring (ops/ring_attention.py). If "sp" is already bound manually
        # (pipeline stage traced with extra_manual=("sp",)), call the collective form
        # directly — nested shard_map is not composable.
        from ray_tpu.ops import ring_attention as ra
        from ray_tpu.parallel.sharding import active_manual_axes

        if "sp" in active_manual_axes():
            if cfg.attention_impl == "ring":
                attn = ra.ring_attention(q, k, v, causal=True, segment_ids=segment_ids)
            else:
                if segment_ids is not None:
                    # mirror ring_attention_sharded's refusal — dropping the
                    # packing mask here would silently attend across documents
                    raise NotImplementedError(
                        "segment_ids only supported with impl='ring'")
                attn = ra.ulysses_attention(q, k, v, causal=True)
        else:
            attn = ra.ring_attention_sharded(
                q, k, v, causal=True, segment_ids=segment_ids, impl=cfg.attention_impl
            )
    else:
        attn = attention(q, k, v, causal=True, segment_ids=segment_ids, impl=cfg.attention_impl)
    o = jnp.einsum("bshk,hkd->bsd", attn, _w(lp["wo"], dt))
    x = wsc(x + o, "batch", "seq", "act_embed")

    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts > 0:
        from . import moe as _moe

        b, s, d = h.shape
        y2, aux = _moe.moe_mlp(
            h.reshape(b * s, d), lp["router"], lp["w_gate"], lp["w_up"],
            lp["w_down"], cfg,
            mask=None if token_mask is None else token_mask.reshape(b * s),
        )
        down = y2.reshape(b, s, d)
    else:
        gate = jnp.einsum("bsd,df->bsf", h, _w(lp["w_gate"], dt))
        up = jnp.einsum("bsd,df->bsf", h, _w(lp["w_up"], dt))
        ff = wsc(jax.nn.silu(gate) * up, "batch", "seq", "act_mlp")
        down = jnp.einsum("bsf,fd->bsd", ff, _w(lp["w_down"], dt))
        aux = jnp.zeros((), jnp.float32)
    return wsc(x + down, "batch", "seq", "act_embed"), new_kv, aux


def _pipeline_layers(
    x: jax.Array,
    params: Params,
    cfg: ModelConfig,
    positions: jax.Array,
    segment_ids: Optional[jax.Array],
    token_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Run the layer stack as cfg.pipeline_stages pipeline stages over the "pp" axis.

    Stage-stacks the scanned layer params [L, ...] -> [pp, L/pp, ...] and feeds the
    GPipe schedule (parallel/pipeline.py). Training path only (no KV cache). Packed
    sequences (segment_ids) and MoE token masks ride the schedule as microbatched
    side inputs (pipeline side=...). Returns (x, moe aux loss): MoE composes with
    pp — each stage threads its layers' load-balancing aux through the schedule
    (bubble ticks masked; see pipeline_spmd with_aux).
    """
    from ray_tpu.parallel.pipeline import pipeline

    pp = cfg.pipeline_stages
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pipeline_stages {pp}")
    if not cfg.scan_layers:
        raise ValueError("pipeline_stages > 1 requires scan_layers=True (stacked params)")
    layers = params["layers"]
    stacked = jax.tree_util.tree_map(
        lambda p: p.reshape(pp, cfg.n_layers // pp, *p.shape[1:]), layers
    )
    seq_manual = cfg.attention_impl in ("ring", "ulysses")

    moe = cfg.n_experts > 0
    from jax.sharding import PartitionSpec as P

    side = {}
    side_spec = {}
    seq_spec = P(None, "sp") if seq_manual else P()
    # positions ride as a side input too — caller-supplied offsets (e.g. a
    # nonzero RoPE start) reach every stage instead of being rebuilt as 0..S-1
    side["positions"] = jnp.broadcast_to(positions, x.shape[:2])
    side_spec["positions"] = seq_spec
    if segment_ids is not None:
        side["segment_ids"] = segment_ids
        side_spec["segment_ids"] = seq_spec
    if token_mask is not None:
        side["token_mask"] = token_mask
        side_spec["token_mask"] = seq_spec

    def stage_fn(stage_params, xm, side_now):
        pos = side_now["positions"]
        seg = side_now.get("segment_ids")
        mask = side_now.get("token_mask")

        def body(carry, lp):
            h, aux_acc = carry
            h, _, aux = _block(h, lp, cfg, pos, seg, token_mask=mask)
            return (h, aux_acc + aux), None

        # aux carry must match the loop body's varying-manual-axes type (it
        # inherits xm's vma plus pp)
        from ray_tpu.parallel.sharding import vary_like

        aux0 = vary_like(jnp.zeros((), jnp.float32), xm)
        fn = _maybe_remat(body, cfg)
        (out, aux), _ = jax.lax.scan(fn, (xm, aux0), stage_params)
        return (out, aux) if moe else out

    m = cfg.pipeline_microbatches or pp

    out = pipeline(
        stage_fn,
        stacked,
        x,
        num_microbatches=m,
        x_spec=P(None, "sp", None) if seq_manual else None,
        extra_manual=("sp",) if seq_manual else (),
        with_aux=moe,
        side=side,
        side_spec=side_spec,
    )
    return out if moe else (out, jnp.zeros((), jnp.float32))


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: ModelConfig,
    *,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    cache: Optional[KVCache] = None,
    return_aux: bool = False,
    token_mask: Optional[jax.Array] = None,  # [B, S] 1=real; MoE capacity masking
):
    """tokens [B, S] -> (logits [B, S, vocab] f32, updated cache or None).

    With return_aux=True also returns the summed MoE load-balancing loss (zero for
    dense configs) as a third element."""
    b, s = tokens.shape
    if positions is None:
        start = cache.length if cache is not None else 0
        positions = jnp.broadcast_to(jnp.arange(s)[None, :] + start, (b, s))
    x = _embed_lookup(params["embed"].astype(cfg.activation_dtype), tokens)
    x = wsc(x, "batch", "seq", "act_embed")
    aux_total = jnp.zeros((), jnp.float32)

    if cfg.pipeline_stages > 1 and cache is None:
        x, aux_total = _pipeline_layers(x, params, cfg, positions, segment_ids,
                                        token_mask)
        new_cache = None
    elif cfg.scan_layers:
        if cache is not None:

            def body(carry, xs):
                h = carry
                lp, ck, cv = xs
                h, new_kv, aux = _block(h, lp, cfg, positions, segment_ids, (ck, cv),
                                        cache.length, token_mask)
                return h, (new_kv, aux)

            fn = _maybe_remat(body, cfg)
            x, ((nk, nv), auxs) = jax.lax.scan(fn, x, (params["layers"], cache.k, cache.v))
            new_cache = KVCache(k=nk, v=nv, length=cache.length + s)
            aux_total = auxs.sum()
        else:

            def body(carry, lp):
                h, _, aux = _block(carry, lp, cfg, positions, segment_ids,
                                   token_mask=token_mask)
                return h, aux

            fn = _maybe_remat(body, cfg)
            x, auxs = jax.lax.scan(fn, x, params["layers"])
            new_cache = None
            aux_total = auxs.sum()
    else:
        new_cache = None
        ks, vs = [], []
        for i, lp in enumerate(params["layers"]):
            if cache is not None:
                x, kv, aux = _block(x, lp, cfg, positions, segment_ids,
                                    (cache.k[i], cache.v[i]), cache.length, token_mask)
                ks.append(kv[0])
                vs.append(kv[1])
            else:
                x, _, aux = _block(x, lp, cfg, positions, segment_ids,
                                   token_mask=token_mask)
            aux_total = aux_total + aux
        if cache is not None:
            new_cache = KVCache(jnp.stack(ks), jnp.stack(vs), cache.length + s)

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bsd,dv->bsv", x, _w(head, cfg.activation_dtype))
    logits = wsc(logits.astype(jnp.float32), "batch", "seq", "act_vocab")
    if return_aux:
        return logits, new_cache, aux_total
    return logits, new_cache


def loss_fn(
    params: Params,
    batch: Dict[str, jax.Array],
    cfg: ModelConfig,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross entropy. batch: tokens [B,S]; optional loss_mask/segment_ids."""
    tokens = batch["tokens"]
    seg = batch.get("segment_ids")
    logits, _, aux = forward(
        params, tokens[:, :-1], cfg,
        segment_ids=None if seg is None else seg[:, :-1], return_aux=True,
    )
    targets = tokens[:, 1:]
    # target-logit minus logsumexp == log_softmax gathered at the target, without
    # materializing a second [B,S,vocab] f32 tensor (1 GB/chip at 8B scale).
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ll = tgt - lse
    mask = batch.get("loss_mask")
    mask = jnp.ones_like(ll) if mask is None else mask[:, 1:].astype(ll.dtype)
    denom = jnp.maximum(mask.sum(), 1.0)
    ce = -(ll * mask).sum() / denom
    loss = ce + aux
    return loss, {"loss": loss, "ce_loss": ce, "moe_aux_loss": aux, "tokens": denom}
