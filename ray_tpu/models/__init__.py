"""Model zoo: JAX-native transformer families with GSPMD logical-axis sharding.

The reference framework ships no models (it orchestrates torch/vLLM models —
SURVEY.md §2.7); a TPU-native stack needs its own, so the flagship Llama family
lives here and Train/Serve/RLlib build on it.
"""
from .config import ModelConfig, get_config, register_config  # noqa: F401
from . import llama  # noqa: F401
