"""Mixture-of-experts MLP with static capacity-based dispatch, TPU-first.

The reference has no MoE implementation (vLLM-internal only; SURVEY.md §2.3 row
"Expert parallel (EP/MoE): absent — must be built natively"). This is the
GShard/Switch dispatch pattern expressed as einsums over one-hot dispatch masks:
every shape is static (tokens × experts × capacity), so XLA tiles the expert
matmuls onto the MXU and GSPMD turns the "expert" axis sharding ("ep" mesh axis)
into all-to-alls on ICI — no ragged host-side routing.

Capacity semantics: tokens are processed in fixed-size groups (GShard-style, so
dispatch memory stays linear in sequence length); within a group each expert
takes at most C = ceil(capacity_factor · k · g / E) tokens. An overflow slot is
dropped for that expert and its gate weight is simply lost — the token's MLP
output is underweighted by that fraction (no renormalization over survivors).
With top_k=1 the raw router probability gates the output (Switch), keeping the
router differentiable through the task loss; with top_k>1 the top-k gate values
renormalize to sum to 1 (Mixtral convention).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.quant import as_weight as _qw
from ray_tpu.parallel.sharding import with_sharding_constraint as wsc

from .config import ModelConfig

# Tokens per dispatch group: dispatch/combine tensors are [g, E, C] with C ∝ g/E,
# so per-group memory is O(g²) and total is O(T·g) — bounded, unlike one [T, E, C]
# block whose memory grows as O(T²).
def _moe_group_size() -> int:
    from ray_tpu.config import CONFIG

    return CONFIG.moe_group_size


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    c = int(cfg.moe_capacity_factor * cfg.moe_top_k * n_tokens / cfg.n_experts) + 1
    return max(4, min(c, n_tokens))


def _group_size(t: int) -> int:
    """Largest divisor of t that is <= the group-size flag (static shapes)."""
    cap = _moe_group_size()
    if t <= cap:
        return t
    for g in range(cap, 0, -1):
        if t % g == 0:
            return g
    return t


def _moe_group(x, mask, router_w, w_gate, w_up, w_down, cfg: ModelConfig):
    """Dispatch one token group. x [g, D]; mask [g] 1.0=real token, 0.0=pad/inactive."""
    g, d = x.shape
    e, k = cfg.n_experts, cfg.moe_top_k
    c = expert_capacity(cfg, g)
    dt = x.dtype

    logits = jnp.einsum("td,de->te", x, _qw(router_w, dt)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [g, E]

    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [g, k]
    if k > 1 or cfg.moe_top1_renorm:
        gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)
    # k == 1 without moe_top1_renorm: raw top-1 prob gates the output (Switch) so
    # the router receives task-loss gradient; renormalizing pins the gate to 1.0
    # (Mixtral inference semantics — set by config_from_hf for HF checkpoints).

    # Position of each (token, slot) within its expert's capacity. Slot-major order
    # (all top-1 picks get priority over top-2 picks, GShard convention). Masked
    # tokens (padding, inactive decode slots) never claim capacity.
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32) * mask[:, None, None]
    slot_major = onehot.transpose(1, 0, 2).reshape(k * g, e)  # [k*g, E]
    pos_flat = jnp.cumsum(slot_major, axis=0) - slot_major  # rank among same-expert picks
    pos = pos_flat.reshape(k, g, e).transpose(1, 0, 2)  # [g, k, E]
    keep = (pos < c) * onehot  # drop overflow beyond capacity

    # dispatch/combine tensors
    pos_idx = jnp.minimum(pos.astype(jnp.int32), c - 1)
    pos_onehot = jax.nn.one_hot(pos_idx, c, dtype=jnp.float32)  # [g, k, E, C]
    dispatch = jnp.einsum("tke,tkec->tec", keep, pos_onehot)  # [g, E, C] 0/1
    combine = jnp.einsum("tk,tke,tkec->tec", gate_vals, keep, pos_onehot)

    # route tokens to expert buffers, run experts, route back
    xin = jnp.einsum("tec,td->ecd", dispatch.astype(dt), x)  # [E, C, D]
    xin = wsc(xin, "act_expert", None, "act_embed")
    gate = jnp.einsum("ecd,edf->ecf", xin, _qw(w_gate, dt))
    up = jnp.einsum("ecd,edf->ecf", xin, _qw(w_up, dt))
    act = wsc(jax.nn.silu(gate) * up, "act_expert", None, "act_mlp")
    out = jnp.einsum("ecf,efd->ecd", act, _qw(w_down, dt))  # [E, C, D]
    y = jnp.einsum("tec,ecd->td", combine.astype(dt), out)  # [g, D]

    # load-balancing loss (Switch eq. 4) over real tokens only: E * sum_e f_e * P_e
    denom = jnp.maximum(mask.sum(), 1.0)
    me = (probs * mask[:, None]).sum(axis=0) / denom
    ce = (keep.sum(axis=1)).sum(axis=0) / denom
    aux = (me * ce).sum() * e * cfg.moe_aux_loss_coef
    return y, aux


def moe_mlp(
    x: jax.Array,  # [T, D] tokens
    router_w: jax.Array,  # [D, E]
    w_gate: jax.Array,  # [E, D, F]
    w_up: jax.Array,  # [E, D, F]
    w_down: jax.Array,  # [E, F, D]
    cfg: ModelConfig,
    mask: Optional[jax.Array] = None,  # [T] 1.0 = real token
) -> Tuple[jax.Array, jax.Array]:
    """Returns ([T, D] output, scalar load-balancing aux loss)."""
    t, d = x.shape
    if mask is None:
        mask = jnp.ones((t,), jnp.float32)
    mask = mask.astype(jnp.float32)
    g = _group_size(t)
    if g == t:
        return _moe_group(x, mask, router_w, w_gate, w_up, w_down, cfg)
    xg = x.reshape(t // g, g, d)
    mg = mask.reshape(t // g, g)
    yg, auxg = jax.vmap(
        lambda xi, mi: _moe_group(xi, mi, router_w, w_gate, w_up, w_down, cfg)
    )(xg, mg)
    return yg.reshape(t, d), auxg.mean()


def init_expert_weights(key: jax.Array, cfg: ModelConfig):
    """Per-layer MoE parameter block (replaces the dense w_gate/w_up/w_down)."""
    d, f, e = cfg.d_model, cfg.d_ff, cfg.n_experts
    ks = jax.random.split(key, 4)
    s_in = d**-0.5
    s_out = (2 * cfg.n_layers * f) ** -0.5
    return {
        "router": jax.random.normal(ks[0], (d, e), jnp.float32) * s_in,
        "w_gate": jax.random.normal(ks[1], (e, d, f), jnp.float32) * s_in,
        "w_up": jax.random.normal(ks[2], (e, d, f), jnp.float32) * s_in,
        "w_down": jax.random.normal(ks[3], (e, f, d), jnp.float32) * s_out,
    }


EXPERT_AXES = {
    "router": ("embed", "expert"),
    "w_gate": ("expert", "embed", "mlp"),
    "w_up": ("expert", "embed", "mlp"),
    "w_down": ("expert", "mlp", "embed"),
}
