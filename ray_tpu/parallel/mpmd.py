"""MPMD pipeline parallelism facade.

`parallel/pipeline.py` is the IN-program combinator: one GSPMD-traced program,
stages as mesh shards, activations hopping via lax.ppermute. This module
fronts the CROSS-process runner (train/mpmd_pipeline.py): each stage is its
own process compiling its own forward/backward/update programs, microbatch
blocks streaming stage-to-stage over the collective data plane on a 1F1B
schedule — for models and topologies one mesh cannot hold (arXiv 2412.14374).
"""
from ray_tpu.train.mpmd_pipeline import (  # noqa: F401
    MPMDPipeline,
    MPMDPipelineConfig,
    StageRunner,
    build_schedule,
    bubble_fraction,
    stage_runner_from_train_context,
)
