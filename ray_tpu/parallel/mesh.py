"""Device-mesh construction.

A `MeshSpec` is the single declarative knob for every parallelism strategy the framework
supports — data (dp), fully-sharded data (fsdp), tensor (tp), sequence/context (sp),
pipeline (pp), expert (ep). The reference framework reaches the same goals with NCCL
process groups per strategy (reference: python/ray/util/collective/collective.py:150,
python/ray/train/torch/config.py:66); on TPU a single mesh + NamedSharding per array is
the idiomatic equivalent, and XLA chooses the collectives.

Axis order matters on TPU: later (minor) axes map to physically-adjacent devices, so put
the most bandwidth-hungry axis (tp, then sp) last so its collectives ride ICI.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: most-major (cross-slice / DCN friendly) → most-minor (ICI).
AXIS_ORDER: Tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. Size -1 on at most one axis means "use all remaining".

    Examples:
        MeshSpec(dp=-1)                      # pure data parallel
        MeshSpec(fsdp=-1, tp=4)              # FSDP with 4-way tensor parallel
        MeshSpec(dp=2, sp=2, tp=2)           # 8-chip mixed
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in a single -1 axis so the product equals n_devices."""
        sizes = list(self.sizes())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {self}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {self} needs {fixed} devices, have {n_devices}")
        return MeshSpec(**dict(zip(AXIS_ORDER, sizes)))

    @property
    def n_devices(self) -> int:
        return math.prod(self.sizes())


def build_mesh(
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` from a spec over the given (or all) devices.

    Keeps every axis in the mesh even if size 1 — downstream PartitionSpecs can then
    name any axis unconditionally, and XLA elides the trivial collectives.
    """
    if devices is None:
        devices = jax.devices()
    spec = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(spec.sizes())
    return Mesh(arr, AXIS_ORDER)


def local_mesh(**axes: int) -> Mesh:
    """Convenience: build_mesh(MeshSpec(**axes)) over all visible devices."""
    return build_mesh(MeshSpec(**axes))


def use_mesh(mesh: Mesh):
    """Context manager installing `mesh` as the ambient mesh (jax version compat)."""
    if hasattr(jax.sharding, "set_mesh"):
        return jax.sharding.set_mesh(mesh)
    if hasattr(jax.sharding, "use_mesh"):
        return jax.sharding.use_mesh(mesh)
    # jax<=0.4.x: Mesh is itself the context manager (thread-local physical
    # mesh env; sharding.py's ambient-mesh probe reads it back)
    return mesh
