"""Parallelism primitives: device meshes, sharding rules, collective helpers.

TPU-native replacement for the reference's process-group/NCCL plane
(reference: python/ray/util/collective/collective.py, python/ray/train/torch/config.py):
instead of bootstrapping NCCL communicators, we describe a `jax.sharding.Mesh` once and
let XLA insert collectives (psum/all_gather/reduce_scatter/ppermute) over ICI/DCN.
"""
from .mesh import MeshSpec, build_mesh, local_mesh, use_mesh  # noqa: F401

# `from ray_tpu.parallel import mpmd` — the cross-process MPMD pipeline facade —
# is imported on demand, not here: it fronts ray_tpu.train, whose package init
# imports this one.
from .sharding import (  # noqa: F401
    AxisRules,
    LogicalAxis,
    logical_to_mesh_axes,
    named_sharding,
    shard_pytree,
    with_sharding_constraint,
)
