"""Pipeline parallelism: GPipe-style microbatch schedule over the `pp` mesh axis.

Reference capability: the reference only *places* TP×PP workers for vLLM
(SURVEY.md §2.3 TP/PP row); the actual pipeline engine is external. Here it is native:
stages are mesh shards, activations hop stage→stage via `lax.ppermute` over ICI/DCN, and
the whole schedule compiles into the train step (bubbles and all), so autodiff gives the
1F1B-equivalent gradient accumulation for free.

Layout: stage-stacked params (leading axis = pp, sharded over "pp"); inputs split into M
microbatches. The schedule runs M + pp - 1 ticks; each tick every stage runs its layer on
its current microbatch and ppermutes the result forward. Other mesh axes (dp/fsdp/tp/sp)
stay in GSPMD "auto" mode inside the stage function — pipeline composes with them.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_spmd(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x_mb: jax.Array,
    *,
    axis_name: str = "pp",
) -> jax.Array:
    """Collective pipeline schedule; call inside shard_map manual over `axis_name`.

    stage_fn(params, x) -> y with y.shape == x.shape (a transformer block stack).
    stage_params: THIS stage's params. x_mb: [M, ...] microbatches (same array on every
    stage; only stage 0 consumes it). Returns [M, ...] outputs on every stage.
    """
    pp = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    ticks = m + pp - 1
    # pcast-to-varying: the carry is device-varying from tick 1 on; the init must match
    # the full varying set (pp plus any other manual axes x_mb carries, e.g. sp) —
    # adding only the axes the value doesn't already vary over.
    def _vary(z):
        try:
            want = set(jax.typeof(x_mb).vma) | {axis_name}
            have = set(jax.typeof(z).vma)
        except Exception:
            want, have = {axis_name}, set()
        need = tuple(want - have)
        if not need:
            return z
        if hasattr(lax, "pcast"):
            return lax.pcast(z, need, to="varying")
        return lax.pvary(z, need)

    y0 = _vary(jnp.zeros_like(x_mb))
    buf0 = _vary(jnp.zeros_like(x_mb[0]))
    fwd = [(i, i + 1) for i in range(pp - 1)]  # non-circular: stage 0 receives zeros

    def body(carry, t):
        buf, y = carry
        inp = jnp.where(stage == 0, x_mb[jnp.clip(t, 0, m - 1)], buf)
        out = stage_fn(stage_params, inp)
        mb = t - (pp - 1)
        done = lax.dynamic_update_index_in_dim(y, out, jnp.clip(mb, 0, m - 1), 0)
        y = jnp.where((stage == pp - 1) & (mb >= 0), done, y)
        buf_next = lax.ppermute(out, axis_name, fwd) if pp > 1 else buf
        return (buf_next, y), None

    (_, y), _ = lax.scan(body, (buf0, y0), jnp.arange(ticks))
    # Hand the last stage's outputs to every stage (loss is then computed redundantly —
    # the SPMD idiom; XLA keeps one copy per pp group member).
    return lax.psum(jnp.where(stage == pp - 1, y, jnp.zeros_like(y)), axis_name)


def pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    num_microbatches: int,
    mesh=None,
    axis_name: str = "pp",
    x_spec: P = None,
    extra_manual: tuple = (),
) -> jax.Array:
    """Driver-level wrapper: global [B, ...] input, stage-stacked params.

    stacked_params: pytree whose leaves have leading dim pp, sharded P("pp", ...).
    Splits x into `num_microbatches`, runs the schedule, returns [B, ...] outputs.
    Jit-friendly: trace under use_mesh(mesh) or pass mesh explicitly.

    `extra_manual` names additional mesh axes the stage itself handles collectively
    (e.g. "sp" when the stage runs ring attention); `x_spec` is the PartitionSpec of one
    microbatch [B/M, ...] over those axes. Nested shard_map is not composable (sdy
    rejects re-bound axes), so pp and sp share ONE manual region here.
    """
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by num_microbatches {num_microbatches}")
    env_mesh = mesh if mesh is not None else jax.sharding.get_abstract_mesh()
    pp_size = env_mesh.shape.get(axis_name) if getattr(env_mesh, "shape", None) else None
    leading = {leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)}
    if pp_size is not None and leading and leading != {pp_size}:
        raise ValueError(
            f"stacked_params leading dims {sorted(leading)} must equal mesh '{axis_name}' "
            f"size {pp_size}; a mismatch would silently drop pipeline stages"
        )
    x_mb = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
    manual = {axis_name, *extra_manual}
    mb_spec = P(None, *(x_spec or P())) if (x_spec or extra_manual) else P()

    def inner(params, x_mb):
        from .sharding import manual_axes

        local = jax.tree_util.tree_map(lambda p: p[0], params)  # drop stage axis (len 1)
        with manual_axes(*manual):
            return pipeline_spmd(stage_fn, local, x_mb, axis_name=axis_name)

    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    mapped = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(param_specs, mb_spec),
        out_specs=mb_spec,
        axis_names=manual,
    )
    y_mb = mapped(stacked_params, x_mb)
    return y_mb.reshape(b, *x.shape[1:])
