"""Pipeline parallelism: GPipe-style microbatch schedule over the `pp` mesh axis.

Reference capability: the reference only *places* TP×PP workers for vLLM
(SURVEY.md §2.3 TP/PP row); the actual pipeline engine is external. Here it is native:
stages are mesh shards, activations hop stage→stage via `lax.ppermute` over ICI/DCN, and
the whole schedule compiles into the train step (bubbles and all), so autodiff gives the
1F1B-equivalent gradient accumulation for free.

Layout: stage-stacked params (leading axis = pp, sharded over "pp"); inputs split into M
microbatches. The schedule runs M + pp - 1 ticks; each tick every stage runs its layer on
its current microbatch and ppermutes the result forward. Other mesh axes (dp/fsdp/tp/sp)
stay in GSPMD "auto" mode inside the stage function — pipeline composes with them.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_spmd(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x_mb: jax.Array,
    *,
    axis_name: str = "pp",
    with_aux: bool = False,
    side_mb: Any = None,
):
    """Collective pipeline schedule; call inside shard_map manual over `axis_name`.

    stage_fn(params, x) -> y with y.shape == x.shape (a transformer block stack).
    stage_params: THIS stage's params. x_mb: [M, ...] microbatches (same array on every
    stage; only stage 0 consumes it). Returns [M, ...] outputs on every stage.

    with_aux=True: stage_fn returns (y, aux_scalar) — e.g. a MoE load-balancing
    loss. Bubble ticks run on zero inputs, so each stage's aux only counts ticks
    where it holds a real microbatch (its valid window is t - stage in [0, M));
    the return is then (y, psum-over-stages of the per-microbatch MEAN aux) —
    matching the non-pipelined sum-over-layers of a full-batch mean, since
    microbatches are equal-sized.

    side_mb: optional pytree of [M, ...] per-microbatch side inputs that do NOT
    flow stage-to-stage (segment_ids, token masks). Unlike x_mb, every stage
    reads the side slice of the microbatch it is CURRENTLY processing (t - stage),
    and stage_fn is called as stage_fn(params, x, side).
    """
    pp = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = x_mb.shape[0]
    ticks = m + pp - 1
    # pcast-to-varying: the carry is device-varying from tick 1 on; the init must match
    # the full varying set (pp plus any other manual axes x_mb carries, e.g. sp) —
    # adding only the axes the value doesn't already vary over.
    from .sharding import vary_like

    def _vary(z):
        return vary_like(z, x_mb, extra=(axis_name,))

    y0 = _vary(jnp.zeros_like(x_mb))
    buf0 = _vary(jnp.zeros_like(x_mb[0]))
    aux0 = _vary(jnp.zeros((), jnp.float32))
    fwd = [(i, i + 1) for i in range(pp - 1)]  # non-circular: stage 0 receives zeros

    def body(carry, t):
        buf, y, aux_acc = carry
        inp = jnp.where(stage == 0, x_mb[jnp.clip(t, 0, m - 1)], buf)
        args = (stage_params, inp)
        if side_mb is not None:
            mb_now = jnp.clip(t - stage, 0, m - 1)
            args += (jax.tree_util.tree_map(lambda a: a[mb_now], side_mb),)
        if with_aux:
            out, aux = stage_fn(*args)
            valid = (t >= stage) & (t - stage < m)
            aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
        else:
            out = stage_fn(*args)
        mb = t - (pp - 1)
        done = lax.dynamic_update_index_in_dim(y, out, jnp.clip(mb, 0, m - 1), 0)
        y = jnp.where((stage == pp - 1) & (mb >= 0), done, y)
        buf_next = lax.ppermute(out, axis_name, fwd) if pp > 1 else buf
        return (buf_next, y, aux_acc), None

    (_, y, aux_acc), _ = lax.scan(body, (buf0, y0, aux0), jnp.arange(ticks))
    # Hand the last stage's outputs to every stage (loss is then computed redundantly —
    # the SPMD idiom; XLA keeps one copy per pp group member).
    y = lax.psum(jnp.where(stage == pp - 1, y, jnp.zeros_like(y)), axis_name)
    if with_aux:
        return y, lax.psum(aux_acc, axis_name) / m
    return y


def pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    num_microbatches: int,
    mesh=None,
    axis_name: str = "pp",
    x_spec: P = None,
    extra_manual: tuple = (),
    with_aux: bool = False,
    side: Any = None,
    side_spec: Any = None,
):
    """Driver-level wrapper: global [B, ...] input, stage-stacked params.

    stacked_params: pytree whose leaves have leading dim pp, sharded P("pp", ...).
    Splits x into `num_microbatches`, runs the schedule, returns [B, ...] outputs
    (or (outputs, aux scalar) when with_aux — see pipeline_spmd; aux is pmean'd
    over `extra_manual` axes, since e.g. sp shards hold disjoint token chunks
    whose shard-mean auxes average to the global mean).
    Jit-friendly: trace under use_mesh(mesh) or pass mesh explicitly.

    `extra_manual` names additional mesh axes the stage itself handles collectively
    (e.g. "sp" when the stage runs ring attention); `x_spec` is the PartitionSpec of one
    microbatch [B/M, ...] over those axes. Nested shard_map is not composable (sdy
    rejects re-bound axes), so pp and sp share ONE manual region here.

    `side`: optional pytree of [B, ...] per-example side inputs (segment_ids,
    token masks) split into microbatches alongside x; stage_fn then receives a
    third argument holding its current microbatch's slice (see pipeline_spmd).
    `side_spec`: matching pytree of per-microbatch PartitionSpecs over the
    manual axes (default: replicated).
    """
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by num_microbatches {num_microbatches}")
    for leaf in jax.tree_util.tree_leaves(side):
        if leaf.shape[0] != b:
            raise ValueError(
                f"side input leading dim {leaf.shape[0]} != batch {b}")
    from .sharding import ambient_mesh

    env_mesh = mesh if mesh is not None else ambient_mesh()
    pp_size = env_mesh.shape.get(axis_name) if getattr(env_mesh, "shape", None) else None
    leading = {leaf.shape[0] for leaf in jax.tree_util.tree_leaves(stacked_params)}
    if pp_size is not None and leading and leading != {pp_size}:
        raise ValueError(
            f"stacked_params leading dims {sorted(leading)} must equal mesh '{axis_name}' "
            f"size {pp_size}; a mismatch would silently drop pipeline stages"
        )
    x_mb = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
    side_mb = jax.tree_util.tree_map(
        lambda a: a.reshape(num_microbatches, b // num_microbatches, *a.shape[1:]),
        side)
    manual = {axis_name, *extra_manual}
    mb_spec = P(None, *(x_spec or P())) if (x_spec or extra_manual) else P()
    side_specs = (jax.tree_util.tree_map(
        lambda s: P(None, *s), side_spec, is_leaf=lambda s: isinstance(s, P))
        if side_spec is not None
        else jax.tree_util.tree_map(lambda _: P(), side))

    def inner(params, x_mb, side_mb):
        from .sharding import manual_axes

        local = jax.tree_util.tree_map(lambda p: p[0], params)  # drop stage axis (len 1)
        with manual_axes(*manual):
            out = pipeline_spmd(stage_fn, local, x_mb, axis_name=axis_name,
                                with_aux=with_aux, side_mb=side_mb)
            if with_aux:
                y, aux = out
                for ax in extra_manual:
                    aux = lax.pmean(aux, ax)
                return y, aux
            return out

    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    from .sharding import compat_shard_map

    mapped = compat_shard_map(
        inner,
        mesh,
        (param_specs, mb_spec, side_specs),
        (mb_spec, P()) if with_aux else mb_spec,
        manual,
    )
    if with_aux:
        y_mb, aux = mapped(stacked_params, x_mb, side_mb)
        return y_mb.reshape(b, *x.shape[1:]), aux
    y_mb = mapped(stacked_params, x_mb, side_mb)
    return y_mb.reshape(b, *x.shape[1:])
