"""Logical-axis sharding rules.

Model code annotates arrays with *logical* axis names ("batch", "embed", "heads", …);
a single `AxisRules` table maps logical names to mesh axes. Changing the parallelism
strategy = changing the table, not the model. This is the GSPMD idiom the reference
delegates to external libraries (FSDP/DeepSpeed — SURVEY.md §2.3) but is native here.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LogicalAxis = Optional[str]
MeshAxes = Union[None, str, Tuple[str, ...]]


class AxisRules:
    """Mapping logical axis name → mesh axis (or tuple of mesh axes, or None)."""

    def __init__(self, rules: Dict[str, MeshAxes]):
        self.rules = dict(rules)

    def __getitem__(self, logical: LogicalAxis) -> MeshAxes:
        if logical is None:
            return None
        return self.rules.get(logical)

    def spec(self, logical_axes: Sequence[LogicalAxis]) -> P:
        return P(*(self[a] for a in logical_axes))


# Default rules for transformer training. Parameter axes ("embed"/"mlp"/"heads"/"vocab")
# and activation axes ("act_*") are distinct logical names because a PartitionSpec may not
# reuse a mesh axis: batch → (dp, fsdp) shards activations ZeRO-style while embed → fsdp
# shards parameters, and the two never appear on the same array.
TRAIN_RULES = AxisRules(
    {
        # parameters
        "embed": "fsdp",
        "heads": "tp",
        "kv_heads": "tp",
        "head_dim": None,
        "mlp": "tp",
        "vocab": "tp",
        "expert": "ep",
        "stage": "pp",
        # activations
        "batch": ("dp", "fsdp"),
        "seq": "sp",
        "act_embed": None,
        "act_heads": "tp",
        "act_kv_heads": "tp",
        "act_mlp": "tp",
        "act_vocab": "tp",
        "act_expert": "ep",
    }
)

# Inference: params replicated across dp, sharded over tp; KV cache sharded over heads
# (tp) and batch (dp).
INFER_RULES = AxisRules(
    {
        "embed": None,
        "heads": "tp",
        "kv_heads": "tp",
        "head_dim": None,
        "mlp": "tp",
        "vocab": "tp",
        "expert": "ep",
        "stage": "pp",
        "batch": "dp",
        "seq": "sp",
        "act_embed": None,
        "act_heads": "tp",
        "act_kv_heads": "tp",
        "act_mlp": "tp",
        "act_vocab": "tp",
        "act_expert": "ep",
    }
)


def logical_to_mesh_axes(
    logical_axes: Sequence[LogicalAxis], rules: AxisRules = TRAIN_RULES
) -> P:
    return rules.spec(logical_axes)


def named_sharding(
    mesh: Mesh, *logical_axes: LogicalAxis, rules: AxisRules = TRAIN_RULES
) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes))


def shard_pytree(tree, axes_tree, mesh: Mesh, rules: AxisRules = TRAIN_RULES):
    """device_put a pytree according to a parallel tree of logical-axes tuples.

    `axes_tree` mirrors `tree`; each leaf is a tuple of logical axis names (or None)
    matching the array rank.
    """

    def _put(x, axes):
        return jax.device_put(x, named_sharding(mesh, *axes, rules=rules))

    return jax.tree.map(_put, tree, axes_tree, is_leaf=lambda x: x is None)


_MANUAL_AXES: "contextvars.ContextVar[frozenset]" = None  # initialized below


def ambient_mesh():
    """The mesh in scope, across jax versions: the abstract mesh
    (use_mesh/set_mesh on jax>=0.5) or the entered physical mesh
    (`with mesh:` on jax<=0.4.x). None when no mesh is active."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is not None and not mesh.empty:
            return mesh
    except AttributeError:
        pass
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (return None) by design
    except Exception:
        return None
    try:
        from jax._src.mesh import thread_resources

        mesh = thread_resources.env.physical_mesh
        if mesh is not None and not mesh.empty:
            return mesh
    # graftlint: allow[swallowed-exception] jax-version probe: missing thread_resources means no ambient mesh
    except Exception:
        pass
    return None


def with_sharding_constraint(x, *logical_axes: LogicalAxis, rules: AxisRules = TRAIN_RULES):
    """In-jit sharding hint using logical names. No-op outside jit or without a mesh.

    Mesh axes currently bound manually (inside a shard_map region entered via
    `manual_axes()`) are dropped from the constraint — GSPMD may only constrain auto axes.
    """
    mesh = ambient_mesh()
    if mesh is None:
        return x
    spec = rules.spec(logical_axes)
    manual = active_manual_axes()
    if manual:
        if isinstance(mesh, Mesh):
            # jax<=0.4.x: constraining auto axes from inside a partial-manual
            # shard_map region trips the partitioner's IsManualSubgroup check —
            # skip the hint entirely (it is an optimization, not semantics).
            return x

        def _filt(entry):
            if entry is None:
                return None
            if isinstance(entry, tuple):
                kept = tuple(a for a in entry if a not in manual)
                return kept if kept else None
            return None if entry in manual else entry

        spec = P(*(_filt(e) for e in spec))
    if isinstance(mesh, Mesh):
        # concrete (physical) mesh: the constraint needs a full NamedSharding
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


# -- manual-axes context ---------------------------------------------------------------
# shard_map callees (pipeline stages, ring attention) trace model code while some mesh
# axes are manual; with_sharding_constraint must not reference those. Code entering a
# manual region wraps the trace in `with manual_axes("pp", "sp"): ...`.
import contextlib as _contextlib
import contextvars as _contextvars

_MANUAL_AXES = _contextvars.ContextVar("ray_tpu_manual_axes", default=frozenset())


def active_manual_axes() -> frozenset:
    return _MANUAL_AXES.get()


@_contextlib.contextmanager
def manual_axes(*names: str):
    token = _MANUAL_AXES.set(_MANUAL_AXES.get() | frozenset(names))
    try:
        yield
    finally:
        _MANUAL_AXES.reset(token)


def vary_like(z, ref=None, *, extra: Sequence[str] = ()):
    """Cast `z` to vary over the manual axes `ref` varies over, plus `extra`.

    The shard_map vma type system requires loop carries/inits to match the body's
    varying-axes set; this is the one shared implementation of the
    pcast/pvary-to-varying idiom (jax moved pvary -> pcast(..., to="varying")
    across versions, hence the feature probe). ref=None means "just `extra`".
    """
    want = set(extra)
    if ref is not None:
        try:
            want |= set(jax.typeof(ref).vma)
        # graftlint: allow[swallowed-exception] jax-version probe: typeof/vma absent on older jax
        except Exception:
            pass
    try:
        have = set(jax.typeof(z).vma)
    # graftlint: allow[swallowed-exception] degrades to the coded fallback (have = set()) by design
    except Exception:
        have = set()
    need = tuple(want - have)
    if not need:
        return z
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(z, need, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(z, need)
    return z  # pre-vma jax: shard_map has no varying-axes type system to satisfy


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs, manual: Sequence[str]):
    """shard_map with the given axes manual and the rest in GSPMD auto mode,
    across jax versions (jax.shard_map axis_names= vs experimental auto=).
    One shared implementation for grad_sync's bucketed region, the in-program
    pipeline combinator, and the MPMD stage runner's stage_dp sharding."""
    manual = frozenset(manual)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=set(manual))
    from jax.experimental.shard_map import shard_map as _sm

    auto = frozenset(mesh.axis_names) - manual
    bad = [a for a in sorted(auto) if mesh.shape[a] > 1]
    if bad:
        # jaxlib<=0.4.x partial-auto shard_map hard-crashes XLA
        # (IsManualSubgroup check) when a non-trivial auto axis crosses the
        # region — refuse with a python error instead.
        raise NotImplementedError(
            f"shard_map over manual axes {sorted(manual)} with non-trivial "
            f"auto axes {bad} needs jax.shard_map (jax>=0.5); this jax only "
            "supports fully-manual meshes here")
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               auto=auto, check_rep=False)
