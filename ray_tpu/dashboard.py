"""Dashboard: HTTP endpoints for cluster state + Prometheus metrics + web UI.

Capability parity: reference python/ray/dashboard/ (DashboardHead head.py:48 +
per-node agent; modules: state, metrics, reporter; React client). The UI here
is a single dependency-free page (vanilla JS polling the JSON endpoints) rather
than the reference's React app — something a human can actually look at without
a node toolchain in the image.

Endpoints:
    GET /                   human-facing dashboard (auto-refreshing tables,
                            worker-log browser, task timeline lanes)
    GET /api/summary        cluster summary
    GET /api/nodes|workers|actors|tasks|objects|placement_groups
    GET /api/logs           remote-worker log index
    GET /api/log?worker_id=&tail=  one worker's captured lines
    GET /api/timeline       chrome-trace JSON (finished tasks)
    GET /api/telemetry_timeline  merged cross-worker chrome trace: hot-path
                            telemetry spans (transfers/collectives/serve/
                            train) + tasks, clock-aligned
    GET /api/status         live load summary (transfer GB/s, collective
                            ops/aborts, serve TTFT + queue depth, train MFU)
    GET /api/history        metrics-history time series (windowed rates and
                            frame-over-frame quantiles; ?window=seconds)
    GET /api/slo            SLO engine status (burn rates, ok|burning)
    GET /api/trace?trace_id=  request-scoped critical path (span tree +
                            queue/prefill/decode/transfer/other attribution)
    GET /metrics            Prometheus exposition text
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
  body { font-family: ui-monospace, monospace; margin: 1.5rem; background: #101418;
         color: #d7dde3; }
  h1 { font-size: 1.1rem; } h2 { font-size: .95rem; margin: 1.2rem 0 .4rem; }
  table { border-collapse: collapse; width: 100%; font-size: .8rem; }
  th, td { border: 1px solid #2a3340; padding: .25rem .5rem; text-align: left; }
  th { background: #1a2129; position: sticky; top: 0; }
  tr:nth-child(even) { background: #161c23; }
  .pill { padding: 0 .45rem; border-radius: .6rem; background: #1f5c2d; }
  .pill.bad { background: #6b2020; }
  #summary { display: flex; gap: 1.5rem; flex-wrap: wrap; margin: .6rem 0 1rem; }
  .stat { background: #1a2129; padding: .5rem .9rem; border-radius: .4rem; }
  .stat b { display: block; font-size: 1.2rem; }
  small { color: #7b8794; }
  pre { background: #0b0e12; padding: .5rem; font-size: .75rem; overflow-x: auto; }
  details summary { cursor: pointer; font-size: .85rem; margin: .2rem 0; }
  .lane { position: relative; height: 14px; margin: 2px 0 2px 0;
          background: #161c23; }
  .lane small { position: absolute; left: 2px; z-index: 1; }
  .bar { position: absolute; top: 2px; height: 10px; background: #2f81f7;
         border-radius: 2px; }
</style></head>
<body>
<h1>ray_tpu dashboard <small id="ts"></small></h1>
<div id="summary"></div>
<div id="tables"></div>
<script>
const TABLES = ["nodes", "workers", "actors", "tasks", "placement_groups"];
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({"&": "&amp;", "<": "&lt;",
    ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
}
function cell(v) {
  if (v === true) return '<span class="pill">yes</span>';
  if (v === false) return '<span class="pill bad">no</span>';
  if (v !== null && typeof v === "object") return esc(JSON.stringify(v));
  return v === null || v === undefined ? "" : esc(v);
}
function table(rows) {
  if (!rows.length) return "<small>(empty)</small>";
  const cols = Object.keys(rows[0]);
  return "<table><tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>" +
    rows.slice(0, 200).map(r =>
      "<tr>" + cols.map(c => `<td>${cell(r[c])}</td>`).join("") + "</tr>").join("") +
    "</table>" + (rows.length > 200 ? `<small>showing 200 of ${rows.length}</small>` : "");
}
async function logsSection() {
  const idx = await (await fetch("/api/logs")).json();
  if (!idx.length) return "<h2>worker logs</h2><small>(none captured)</small>";
  let html = `<h2>worker logs (${idx.length} workers)</h2>`;
  for (const e of idx.slice(0, 20)) {
    const lines = await (await fetch(
      `/api/log?worker_id=${e.worker_id}&tail=30`)).json();
    html += `<details><summary>${esc(e.worker_id.slice(0, 12))} ` +
      `on ${esc(e.node_id.slice(0, 12))} (${e.num_lines} lines)</summary>` +
      `<pre>${lines.map(esc).join("\\n")}</pre></details>`;
  }
  return html;
}
function timelineSection(events) {
  // chrome-trace "X" events -> one lane per worker, bars scaled to the span
  const xs = events.filter(e => e.ph === "X" && e.dur > 0);
  if (!xs.length) return "<h2>timeline</h2><small>(no finished tasks)</small>";
  const t0 = Math.min(...xs.map(e => e.ts)), t1 = Math.max(...xs.map(e => e.ts + e.dur));
  const span = Math.max(t1 - t0, 1);
  const lanes = {};
  for (const e of xs.slice(-300)) (lanes[e.tid] = lanes[e.tid] || []).push(e);
  let html = `<h2>timeline <small>(${xs.length} tasks, ` +
    `${(span / 1e6).toFixed(2)}s span)</small></h2>`;
  for (const [tid, evs] of Object.entries(lanes)) {
    html += `<div class="lane"><small>${esc(String(tid).slice(0, 12))}</small>` +
      evs.map(e => `<span class="bar" title="${esc(e.name)} ` +
        `${(e.dur / 1e3).toFixed(1)}ms" style="left:${(e.ts - t0) / span * 100}%;` +
        `width:${Math.max(e.dur / span * 100, .3)}%"></span>`).join("") + "</div>";
  }
  return html;
}
async function refresh() {
  try {
    const s = await (await fetch("/api/summary")).json();
    document.getElementById("summary").innerHTML = Object.entries(s)
      .filter(([k, v]) => typeof v !== "object")
      .map(([k, v]) => `<div class="stat"><b>${cell(v)}</b>${esc(k)}</div>`).join("");
    const parts = [];
    for (const t of TABLES) {
      const rows = await (await fetch("/api/" + t)).json();
      parts.push(`<h2>${t} (${rows.length})</h2>` + table(rows));
    }
    parts.push(await logsSection());
    const tl = await (await fetch("/api/timeline")).json();
    parts.push(timelineSection(tl));
    document.getElementById("tables").innerHTML = parts.join("");
    document.getElementById("ts").textContent = new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("ts").textContent = "refresh failed: " + e;
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body></html>
"""


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: Optional[int] = None):
        from ray_tpu.config import CONFIG

        self.host = host
        self.port = port if port is not None else CONFIG.dashboard_port
        # resolve TLS BEFORE the serving thread starts: a missing cert must
        # fail fast with the tls-init hint, not a 10s 'failed to start' hang
        self._ssl_ctx = None
        if CONFIG.serve_ingress_tls:
            # same server-side-TLS posture as the serve HTTP/gRPC ingress:
            # browsers/scrapers verify against ca.crt, no client cert needed
            from ray_tpu.core.tls_utils import ingress_ssl_context

            self._ssl_ctx = ingress_ssl_context()
        self._ready = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="rt-dashboard")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError("dashboard failed to start")

    def _serve(self) -> None:
        from aiohttp import web

        from ray_tpu.util import state as st

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        tables = {
            "nodes": st.list_nodes,
            "workers": st.list_workers,
            "actors": st.list_actors,
            "tasks": st.list_tasks,
            "objects": st.list_objects,
            "placement_groups": st.list_placement_groups,
        }

        async def api(request: "web.Request") -> "web.Response":
            name = request.match_info["name"]
            if name == "summary":
                return web.json_response(st.summarize_cluster())
            if name == "status":
                # cluster load summary: transfer GB/s, collective ops/aborts,
                # serve TTFT + queue depths, train MFU (util/state.cluster_status)
                return web.json_response(st.cluster_status())
            if name == "history":
                # retained metrics history as JSON-safe per-frame time series
                # (windowed rates + frame-over-frame quantiles; sparkline feed)
                try:
                    window = float(request.query.get("window", "300"))
                except ValueError:
                    window = float("nan")
                if not window > 0:  # rejects NaN, 0, and negatives alike
                    return web.Response(
                        status=400, text="window must be a positive number "
                        "of seconds")
                return web.json_response(st.history_series(window_s=window))
            if name == "slo":
                # SLO engine status: burn rates + ok|burning per objective
                return web.json_response(st.slo_status())
            if name == "trace":
                # request-scoped critical path: /api/trace?trace_id=...
                tid = request.query.get("trace_id", "")
                if not tid:
                    return web.Response(status=400, text="trace_id required")
                return web.json_response(st.request_trace(tid))
            if name == "timeline":
                return web.json_response(st.timeline())
            if name == "telemetry_timeline":
                # merged cross-worker chrome trace (telemetry spans + tasks)
                return web.json_response(st.telemetry_timeline())
            if name == "logs":
                return web.json_response(st.list_logs())
            if name == "log":
                wid = request.query.get("worker_id", "")
                tail = int(request.query.get("tail", "100"))
                return web.json_response(st.get_log(wid, tail=tail))
            if name == "profile":
                # sampling flamegraph (py-spy-record analogue): blocks for
                # `duration` seconds, returns a speedscope document
                duration = min(30.0, float(request.query.get("duration", "2")))
                hz = min(500.0, float(request.query.get("hz", "100")))
                loop = asyncio.get_running_loop()
                profs = await loop.run_in_executor(
                    None, lambda: st.profile_workers(duration_s=duration, hz=hz))
                if request.query.get("format") == "collapsed":
                    return web.json_response(profs)
                return web.json_response(st.profile_to_speedscope(profs))
            fn = tables.get(name)
            if fn is None:
                return web.Response(status=404, text=f"unknown table {name}")
            return web.json_response(fn())

        async def metrics(request: "web.Request") -> "web.Response":
            return web.Response(text=st.prometheus_metrics(),
                                content_type="text/plain")

        async def index(request: "web.Request") -> "web.Response":
            return web.Response(text=_INDEX_HTML, content_type="text/html")

        app = web.Application()
        app.router.add_get("/", index)
        app.router.add_get("/api/{name}", api)
        app.router.add_get("/metrics", metrics)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port,
                           ssl_context=self._ssl_ctx)
        loop.run_until_complete(site.start())
        self._ready.set()
        loop.run_forever()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
