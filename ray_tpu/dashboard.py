"""Dashboard-lite: HTTP endpoints for cluster state + Prometheus metrics.

Capability parity: reference python/ray/dashboard/ (DashboardHead head.py:48 +
per-node agent; modules: state, metrics, reporter). The React UI is out of scope;
the data plane — JSON state endpoints and a Prometheus scrape target — is here,
served from the driver process (our GCS-equivalent lives in-process).

Endpoints:
    GET /api/summary        cluster summary
    GET /api/nodes|workers|actors|tasks|objects|placement_groups
    GET /api/timeline       chrome-trace JSON
    GET /metrics            Prometheus exposition text
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._ready = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="rt-dashboard")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError("dashboard failed to start")

    def _serve(self) -> None:
        from aiohttp import web

        from ray_tpu.util import state as st

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        tables = {
            "nodes": st.list_nodes,
            "workers": st.list_workers,
            "actors": st.list_actors,
            "tasks": st.list_tasks,
            "objects": st.list_objects,
            "placement_groups": st.list_placement_groups,
        }

        async def api(request: "web.Request") -> "web.Response":
            name = request.match_info["name"]
            if name == "summary":
                return web.json_response(st.summarize_cluster())
            if name == "timeline":
                return web.json_response(st.timeline())
            fn = tables.get(name)
            if fn is None:
                return web.Response(status=404, text=f"unknown table {name}")
            return web.json_response(fn())

        async def metrics(request: "web.Request") -> "web.Response":
            return web.Response(text=st.prometheus_metrics(),
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/api/{name}", api)
        app.router.add_get("/metrics", metrics)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        self._ready.set()
        loop.run_forever()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
