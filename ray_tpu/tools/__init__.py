"""Developer tooling that ships with the package (static analysis, doc
generation). Nothing here is imported by the runtime."""
