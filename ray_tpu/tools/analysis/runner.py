"""graftlint runner: walk the tree, run every check, apply the allowlist.

Entry points:

- ``run_lint(root, ...)`` — programmatic (tests/test_lint.py runs it over
  ``ray_tpu/`` in tier-1);
- ``main(argv)`` — the ``ray-tpu lint`` CLI (also
  ``python -m ray_tpu.tools.analysis``): exit 0 = clean, 1 = violations,
  2 = a file failed to parse. ``--write-docs`` regenerates the README knob
  tables from the registry instead of failing on drift.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional, Sequence

from .base import Project, SourceFile, Violation
from .checks import ALL_CHECKS, CHECK_NAMES
from .checks.knob_registry import load_knobs

EXCLUDE_PARTS = ("__pycache__", "_pb2")


def collect_files(root: str, subdirs: Sequence[str]) -> List[SourceFile]:
    out: List[SourceFile] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if not os.path.exists(base):
            # a typo'd path must not become a lint gate that "passes" over
            # zero files
            raise SystemExit(f"graftlint: no such path: {base}")
        if os.path.isfile(base) and base.endswith(".py"):
            out.append(SourceFile(root, os.path.relpath(base, root)
                                  .replace(os.sep, "/")))
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root) \
                    .replace(os.sep, "/")
                if any(part in rel for part in EXCLUDE_PARTS):
                    continue
                out.append(SourceFile(root, rel))
    return out


@dataclasses.dataclass
class LintResult:
    violations: List[Violation]  # unallowlisted — these fail the run
    allowed: List[Violation]  # suppressed by a reasoned inline allow
    problems: List[Violation]  # allowlist meta-problems (no reason / stale)
    files: int

    @property
    def failures(self) -> List[Violation]:
        return self.violations + self.problems


def run_lint(root: str, subdirs: Sequence[str] = ("ray_tpu",),
             checks=None, readme: Optional[str] = "README.md") -> LintResult:
    checks = list(ALL_CHECKS) if checks is None else list(checks)
    for c in checks:
        if c.name == "knob-registry":
            c.readme = readme
    files = collect_files(root, subdirs)
    project = Project(root, files)
    violations: List[Violation] = []
    allowed: List[Violation] = []
    raw: List[Violation] = []
    for check in checks:
        for f in files:
            if check.skip(f.path):
                continue
            raw.extend(check.run(f, project))
        raw.extend(check.run_project(project))
    problems: List[Violation] = []
    for v in raw:
        f = project.by_path.get(v.path)
        allow = f.allow_for(v.check, v.line) if f is not None else None
        if allow is None:
            violations.append(v)
            continue
        allow.used = True
        if not allow.reason:
            problems.append(Violation(
                "allowlist", v.path, allow.line,
                f"allow[{v.check}] has no reason — every suppression must "
                "say why the invariant is intentionally bent"))
        allowed.append(v)
    for f in files:
        for allow in f.allows:
            unknown = [c for c in allow.checks
                       if c not in CHECK_NAMES and c != "allowlist"]
            if unknown:
                problems.append(Violation(
                    "allowlist", f.path, allow.line,
                    f"allow[{', '.join(unknown)}] names no known check "
                    f"(known: {', '.join(CHECK_NAMES)})"))
            elif not allow.used:
                problems.append(Violation(
                    "allowlist", f.path, allow.line,
                    f"stale allow[{', '.join(allow.checks)}]: no violation "
                    "fires here anymore — delete the comment"))
    key = lambda v: (v.path, v.line, v.check)
    return LintResult(sorted(violations, key=key), sorted(allowed, key=key),
                      sorted(problems, key=key), len(files))


def write_docs(root: str, readme: str = "README.md") -> bool:
    """Regenerate the README knob tables in place; True if anything changed."""
    knobs = load_knobs(os.path.join(root, "ray_tpu"))
    path = os.path.join(root, readme)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    new = knobs.generate_readme(text)
    if new != text:
        with open(path, "w", encoding="utf-8") as f:
            f.write(new)
        return True
    return False


def find_root(start: Optional[str] = None) -> str:
    """Nearest ancestor holding the ray_tpu package (repo root)."""
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isfile(os.path.join(cur, "ray_tpu", "__init__.py")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            raise SystemExit("graftlint: no ray_tpu package found above "
                             f"{start or os.getcwd()}")
        cur = parent


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ray-tpu lint",
        description="project-invariant static analysis (graftlint)")
    p.add_argument("paths", nargs="*", default=None,
                   help="subdirs/files to lint, relative to the repo root "
                        "(default: ray_tpu)")
    p.add_argument("--root", default=None,
                   help="repo root (default: walk up to the ray_tpu package)")
    p.add_argument("--write-docs", action="store_true",
                   help="regenerate the README knob tables from "
                        "ray_tpu/knobs.py and exit")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument("--show-allowed", action="store_true",
                   help="also list allowlisted (suppressed) violations")
    args = p.parse_args(argv)

    root = args.root or find_root()
    if args.write_docs:
        changed = write_docs(root)
        print("README knob tables " +
              ("rewritten from ray_tpu/knobs.py" if changed else "already current"))
        return 0

    subdirs = args.paths or ["ray_tpu"]
    try:
        res = run_lint(root, subdirs)
    except SyntaxError as e:
        print(f"graftlint: parse failure: {e}", file=sys.stderr)
        return 2
    if res.files == 0:
        print("graftlint: the given paths contain no python files",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "files": res.files,
            "violations": [dataclasses.asdict(v) for v in res.violations],
            "problems": [dataclasses.asdict(v) for v in res.problems],
            "allowed": [dataclasses.asdict(v) for v in res.allowed],
        }, indent=2))
        return 1 if res.failures else 0

    for v in res.failures:
        print(v.render())
    if args.show_allowed:
        for v in res.allowed:
            print(f"(allowed) {v.render()}")
    ok = not res.failures
    print(f"graftlint: {res.files} files, "
          f"{len(res.violations)} violation(s), "
          f"{len(res.problems)} allowlist problem(s), "
          f"{len(res.allowed)} allowlisted" + (" — ok" if ok else ""))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
