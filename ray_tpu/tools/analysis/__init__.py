"""graftlint — AST-based project-invariant analyzer for ray_tpu.

Six invariants this codebase keeps by machine instead of by review:

1. swallowed-exception — broad excepts must re-raise, log, or use the error
2. host-sync-in-hot-path — no device->host syncs inside @hot_path functions
3. blocking-control-path — no blocking calls on control-plane code
4. knob-registry — every RAY_TPU_* knob registered in ray_tpu/knobs.py,
   README tables generated from the registry
5. thread-hygiene / lock-hygiene — named+explicit-daemon threads; no mixed
   locked/unlocked writes in thread-spawning classes
6. no-print — runtime code logs via LOGGER

Run: ``ray-tpu lint`` (or ``python -m ray_tpu.tools.analysis``).
Suppress: ``# graftlint: allow[check-name] reason`` (reason required).
"""
from __future__ import annotations

from .base import Allow, Check, Project, SourceFile, Violation  # noqa: F401
from .checks import ALL_CHECKS, CHECK_NAMES  # noqa: F401
from .runner import LintResult, main, run_lint, write_docs  # noqa: F401
