"""graftlint core: project model, allowlist parsing, check protocol.

The analyzer is pure-AST and import-free with respect to the analyzed tree —
it never executes or imports runtime modules (and therefore never pulls in
jax), which is what keeps the tier-1 lint test cheap. The one deliberate
exception is `ray_tpu/knobs.py`, the stdlib-only knob registry, which the
knob-registry check loads as a detached module from its file path (see
checks/knob_registry.py).

Escape hatch: a violation is suppressed by an inline COMMENT (string
literals never count — comments are recovered via tokenize) on the same line
or the line directly above it, `# graftlint: allow[<check>] <reason>`. The
reason is mandatory (an allow without one is itself a violation), and an
allow that no check fires against is reported as stale — nothing gets
suppressed silently.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Tuple

ALLOW_RE = re.compile(
    r"#\s*graftlint:\s*allow\[(?P<checks>[a-z0-9_,\- ]+)\]\s*(?P<reason>.*)$")


@dataclasses.dataclass
class Violation:
    check: str
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


@dataclasses.dataclass
class Allow:
    path: str
    line: int
    checks: Tuple[str, ...]
    reason: str
    used: bool = False


class SourceFile:
    """One analyzed file: text, parsed AST, and its allowlist entries."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path  # repo-relative, '/'-separated
        with open(os.path.join(root, path), encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self.allows: List[Allow] = []
        self._allow_by_line: Dict[int, List[Allow]] = {}
        for idx, comment in self._comments():
            m = ALLOW_RE.search(comment)
            if not m:
                continue
            checks = tuple(c.strip() for c in m.group("checks").split(",")
                           if c.strip())
            allow = Allow(self.path, idx, checks, m.group("reason").strip())
            self.allows.append(allow)
            self._allow_by_line.setdefault(idx, []).append(allow)

    def _comments(self) -> Iterable[Tuple[int, str]]:
        """(line, text) for every real comment token — a '#' inside a string
        literal must never read as an allowlist entry."""
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except tokenize.TokenError:
            return

    def allow_for(self, check: str, line: int) -> Optional[Allow]:
        """The allow entry covering `check` at `line`: same line or the line
        directly above (a standalone comment line)."""
        for lineno in (line, line - 1):
            for allow in self._allow_by_line.get(lineno, ()):
                if check in allow.checks:
                    return allow
        return None


class Project:
    """The analyzed file set plus lazily-built cross-file aggregates."""

    def __init__(self, root: str, files: List[SourceFile]):
        self.root = root
        self.files = files
        self.by_path = {f.path: f for f in files}
        self._env_literals: Optional[Dict[str, List[Tuple[str, int]]]] = None
        self._attr_names: Optional[set] = None
        self._str_constants: Optional[set] = None

    ENV_RE = re.compile(r"^RAY_TPU_[A-Z0-9]+(?:_[A-Z0-9]+)*$")

    def _build_aggregates(self) -> None:
        env: Dict[str, List[Tuple[str, int]]] = {}
        attrs: set = set()
        strs: set = set()
        for f in self.files:
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    strs.add(node.value)
                    if self.ENV_RE.match(node.value):
                        env.setdefault(node.value, []).append((f.path, node.lineno))
                elif isinstance(node, ast.Attribute):
                    attrs.add(node.attr)
        self._env_literals, self._attr_names, self._str_constants = env, attrs, strs

    @property
    def env_literals(self) -> Dict[str, List[Tuple[str, int]]]:
        """Every exact RAY_TPU_* string literal -> [(path, line), ...]."""
        if self._env_literals is None:
            self._build_aggregates()
        return self._env_literals

    @property
    def attr_names(self) -> set:
        if self._attr_names is None:
            self._build_aggregates()
        return self._attr_names

    @property
    def str_constants(self) -> set:
        if self._str_constants is None:
            self._build_aggregates()
        return self._str_constants


class Check:
    """Base check: subclasses set `name`, implement run()."""

    name: str = ""

    def skip(self, path: str) -> bool:
        return False

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        raise NotImplementedError

    def run_project(self, project: Project) -> Iterable[Violation]:
        """Project-level pass (drift checks); default: nothing."""
        return ()


def call_name(node: ast.expr) -> str:
    """Dotted name of a call target: `a.b.c(...)` -> 'a.b.c', best effort."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def decorator_names(node: ast.AST) -> List[str]:
    out = []
    for dec in getattr(node, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        out.append(call_name(target))
    return out
