"""``python -m ray_tpu.tools.analysis`` == ``ray-tpu lint``."""
import sys

from .runner import main

sys.exit(main())
