"""thread-hygiene + lock-hygiene: thread construction and cross-thread state.

thread-hygiene — every ``threading.Thread(...)`` must pass BOTH ``daemon=``
and ``name=``. Unnamed threads make `ray-tpu list stacks` and py-spy dumps
unreadable; non-explicit daemonness is how shutdown hangs are born (a
forgotten non-daemon thread pins the process; an accidental daemon thread
gets killed mid-write).

lock-hygiene — a heuristic race detector for the PR 8 stale-snapshot /
PR 11 undeclared-router-field class of bug: in any class that spawns
threads, an instance attribute assigned BOTH inside ``with self.<lock>:``
blocks and outside them (excluding ``__init__``/``__new__`` construction and
``*_locked`` methods, whose callers hold the lock by convention) is flagged
at each unlocked write site. Either take the lock, move the write into
``__init__``, or allow it with the reason the unlocked write is safe
(immutable publish, single-writer field, ...).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..base import Check, Project, SourceFile, Violation, call_name

LOCKISH = ("lock", "_mu", "mutex", "cond")


def _is_thread_ctor(node: ast.Call) -> bool:
    name = call_name(node.func)
    return name == "threading.Thread" or name.endswith(".Thread") \
        or name == "Thread"


class ThreadHygiene(Check):
    name = "thread-hygiene"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            kwargs = {kw.arg for kw in node.keywords}
            missing = [k for k in ("daemon", "name") if k not in kwargs]
            if missing:
                yield Violation(
                    self.name, f.path, node.lineno,
                    f"threading.Thread without {'/'.join(missing)}= — name "
                    "threads for stack listings and make daemonness an "
                    "explicit decision")


def _lock_guarded(with_node: ast.With) -> bool:
    for item in with_node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
        name = call_name(expr).lower()
        if any(tok in name for tok in ("start", "init")):
            # a start/init gate orders one-time construction; it does not
            # declare the attributes written inside it lock-protected in
            # steady state (the llm engine's _start_lock pattern)
            continue
        if any(tok in name for tok in LOCKISH):
            return True
    return False


def _self_writes(method: ast.AST) -> Iterable[Tuple[str, int, bool]]:
    """(attr, line, locked) for every `self.X = ...` in the method body."""

    def visit(node: ast.AST, locked: bool) -> Iterable[Tuple[str, int, bool]]:
        if isinstance(node, ast.With) and _lock_guarded(node):
            locked = True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not method:
            return  # nested defs run elsewhere
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for sub in ast.walk(t):
                    if (isinstance(sub, ast.Attribute)
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id == "self"):
                        yield sub.attr, sub.lineno, locked
        for child in ast.iter_child_nodes(node):
            yield from visit(child, locked)

    yield from visit(method, False)


class LockHygiene(Check):
    name = "lock-hygiene"

    def run(self, f: SourceFile, project: Project) -> Iterable[Violation]:
        for cls in ast.walk(f.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            spawns = any(isinstance(n, ast.Call) and _is_thread_ctor(n)
                         for n in ast.walk(cls))
            if not spawns:
                continue
            locked_attrs: Set[str] = set()
            unlocked: Dict[str, List[int]] = {}
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name in ("__init__", "__new__") \
                        or item.name.endswith("_locked"):
                    continue
                for attr, line, locked in _self_writes(item):
                    if locked:
                        locked_attrs.add(attr)
                    else:
                        unlocked.setdefault(attr, []).append(line)
            for attr in sorted(locked_attrs & set(unlocked)):
                for line in unlocked[attr]:
                    yield Violation(
                        self.name, f.path, line,
                        f"self.{attr} is written under a lock elsewhere in "
                        f"{cls.name} but assigned here without it — take "
                        "the lock or justify the lock-free write")
